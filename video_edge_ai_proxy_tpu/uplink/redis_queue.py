"""Durable Redis-backed annotation queue (VERDICT round-2 missing #2).

The reference queues annotations in Redis via rmq
(``server/grpcapi/grpc_api.go:69-75``: connection "annotationService",
queue "annotationqueue"; ``server/main.go:59-64`` wires the consumer), so
a server restart mid-outage keeps every unacked event. The in-memory
``AnnotationQueue`` loses up to ``unacked_limit`` events on a crash; this
subclass stores the same pipeline in Redis — selected automatically when
``bus.backend: redis`` (the deployment that HAS a Redis to be durable in).

Wire layout is rmq's own (github.com/adjust/rmq v4), so a reference
server's rmq consumer pointed at the same Redis can drain events this
framework publishes and vice versa:

- ready:    ``rmq::queue::[annotationqueue]::ready``        (LPUSH)
- unacked:  ``rmq::connection::<conn>::queue::[annotationqueue]::unacked``
- rejected: ``rmq::queue::[annotationqueue]::rejected``

A delivery moves ready → unacked atomically (RPOPLPUSH), so there is no
instant at which a crash loses it. Recovery is rmq's stale-connection
cleaner, heartbeat-gated: each instance maintains
``rmq::connection::<name>::heartbeat`` (timestamp value ≈ rmq's TTL'd
key); at startup and periodically, unacked lists of connections whose
heartbeat is stale or absent sweep back to ready — a LIVE peer's
mid-delivery batch is never stolen into duplicate uploads. Our own
connection name sweeps unconditionally at startup (we are its new
incarnation; give each instance of a multi-consumer fleet a distinct
``connection`` name).

Counter semantics note: ``published``/``acked``/``dropped`` count THIS
process's traffic (Prometheus counters must be monotonic per process);
``depth()`` is read from Redis and covers everything, including events
inherited from a previous incarnation.
"""

from __future__ import annotations

import time
from typing import Optional

from ..bus.resp import RespClient, RespError
from ..utils.logging import get_logger
from .queue import AnnotationQueue, BatchHandler

log = get_logger("uplink.redis_queue")


class RedisAnnotationQueue(AnnotationQueue):
    def __init__(
        self,
        handler: Optional[BatchHandler] = None,
        *,
        addr: str = "127.0.0.1:6379",
        password: str = "",
        db: int = 0,
        queue_name: str = "annotationqueue",
        connection: str = "vepTpu",
        timeout_s: float = 5.0,
        **kwargs,
    ):
        super().__init__(handler, **kwargs)
        handshake = []
        if password:
            handshake.append(("AUTH", password))
        if db:
            handshake.append(("SELECT", str(db)))
        self._client = RespClient.from_addr(
            addr, timeout_s, handshake=tuple(handshake)
        )
        self._qname = queue_name
        self._conn_name = connection
        self._ready = f"rmq::queue::[{queue_name}]::ready"
        self._rejected_key = f"rmq::queue::[{queue_name}]::rejected"
        self._unacked = (
            f"rmq::connection::{connection}::queue::[{queue_name}]::unacked"
        )
        self._hb_key = f"rmq::connection::{connection}::heartbeat"
        self._other_cached, self._other_at = 0, float("-inf")
        self._last_beat = float("-inf")
        self._last_sweep = time.monotonic()
        self._beat()   # claim our connection before sweeping others
        self.resumed = self._sweep_orphans()
        if self.resumed:
            log.info(
                "recovered %d unacked annotation(s) from a previous run",
                self.resumed,
            )

    # -- crash recovery --

    # A connection whose heartbeat timestamp is older than this (or whose
    # heartbeat key is gone) is considered dead and its unacked deliveries
    # recoverable. Must comfortably exceed the consumer cycle (~300 ms).
    _HEARTBEAT_STALE_S = 30.0

    def _beat(self) -> None:
        """Refresh this connection's liveness marker (~2 s throttle).
        rmq uses a TTL'd heartbeat key; a TIMESTAMP value gives the same
        observable contract (stale/absent = dead) without requiring key
        expiry from the server. Live peers check it before sweeping our
        unacked list (and we check theirs)."""
        now = time.monotonic()
        if now - self._last_beat < 2.0:
            return
        self._last_beat = now
        try:
            self._client.command(
                "SET", self._hb_key, str(int(time.time() * 1000))
            )
        except (RespError, IOError) as exc:
            log.warning("heartbeat write failed: %s", exc)

    def _connection_alive(self, conn: str) -> bool:
        try:
            raw = self._client.command(
                "GET", f"rmq::connection::{conn}::heartbeat"
            )
        except (RespError, IOError):
            return True    # can't tell: never steal a maybe-live batch
        if raw is None:
            return False   # no heartbeat: dead (or pre-heartbeat rmq gone)
        try:
            ts = int(raw)
        except ValueError:
            # rmq's own heartbeat value ("1" with TTL): existence = alive.
            return True
        return time.time() * 1000 - ts < self._HEARTBEAT_STALE_S * 1000

    def _sweep_orphans(self) -> int:
        """Unacked deliveries of DEAD connections back to ready (rmq
        cleaner parity — rmq likewise gates on connection heartbeats, so
        a live peer's mid-POST batch is never stolen into duplicate
        delivery). Our own connection name is swept unconditionally: we
        are its new incarnation (run multi-instance fleets with distinct
        ``connection`` names). Re-delivering a dead connection's events
        is correct because the uplink POST is idempotent on the cloud
        side (same event payload)."""
        n = 0
        try:
            cursor = b"0"
            keys = set()
            # NB: rmq's literal "[queue]" brackets are glob char-classes
            # to MATCH — scan the connection prefix and filter exactly
            # in Python instead of fighting glob escaping.
            suffix = f"::queue::[{self._qname}]::unacked"
            while True:
                reply = self._client.command(
                    "SCAN", cursor, "MATCH", "rmq::connection::*::unacked",
                    "COUNT", "1000",
                )
                cursor, page = reply
                keys.update(
                    k.decode() for k in page if k.decode().endswith(suffix)
                )
                if cursor in (b"0", 0, "0"):
                    break
            for key in keys:
                conn = key.split("::")[2]   # rmq::connection::<name>::…
                if conn != self._conn_name and self._connection_alive(conn):
                    continue
                # `is not None`: RESP nil ends the list; an EMPTY payload
                # (b"", falsy) is a legal queued event and must not halt
                # the sweep with entries still stranded.
                # unsafe_ok: a resync retry can re-run one RPOPLPUSH; the
                # queue's documented contract is duplicates over loss.
                while self._client.command(
                    "RPOPLPUSH", key, self._ready, unsafe_ok=True
                ) is not None:
                    n += 1
        except (RespError, IOError) as exc:
            log.warning("unacked sweep failed (continuing): %s", exc)
        return n

    # -- producer side --

    # unacked+rejected depth is re-read at most this often on the publish
    # path (the consumer cycles every ~300 ms anyway); keeps publish at
    # ONE Redis round trip steady-state instead of four.
    _OTHER_DEPTH_TTL_S = 1.0

    def publish(self, payload: bytes) -> bool:
        try:
            # LPUSH first and use its reply (the ready length) for the
            # limit check — no pre-flight LLENs on the hot path.
            # unsafe_ok on the LPUSH/LPOP pair: a resync retry can
            # duplicate one queued event — tolerated (duplicates over
            # loss; the cloud POST is idempotent on payload).
            ready_len = int(
                self._client.command("LPUSH", self._ready, payload,
                                     unsafe_ok=True)
            )
            if ready_len + self._other_depth() > self._unacked_limit:
                # Over limit: shed from the head — the event just pushed
                # (or a concurrent publisher's, equally being shed).
                self._client.command("LPOP", self._ready, unsafe_ok=True)
                self.dropped += 1
                if self.dropped % 100 == 1:
                    log.warning(
                        "annotation queue full (%d unacked); dropping",
                        self._unacked_limit,
                    )
                return False
            self.published += 1
            return True
        except (RespError, IOError) as exc:
            self.dropped += 1
            log.warning("annotation publish to redis failed: %s", exc)
            return False

    def _other_depth(self) -> int:
        """Cached LLEN(unacked) + LLEN(rejected); ready is always read
        fresh (it is the fast-moving list and LPUSH returns it free)."""
        now = time.monotonic()
        if now - self._other_at > self._OTHER_DEPTH_TTL_S:
            total = 0
            for key in (self._unacked, self._rejected_key):
                total += int(self._client.command("LLEN", key) or 0)
            self._other_cached, self._other_at = total, now
        return self._other_cached

    def depth(self) -> int:
        total = 0
        for key in (self._ready, self._unacked, self._rejected_key):
            out = self._client.command("LLEN", key)
            total += int(out or 0)
        return total

    # -- consumer side --

    def drain_once(self) -> int:
        self._beat()
        batch: list[bytes] = []
        try:
            # Pipelined pop: max_batch RPOPLPUSHes in ONE round trip
            # (command-by-command this is 299 sequential RTTs per batch —
            # slower than the 299/300 ms drain budget on a ~1 ms link).
            # Extra commands past the queue tail return nil, harmlessly.
            # unsafe_ok: a resync retry re-pops into unacked — events land
            # in unacked twice at worst (double delivery, never loss).
            replies = self._client.pipeline([
                ("RPOPLPUSH", self._ready, self._unacked)
            ] * self._max_batch, unsafe_ok=True)
            for v in replies:
                if isinstance(v, (RespError, type(None))):
                    break
                batch.append(v)
        except (RespError, IOError) as exc:
            log.warning("annotation drain pop failed: %s", exc)
        if not batch:
            return 0
        assert self._handler is not None
        try:
            ok = self._handler(batch)
        except Exception as exc:
            log.error("annotation batch handler raised: %s", exc)
            ok = False
        try:
            if ok:
                # unsafe_ok (here and on reject below): double-applied
                # bookkeeping at worst re-delivers, never loses.
                self._client.pipeline([
                    ("LREM", self._unacked, "-1", v) for v in batch
                ], unsafe_ok=True)
                self.acked += len(batch)
                return len(batch)
            self.rejected_batches += 1
            # LPUSH before LREM per event: a crash between the two leaves
            # a DUPLICATE (in rejected + unacked, reconciled to double
            # delivery by the startup sweep — the uplink is idempotent),
            # never a loss. Pipelining preserves this server-side order.
            cmds = []
            for v in batch:
                cmds.append(("LPUSH", self._rejected_key, v))
                cmds.append(("LREM", self._unacked, "-1", v))
            self._client.pipeline(cmds, unsafe_ok=True)
        except (RespError, IOError) as exc:
            # Whatever we couldn't move stays in unacked; the startup
            # sweep of the next incarnation returns it to ready.
            log.warning("annotation ack/reject bookkeeping failed: %s", exc)
        return 0

    def requeue_rejected(self) -> None:
        try:
            # unsafe_ok: duplicates over loss (see drain_once).
            while self._client.command(
                "RPOPLPUSH", self._rejected_key, self._ready, unsafe_ok=True
            ) is not None:
                pass
        except (RespError, IOError) as exc:
            log.warning("annotation requeue failed: %s", exc)
        # Periodic cleaner leg (rmq parity): a connection that dies AFTER
        # our boot becomes sweepable once its heartbeat goes stale.
        now = time.monotonic()
        if now - self._last_sweep > self._HEARTBEAT_STALE_S:
            self._last_sweep = now
            n = self._sweep_orphans()
            if n:
                log.info("cleaner recovered %d unacked annotation(s)", n)

    def stop(self) -> None:
        super().stop()
        try:
            # Clean shutdown: drop the liveness marker so a successor (or
            # a peer's cleaner) can recover anything left immediately
            # instead of waiting out the staleness window.
            self._client.command("DEL", self._hb_key)
        except Exception:
            pass
        try:
            self._client.close()
        except Exception:
            pass
