"""Durable Redis-backed annotation queue (VERDICT round-2 missing #2).

The reference queues annotations in Redis via rmq
(``server/grpcapi/grpc_api.go:69-75``: connection "annotationService",
queue "annotationqueue"; ``server/main.go:59-64`` wires the consumer), so
a server restart mid-outage keeps every unacked event. The in-memory
``AnnotationQueue`` loses up to ``unacked_limit`` events on a crash; this
subclass stores the same pipeline in Redis — selected automatically when
``bus.backend: redis`` (the deployment that HAS a Redis to be durable in).

Wire layout is rmq's own (github.com/adjust/rmq v4), so a reference
server's rmq consumer pointed at the same Redis can drain events this
framework publishes and vice versa:

- ready:    ``rmq::queue::[annotationqueue]::ready``        (LPUSH)
- unacked:  ``rmq::connection::<conn>::queue::[annotationqueue]::unacked``
- rejected: ``rmq::queue::[annotationqueue]::rejected``

A delivery moves ready → unacked atomically (RPOPLPUSH), so there is no
instant at which a crash loses it: at startup every unacked list for this
queue (ANY connection — a crashed process can't clean its own) sweeps
back to ready, which is rmq's stale-connection cleaner behavior.

Counter semantics note: ``published``/``acked``/``dropped`` count THIS
process's traffic (Prometheus counters must be monotonic per process);
``depth()`` is read from Redis and covers everything, including events
inherited from a previous incarnation.
"""

from __future__ import annotations

import time
from typing import Optional

from ..bus.resp import RespClient, RespError
from ..utils.logging import get_logger
from .queue import AnnotationQueue, BatchHandler

log = get_logger("uplink.redis_queue")


class RedisAnnotationQueue(AnnotationQueue):
    def __init__(
        self,
        handler: Optional[BatchHandler] = None,
        *,
        addr: str = "127.0.0.1:6379",
        password: str = "",
        db: int = 0,
        queue_name: str = "annotationqueue",
        connection: str = "vepTpu",
        timeout_s: float = 5.0,
        **kwargs,
    ):
        super().__init__(handler, **kwargs)
        handshake = []
        if password:
            handshake.append(("AUTH", password))
        if db:
            handshake.append(("SELECT", str(db)))
        self._client = RespClient.from_addr(
            addr, timeout_s, handshake=tuple(handshake)
        )
        self._qname = queue_name
        self._ready = f"rmq::queue::[{queue_name}]::ready"
        self._rejected_key = f"rmq::queue::[{queue_name}]::rejected"
        self._unacked = (
            f"rmq::connection::{connection}::queue::[{queue_name}]::unacked"
        )
        self._other_cached, self._other_at = 0, float("-inf")
        self.resumed = self._sweep_orphans()
        if self.resumed:
            log.info(
                "recovered %d unacked annotation(s) from a previous run",
                self.resumed,
            )

    # -- crash recovery --

    def _sweep_orphans(self) -> int:
        """Unacked deliveries of ANY connection back to ready (rmq cleaner
        parity): a crashed process left them mid-flight; re-delivering is
        correct because the uplink POST is idempotent on the cloud side
        (same event payload)."""
        n = 0
        try:
            cursor = b"0"
            keys = set()
            # NB: rmq's literal "[queue]" brackets are glob char-classes
            # to MATCH — scan the connection prefix and filter exactly
            # in Python instead of fighting glob escaping.
            suffix = f"::queue::[{self._qname}]::unacked"
            while True:
                reply = self._client.command(
                    "SCAN", cursor, "MATCH", "rmq::connection::*::unacked",
                    "COUNT", "1000",
                )
                cursor, page = reply
                keys.update(
                    k.decode() for k in page if k.decode().endswith(suffix)
                )
                if cursor in (b"0", 0, "0"):
                    break
            for key in keys:
                # `is not None`: RESP nil ends the list; an EMPTY payload
                # (b"", falsy) is a legal queued event and must not halt
                # the sweep with entries still stranded.
                while self._client.command(
                    "RPOPLPUSH", key, self._ready
                ) is not None:
                    n += 1
        except (RespError, IOError) as exc:
            log.warning("unacked sweep failed (continuing): %s", exc)
        return n

    # -- producer side --

    # unacked+rejected depth is re-read at most this often on the publish
    # path (the consumer cycles every ~300 ms anyway); keeps publish at
    # ONE Redis round trip steady-state instead of four.
    _OTHER_DEPTH_TTL_S = 1.0

    def publish(self, payload: bytes) -> bool:
        try:
            # LPUSH first and use its reply (the ready length) for the
            # limit check — no pre-flight LLENs on the hot path.
            ready_len = int(
                self._client.command("LPUSH", self._ready, payload)
            )
            if ready_len + self._other_depth() > self._unacked_limit:
                # Over limit: shed from the head — the event just pushed
                # (or a concurrent publisher's, equally being shed).
                self._client.command("LPOP", self._ready)
                self.dropped += 1
                if self.dropped % 100 == 1:
                    log.warning(
                        "annotation queue full (%d unacked); dropping",
                        self._unacked_limit,
                    )
                return False
            self.published += 1
            return True
        except (RespError, IOError) as exc:
            self.dropped += 1
            log.warning("annotation publish to redis failed: %s", exc)
            return False

    def _other_depth(self) -> int:
        """Cached LLEN(unacked) + LLEN(rejected); ready is always read
        fresh (it is the fast-moving list and LPUSH returns it free)."""
        now = time.monotonic()
        if now - self._other_at > self._OTHER_DEPTH_TTL_S:
            total = 0
            for key in (self._unacked, self._rejected_key):
                total += int(self._client.command("LLEN", key) or 0)
            self._other_cached, self._other_at = total, now
        return self._other_cached

    def depth(self) -> int:
        total = 0
        for key in (self._ready, self._unacked, self._rejected_key):
            out = self._client.command("LLEN", key)
            total += int(out or 0)
        return total

    # -- consumer side --

    def drain_once(self) -> int:
        batch: list[bytes] = []
        try:
            for _ in range(self._max_batch):
                v = self._client.command(
                    "RPOPLPUSH", self._ready, self._unacked
                )
                if v is None:
                    break
                batch.append(v)
        except (RespError, IOError) as exc:
            log.warning("annotation drain pop failed: %s", exc)
        if not batch:
            return 0
        assert self._handler is not None
        try:
            ok = self._handler(batch)
        except Exception as exc:
            log.error("annotation batch handler raised: %s", exc)
            ok = False
        try:
            if ok:
                for v in batch:
                    self._client.command("LREM", self._unacked, "-1", v)
                self.acked += len(batch)
                return len(batch)
            self.rejected_batches += 1
            for v in batch:
                # LPUSH before LREM: a crash between the two leaves a
                # DUPLICATE (in rejected + unacked, reconciled to double
                # delivery by the startup sweep — the uplink is
                # idempotent), never a loss. The reverse order would
                # strand the event in no list at all.
                self._client.command("LPUSH", self._rejected_key, v)
                self._client.command("LREM", self._unacked, "-1", v)
        except (RespError, IOError) as exc:
            # Whatever we couldn't move stays in unacked; the startup
            # sweep of the next incarnation returns it to ready.
            log.warning("annotation ack/reject bookkeeping failed: %s", exc)
        return 0

    def requeue_rejected(self) -> None:
        try:
            while self._client.command(
                "RPOPLPUSH", self._rejected_key, self._ready
            ) is not None:
                pass
        except (RespError, IOError) as exc:
            log.warning("annotation requeue failed: %s", exc)

    def stop(self) -> None:
        super().stop()
        try:
            self._client.close()
        except Exception:
            pass
