"""Annotation uplink queue.

Semantics parity with the reference's rmq-backed pipeline
(``server/grpcapi/grpc_api.go:69-75``, ``server/batch/annotation_consumer.go``):

- producers ``publish`` serialized events and return immediately
  (ack-on-enqueue, ``grpc_annotation_api.go:51-56``);
- a consumer thread polls every ``poll_duration_ms`` and hands off batches of
  up to ``max_batch_size`` (reference defaults 300 ms / 299,
  ``server/main.go:59-64``);
- failed batches are rejected and re-queued after ``requeue_interval_s``
  (reference: 5 s ticker returning rejected deliveries,
  ``annotation_consumer.go:33-52``) so the uplink survives internet outages;
- total unacked is bounded by ``unacked_limit`` (``main.go:63``) — beyond it,
  publishes are dropped with a log (backpressure by shedding, matching rmq's
  bounded-unacked behavior).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..utils.logging import get_logger

log = get_logger("uplink.queue")

BatchHandler = Callable[[list[bytes]], bool]  # True = ack, False = reject


class AnnotationQueue:
    def __init__(
        self,
        handler: Optional[BatchHandler] = None,
        *,
        max_batch_size: int = 299,
        poll_duration_ms: int = 300,
        unacked_limit: int = 1000,
        requeue_interval_s: float = 5.0,
    ):
        self._handler = handler
        self._max_batch = max_batch_size
        self._poll_s = poll_duration_ms / 1000.0
        self._unacked_limit = unacked_limit
        self._requeue_s = requeue_interval_s
        self._queue: deque[bytes] = deque()
        self._rejected: deque[bytes] = deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.published = 0
        self.acked = 0
        self.dropped = 0
        self.rejected_batches = 0

    # -- producer side --

    def publish(self, payload: bytes) -> bool:
        with self._lock:
            if len(self._queue) + len(self._rejected) >= self._unacked_limit:
                self.dropped += 1
                if self.dropped % 100 == 1:
                    log.warning(
                        "annotation queue full (%d unacked); dropping",
                        self._unacked_limit,
                    )
                return False
            self._queue.append(payload)
            self.published += 1
            return True

    def depth(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._rejected)

    # -- consumer side --

    def start(self) -> None:
        if self._handler is None:
            raise ValueError("no batch handler configured")
        self._thread = threading.Thread(
            target=self._run, name="annotation-consumer", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        last_requeue = time.monotonic()
        while not self._stop.wait(self._poll_s):
            now = time.monotonic()
            if now - last_requeue >= self._requeue_s:
                # Return rejected deliveries to the ready queue
                # (annotation_consumer.go:33-52).
                self.requeue_rejected()
                last_requeue = now
            self.drain_once()

    def drain_once(self) -> int:
        """Consume one batch synchronously; returns number acked (tests call
        this directly to avoid timing dependence)."""
        with self._lock:
            batch = [
                self._queue.popleft()
                for _ in range(min(self._max_batch, len(self._queue)))
            ]
        if not batch:
            return 0
        assert self._handler is not None
        try:
            ok = self._handler(batch)
        except Exception as exc:
            log.error("annotation batch handler raised: %s", exc)
            ok = False
        if ok:
            self.acked += len(batch)
            return len(batch)
        self.rejected_batches += 1
        with self._lock:
            self._rejected.extend(batch)
        return 0

    def requeue_rejected(self) -> None:
        with self._lock:
            while self._rejected:
                self._queue.appendleft(self._rejected.pop())

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
