from .cloud import CloudClient, ForbiddenError, annotation_to_cloud, make_batch_handler
from .queue import AnnotationQueue
from .redis_queue import RedisAnnotationQueue

__all__ = [
    "AnnotationQueue",
    "RedisAnnotationQueue",
    "CloudClient",
    "ForbiddenError",
    "annotation_to_cloud",
    "make_batch_handler",
]
