"""Signed cloud client + annotation batch consumer.

Reference counterparts: ``server/services/edge_service.go`` (signed HTTPS
calls), ``server/batch/annotation_consumer.go`` (proto -> cloud annotation
mapping + batch POST), ``server/grpcapi/grpc_storage_api.go:63-88`` (storage
toggle PUT).

Deliberate divergence (resilience layer): the reference does one naked
POST per batch and drops it on failure (``annotation_consumer.go:90-93``
rejects; rmq re-delivers forever for transport errors, and the original
``make_batch_handler`` here just lost the batch). Posts now run through a
``RetryPolicy`` (decorrelated-jitter backoff under a ``Deadline`` budget)
inside a per-dependency ``CircuitBreaker``; classification: 401/403
(:class:`ForbiddenError`) and other 4xx are terminal, 5xx and transport
errors (``URLError``/socket) retry. A batch that exhausts its retries is
persisted to a bounded on-disk :class:`~..resilience.spool.DeadLetterSpool`
and re-drained oldest-first once a later post succeeds — a cloud outage
costs latency, not annotations.
"""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Optional

from ..proto import pb
from ..resilience.breaker import BreakerOpen, CircuitBreaker
from ..resilience.policy import Deadline, DeadlineExceeded, RetryPolicy
from ..resilience.spool import DeadLetterSpool
from ..utils.logging import get_logger
from ..utils.signing import sign_request

log = get_logger("uplink.cloud")


class ForbiddenError(RuntimeError):
    """401/403 from the cloud (reference ``ErrForbidden``,
    ``edge_service.go:58-61``)."""


class CloudHTTPError(RuntimeError):
    """Non-auth HTTP error from the cloud; ``retryable`` iff 5xx."""

    def __init__(self, code: int, detail: str = ""):
        super().__init__(f"cloud API error {code}: {detail}")
        self.code = code

    @property
    def retryable(self) -> bool:
        return self.code >= 500


def _transport_retryable(exc: BaseException) -> bool:
    """Retry classification for cloud posts: 5xx/transport yes; auth,
    other 4xx, open breaker, and spent deadline no."""
    if isinstance(exc, (ForbiddenError, BreakerOpen, DeadlineExceeded)):
        return False
    if isinstance(exc, CloudHTTPError):
        return exc.retryable
    return True  # URLError, socket timeouts, connection resets


class CloudClient:
    def __init__(self, settings, api_endpoint: str = "", timeout_s: float = 10.0):
        self._settings = settings
        self._endpoint = api_endpoint.rstrip("/")
        self._timeout = timeout_s

    def call(self, method: str, url: str, body,
             deadline: Optional[Deadline] = None) -> bytes:
        """One signed HTTP call. A ``deadline`` clamps the socket timeout
        to the caller's remaining budget, so nested retries can never
        out-wait the top-level deadline."""
        timeout = self._timeout
        if deadline is not None:
            deadline.check("cloud call")
            timeout = deadline.clamp(self._timeout)
        edge_key, edge_secret = self._settings.edge_credentials()
        payload, headers = sign_request(body, edge_key, edge_secret)
        req = urllib.request.Request(url, data=payload, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code in (401, 403):
                raise ForbiddenError(f"cloud rejected credentials: {exc.code}")
            raise CloudHTTPError(exc.code, repr(exc.read()[:200]))

    def set_storage(self, stream_key: str, enable: bool) -> bytes:
        # Signed PUT <api>/api/v1/edge/storage/<key>?enable=
        # (grpc_storage_api.go:63-88).
        url = f"{self._endpoint}/api/v1/edge/storage/{stream_key}"
        return self.call("PUT", url, {"enabled": enable})

    def post_annotations(self, url: str, annotations: list[dict],
                         deadline: Optional[Deadline] = None) -> bytes:
        return self.call("POST", url, annotations, deadline=deadline)


def annotation_to_cloud(req: pb.AnnotateRequest) -> dict:
    """proto -> cloud event mapping (reference ``RequestToAnnotation``,
    ``annotation_consumer.go:124-175``)."""
    out: dict = {
        "device_name": req.device_name,
        "remote_stream_id": req.remote_stream_id,
        "type": req.type,
        "start_timestamp": req.start_timestamp,
        "end_timestamp": req.end_timestamp,
        "object_type": req.object_type,
        "object_id": req.object_id,
        "object_tracking_id": req.object_tracking_id,
        "confidence": req.confidence,
        "ml_model": req.ml_model,
        "ml_model_version": req.ml_model_version,
        "width": req.width,
        "height": req.height,
        "is_keyframe": req.is_keyframe,
        "video_type": req.video_type,
        "offset_timestamp": req.offset_timestamp,
        "offset_duration": req.offset_duration,
        "offset_frame_id": req.offset_frame_id,
        "offset_packet_id": req.offset_packet_id,
        "custom_meta_1": req.custom_meta_1,
        "custom_meta_2": req.custom_meta_2,
        "custom_meta_3": req.custom_meta_3,
        "custom_meta_4": req.custom_meta_4,
        "custom_meta_5": req.custom_meta_5,
    }
    if req.HasField("object_bouding_box"):
        bb = req.object_bouding_box
        out["bounding_box"] = {
            "top": bb.top, "left": bb.left,
            "width": bb.width, "height": bb.height,
        }
    if req.HasField("location"):
        out["location"] = {"lat": req.location.lat, "lon": req.location.lon}
    if req.HasField("object_coordinate"):
        c = req.object_coordinate
        out["object_coordinate"] = {"x": c.x, "y": c.y, "z": c.z}
    if req.mask:
        out["mask"] = [{"x": c.x, "y": c.y, "z": c.z} for c in req.mask]
    if req.object_signature:
        out["object_signature"] = list(req.object_signature)
    return out


def _decode_batch(batch: list[bytes]) -> list[dict]:
    events = []
    for raw in batch:
        try:
            events.append(annotation_to_cloud(pb.AnnotateRequest.FromString(raw)))
        except Exception as exc:
            log.error("dropping undecodable annotation: %s", exc)
    return events


def make_batch_handler(
    settings,
    annotation_endpoint: str,
    *,
    client=None,
    spool: Optional[DeadLetterSpool] = None,
    retry: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    post_deadline_s: float = 30.0,
):
    """Build the AnnotationQueue batch handler: deserialize, map, signed
    POST through retry + breaker, dead-letter spool on exhaustion.

    Contract per batch:
    - success: POST the live batch, then drain any spooled backlog
      oldest-first through the now-healthy uplink; returns True (ack).
    - transient failure (5xx / transport / breaker open): the RAW batch
      is persisted to ``spool`` and acked (persisted == not lost); with
      no spool (or a full one) returns False so the queue requeues it.
    - ForbiddenError: terminal — the consumer disables itself once
      (credentials do not heal by retrying; reference ``ErrForbidden``
      semantics) and acks subsequent batches without posting.

    ``client``/``retry``/``breaker`` are injectable for tests and the
    chaos harness; attributes ``handle.state`` / ``handle.breaker`` /
    ``handle.spool`` expose the wiring for artifacts.
    """
    client = client or CloudClient(settings)
    retry = retry or RetryPolicy(max_attempts=3, base_s=0.5, cap_s=5.0)
    breaker = breaker or CircuitBreaker(
        "annotation_uplink", failure_threshold=5, recovery_timeout_s=15.0
    )
    state = {"disabled": False}

    def _post(events: list[dict]) -> None:
        deadline = Deadline.after(post_deadline_s)
        retry.run(
            lambda: breaker.call(
                lambda: client.post_annotations(
                    annotation_endpoint, events, deadline=deadline
                ),
                # An auth rejection means the dependency ANSWERED: it
                # must not trip the breaker open.
                excluded=(ForbiddenError,),
            ),
            should_retry=_transport_retryable,
            deadline=deadline,
        )

    def _drain_spool() -> None:
        if spool is None or spool.pending() == 0:
            return

        def deliver(items: list[bytes]) -> bool:
            events = _decode_batch(items)
            if not events:
                return True  # nothing decodable left in this batch
            try:
                _post(events)
                return True
            except ForbiddenError:
                raise  # handled by the caller: terminal disable
            except Exception:
                return False  # uplink unhealthy again; stop, retry later

        n = spool.drain(deliver)
        if n:
            log.info("re-delivered %d spooled annotation batch(es)", n)

    def handle(batch: list[bytes]) -> bool:
        if state["disabled"]:
            return True  # terminally disabled (logged once below)
        events = _decode_batch(batch)
        try:
            if events:
                _post(events)
            _drain_spool()
            return True
        except ForbiddenError:
            state["disabled"] = True
            log.error(
                "cloud rejected edge credentials; annotation uplink disabled"
                " (batches will be acked and dropped)"
            )
            return True  # reference acks-on-forbidden would retry forever;
            # credentials won't heal by retrying — drop and surface in logs
        except Exception as exc:
            if spool is not None:
                if spool.put(batch) is not None:
                    log.warning(
                        "annotation uplink failed (%s); batch spooled", exc
                    )
                    return True  # persisted == acked; drained on recovery
                log.error(
                    "annotation uplink failed and spool is full; requeueing"
                )
                return False
            log.warning("annotation uplink failed (%s); will requeue", exc)
            return False

    handle.state = state
    handle.breaker = breaker
    handle.spool = spool
    return handle
