"""ViT-B/16 frame tagger — BASELINE config 4 (32-stream dynamic batching).

Patchify is a single strided conv (one big MXU matmul per image); the
encoder comes from `transformer.py` with logical sharding names, so the same
model runs single-chip (config 4) and mesh-sharded (parallel/).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.preprocess import pad_channels
from .common import Dtype
from .transformer import AttnFn, Encoder, EncoderConfig


@dataclass(frozen=True)
class ViTConfig:
    num_classes: int = 1000
    image_size: int = 224
    patch_size: int = 16
    encoder: EncoderConfig = field(default_factory=EncoderConfig)  # B/16 defaults
    # Lane-fill channel padding for the patchify conv (ops.preprocess
    # .pad_channels; cpad lever, LEVERS_r05): kernel grows
    # [p,p,3,D]->[p,p,pad,D], zero input planes keep outputs identical;
    # import_weights zero-pads checkpoints. 0 = off.
    patch_pad_c: int = 0

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def tiny_vit_config(num_classes: int = 10) -> ViTConfig:
    return ViTConfig(
        num_classes=num_classes,
        image_size=32,
        patch_size=8,
        encoder=EncoderConfig(num_layers=2, dim=64, num_heads=4, mlp_dim=128),
    )


class ViT(nn.Module):
    cfg: ViTConfig
    dtype: Dtype = jnp.bfloat16
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        c = self.cfg
        x = x.astype(self.dtype)
        x = pad_channels(x, c.patch_pad_c)
        p = c.patch_size
        x = nn.Conv(
            c.encoder.dim, kernel_size=(p, p), strides=(p, p),
            padding="VALID", dtype=self.dtype, name="patch_embed",
        )(x)
        b = x.shape[0]
        x = x.reshape(b, -1, c.encoder.dim)
        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, c.encoder.dim), jnp.float32
        ).astype(self.dtype)
        x = jnp.concatenate([jnp.tile(cls, (b, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, c.num_patches + 1, c.encoder.dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        x = Encoder(c.encoder, self.dtype, self.attn_fn, name="encoder")(
            x, deterministic=not train
        )
        return nn.Dense(c.num_classes, dtype=jnp.float32, name="classifier")(x[:, 0])
