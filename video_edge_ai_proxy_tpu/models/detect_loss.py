"""YOLOv8 detection loss: task-aligned assignment + CIoU + DFL.

The reference has no training at all; this module makes the flagship
detector fine-tunable on-TPU (edge deployments retrain on site footage).
Everything is static-shape: ground truth arrives padded to ``max_boxes``
with a validity mask, assignment is a dense [B, M, A] tensor computation
(no data-dependent gathers), so the whole loss jits cleanly and shards
over the dp axis like any other step.

Components (standard YOLOv8 formulation):
- Task-aligned assigner: align = cls_prob^alpha * IoU^beta over anchors
  whose center lies inside the GT box; top-k per GT; conflicts resolved to
  the highest-align GT.
- Classification: BCE against IoU-scaled soft targets.
- Box: CIoU loss on assigned anchors.
- DFL: two-hot cross-entropy on the ltrb bin distribution.
"""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .yolov8 import YOLOv8Config, _anchor_points

ALPHA, BETA = 0.5, 6.0          # TAL exponents
TOP_K = 10
W_BOX, W_CLS, W_DFL = 7.5, 0.5, 1.5
EPS = 1e-9


def flatten_levels(head_out, cfg: YOLOv8Config):
    """Per-level head outputs -> flat [B, A, ...] plus anchor geometry."""
    box_l, cls_l, anchors, strides = [], [], [], []
    for (box, cls), stride in zip(head_out, cfg.strides):
        b, h, w, _ = box.shape
        box_l.append(box.reshape(b, h * w, 4 * cfg.reg_max))
        cls_l.append(cls.reshape(b, h * w, cfg.num_classes))
        anchors.append(_anchor_points(h, w, stride))
        strides.append(jnp.full((h * w,), stride, jnp.float32))
    return (
        jnp.concatenate(box_l, 1),
        jnp.concatenate(cls_l, 1),
        jnp.concatenate(anchors, 0),     # [A, 2] px
        jnp.concatenate(strides, 0),     # [A]
    )


def _decode_dfl(box_logits: jnp.ndarray, anchors: jnp.ndarray,
                strides: jnp.ndarray, reg_max: int) -> jnp.ndarray:
    """[B, A, 4*reg_max] -> xyxy px (same math as inference decode)."""
    b, a, _ = box_logits.shape
    probs = nn.softmax(box_logits.reshape(b, a, 4, reg_max), axis=-1)
    dist = probs @ jnp.arange(reg_max, dtype=jnp.float32)   # [B, A, 4] strides
    dist = dist * strides[None, :, None]
    x1y1 = anchors[None] - dist[..., :2]
    x2y2 = anchors[None] + dist[..., 2:]
    return jnp.concatenate([x1y1, x2y2], -1)


def iou_pairwise(gt: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """[B, M, 4] x [B, A, 4] -> IoU [B, M, A]."""
    gt_ = gt[:, :, None, :]       # [B, M, 1, 4]
    pr_ = pred[:, None, :, :]     # [B, 1, A, 4]
    lt = jnp.maximum(gt_[..., :2], pr_[..., :2])
    rb = jnp.minimum(gt_[..., 2:], pr_[..., 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_g = jnp.maximum(gt_[..., 2] - gt_[..., 0], 0) * jnp.maximum(
        gt_[..., 3] - gt_[..., 1], 0)
    area_p = jnp.maximum(pr_[..., 2] - pr_[..., 0], 0) * jnp.maximum(
        pr_[..., 3] - pr_[..., 1], 0)
    return inter / jnp.maximum(area_g + area_p - inter, EPS)


def ciou(box1: jnp.ndarray, box2: jnp.ndarray) -> jnp.ndarray:
    """Complete IoU between aligned boxes [..., 4] xyxy -> [...]."""
    lt = jnp.maximum(box1[..., :2], box2[..., :2])
    rb = jnp.minimum(box1[..., 2:], box2[..., 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    w1, h1 = box1[..., 2] - box1[..., 0], box1[..., 3] - box1[..., 1]
    w2, h2 = box2[..., 2] - box2[..., 0], box2[..., 3] - box2[..., 1]
    union = w1 * h1 + w2 * h2 - inter
    iou = inter / jnp.maximum(union, EPS)
    # enclosing box diagonal
    elt = jnp.minimum(box1[..., :2], box2[..., :2])
    erb = jnp.maximum(box1[..., 2:], box2[..., 2:])
    ewh = jnp.maximum(erb - elt, 0.0)
    c2 = ewh[..., 0] ** 2 + ewh[..., 1] ** 2
    # center distance
    cx1, cy1 = (box1[..., 0] + box1[..., 2]) / 2, (box1[..., 1] + box1[..., 3]) / 2
    cx2, cy2 = (box2[..., 0] + box2[..., 2]) / 2, (box2[..., 1] + box2[..., 3]) / 2
    rho2 = (cx1 - cx2) ** 2 + (cy1 - cy2) ** 2
    # aspect-ratio consistency
    v = (4 / jnp.pi ** 2) * (
        jnp.arctan(w2 / jnp.maximum(h2, EPS)) - jnp.arctan(w1 / jnp.maximum(h1, EPS))
    ) ** 2
    alpha = v / jnp.maximum(1 - iou + v, EPS)
    alpha = jax.lax.stop_gradient(alpha)
    return iou - rho2 / jnp.maximum(c2, EPS) - alpha * v


def assign(
    cls_logits: jnp.ndarray,     # [B, A, C]
    pred_boxes: jnp.ndarray,     # [B, A, 4] px
    anchors: jnp.ndarray,        # [A, 2]
    gt_boxes: jnp.ndarray,       # [B, M, 4] px xyxy
    gt_labels: jnp.ndarray,      # [B, M] int32
    gt_mask: jnp.ndarray,        # [B, M] bool
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Task-aligned assignment. Returns (fg [B, A] bool, gt_idx [B, A] int32,
    norm_align [B, A] — the IoU-scaled soft target weight)."""
    b, a, _ = cls_logits.shape
    m = gt_boxes.shape[1]

    # anchor center inside GT
    ax = anchors[None, None, :, 0]
    ay = anchors[None, None, :, 1]
    in_gt = (
        (ax >= gt_boxes[..., 0:1]) & (ax < gt_boxes[..., 2:3])
        & (ay >= gt_boxes[..., 1:2]) & (ay < gt_boxes[..., 3:4])
    )                                                     # [B, M, A]
    valid = in_gt & gt_mask[..., None]

    probs = nn.sigmoid(cls_logits)                        # [B, A, C]
    cls_score = jnp.take_along_axis(
        probs.transpose(0, 2, 1),                          # [B, C, A]
        jnp.clip(gt_labels, 0, probs.shape[-1] - 1)[..., None], axis=1,
    )                                                      # [B, M, A]
    ious = iou_pairwise(gt_boxes, pred_boxes)              # [B, M, A]
    align = (cls_score ** ALPHA) * (jnp.maximum(ious, 0) ** BETA)
    align = jnp.where(valid, align, 0.0)

    # top-k anchors per GT (dense mask, no gathers). The floor is the
    # k-th value itself, RELATIVE, never an absolute epsilon: at random
    # init align = cls^0.5 * iou^6 can sit at 1e-10 for small objects,
    # and an absolute cut (the old `max(kth, 1e-9)`) rejected every real
    # candidate — zero positives forever, so the only gradient left was
    # background suppression and the cls head collapsed to -inf (observed:
    # fg=0 from step 0, logits at -1e10 by step 30). With kth == 0 (< k
    # positive-align anchors exist) every align > 0 anchor is admitted —
    # more than k, but they are the only real candidates and the per-
    # anchor conflict resolution below keeps the best GT per anchor.
    k = min(TOP_K, a)
    kth = jnp.sort(align, axis=-1)[..., -k][..., None]     # [B, M, 1]
    topk = (align >= kth) & (align > 0)

    # conflicts: anchor claimed by the GT with max align
    align_masked = jnp.where(topk, align, 0.0)
    gt_idx = jnp.argmax(align_masked, axis=1)              # [B, A]
    best = jnp.max(align_masked, axis=1)                   # [B, A]
    fg = best > 0

    # normalize: per-GT max align -> per-GT max IoU (YOLOv8 target scaling)
    pos_iou = jnp.where(topk, ious, 0.0)
    gt_max_align = jnp.max(align_masked, axis=-1)          # [B, M]
    gt_max_iou = jnp.max(pos_iou, axis=-1)                 # [B, M]
    scale = gt_max_iou / jnp.maximum(gt_max_align, EPS)    # [B, M]
    norm_align = best * jnp.take_along_axis(scale, gt_idx, axis=1)
    return fg, gt_idx, jnp.where(fg, norm_align, 0.0)


def detection_loss(
    head_out,
    targets: Dict[str, jnp.ndarray],
    cfg: YOLOv8Config,
) -> jnp.ndarray:
    """Total loss for raw head output (model.apply(..., decode=False)).

    targets: {"boxes": [B, M, 4] px xyxy, "labels": [B, M] int32,
              "mask": [B, M] bool}.
    """
    box_logits, cls_logits, anchors, strides = flatten_levels(head_out, cfg)
    pred_boxes = _decode_dfl(box_logits, anchors, strides, cfg.reg_max)
    # The assigner is a TARGET BUILDER, not part of the differentiable
    # objective (ultralytics runs it under no_grad). Detaching matters
    # numerically, not just semantically: align = cls^0.5 * iou^6 spans
    # ~1e-40..1, and grad paths like d/db (a / max(b, EPS)) = -a/b^2
    # overflow to inf for tiny aligns, NaN-ing the whole step — observed
    # on the first self-train runs.
    fg, gt_idx, weight = assign(
        jax.lax.stop_gradient(cls_logits),
        jax.lax.stop_gradient(pred_boxes), anchors,
        targets["boxes"], targets["labels"], targets["mask"],
    )
    weight = jax.lax.stop_gradient(weight)

    b, a, c = cls_logits.shape
    t_boxes = jnp.take_along_axis(
        targets["boxes"], gt_idx[..., None], axis=1
    )                                                      # [B, A, 4]
    t_labels = jnp.take_along_axis(targets["labels"], gt_idx, axis=1)
    t_scores = jax.nn.one_hot(t_labels, c) * weight[..., None]

    # classification BCE over every anchor
    cls_loss = optax_bce(cls_logits, t_scores).sum() / jnp.maximum(
        t_scores.sum(), 1.0
    )

    # CIoU on foreground anchors, weighted by alignment
    iou_term = (1.0 - ciou(pred_boxes, t_boxes)) * weight
    denom = jnp.maximum(weight.sum(), 1.0)
    box_loss = jnp.where(fg, iou_term, 0.0).sum() / denom

    # DFL: two-hot cross entropy on ltrb distances in stride units
    lt = (anchors[None] - t_boxes[..., :2]) / strides[None, :, None]
    rb = (t_boxes[..., 2:] - anchors[None]) / strides[None, :, None]
    dist = jnp.clip(
        jnp.concatenate([lt, rb], -1), 0, cfg.reg_max - 1 - 0.01
    )                                                      # [B, A, 4]
    lo = jnp.floor(dist)
    hi_w = dist - lo
    logp = nn.log_softmax(
        box_logits.reshape(b, a, 4, cfg.reg_max), axis=-1
    )
    lo_i = lo.astype(jnp.int32)
    lp_lo = jnp.take_along_axis(logp, lo_i[..., None], -1)[..., 0]
    lp_hi = jnp.take_along_axis(
        logp, jnp.clip(lo_i + 1, 0, cfg.reg_max - 1)[..., None], -1
    )[..., 0]
    dfl = -((1 - hi_w) * lp_lo + hi_w * lp_hi).mean(-1) * weight
    dfl_loss = jnp.where(fg, dfl, 0.0).sum() / denom

    return W_BOX * box_loss + W_CLS * cls_loss + W_DFL * dfl_loss


def optax_bce(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Elementwise sigmoid BCE (kept local: optax's version reduces)."""
    return jnp.maximum(logits, 0) - logits * targets + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


def make_detection_loss_fn(cfg: YOLOv8Config, update_stats: bool = False):
    """Adapter for parallel.make_trainer: loss_fn(model, params, aux,
    batch, targets) with targets as the padded dict above.

    ``update_stats=False`` (default): BatchNorm runs with frozen
    statistics (train=False) — the near-distribution fine-tune stance
    for imported pretrained checkpoints, and what keeps the step purely
    functional. ``update_stats=True``: BatchNorm normalizes by batch
    statistics and the loss_fn returns ``(loss, new_aux)`` for
    ``make_trainer(..., mutable_aux=True)`` — REQUIRED from scratch;
    frozen random-init stats degenerate deep features into constants
    (see make_trainer's docstring)."""
    def loss_fn(model, params, aux, batch, targets):
        if update_stats:
            head_out, mutated = model.apply(
                {"params": params, **(aux or {})}, batch, train=True,
                decode=False, mutable=["batch_stats"],
            )
            new_aux = {**(aux or {}), **mutated}
            return detection_loss(head_out, targets, cfg), new_aux
        head_out = model.apply(
            {"params": params, **(aux or {})}, batch, train=False, decode=False
        )
        return detection_loss(head_out, targets, cfg)

    return loss_fn
