"""VideoMAE action recognizer — BASELINE config 5 (8-frame clips, 8 cameras).

Tubelet embedding (2×16×16) is a 3-D strided conv; the token sequence
(T/2 · H/16 · W/16 = 4·14·14 = 784 for 8×224²) flows through the shared
encoder. The temporal axis is just more tokens (SURVEY.md §5.7: clip length
8 needs no ring attention — but the encoder's `attn_fn` hook accepts the
sequence-parallel implementation from `parallel/ring_attention.py` the
moment clips grow to hundreds of frames).

Mean-pool classification head (the VideoMAE fine-tune head). The MAE
pretraining objective (tube masking + pixel reconstruction) lives in
`masked_pretrain_loss` so the training path exercises the full
encoder-decoder, not just the classifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.preprocess import pad_channels
from .common import Dtype
from .transformer import AttnFn, Encoder, EncoderConfig


@dataclass(frozen=True)
class VideoMAEConfig:
    num_classes: int = 400            # Kinetics-400
    image_size: int = 224
    patch_size: int = 16
    num_frames: int = 8
    tubelet_size: int = 2
    # Lane-fill channel padding for the tubelet conv (ops.preprocess
    # .pad_channels; cpad lever, LEVERS_r05): proj kernel grows
    # [ts,p,p,3,D]->[ts,p,p,pad,D], zero input planes keep outputs
    # identical; import_weights zero-pads checkpoints. 0 = off.
    patch_pad_c: int = 0
    encoder: EncoderConfig = field(default_factory=EncoderConfig)
    # Light decoder for the MAE pretrain objective (VideoMAE uses a narrow
    # 4-layer decoder; scaled here with the encoder config).
    decoder_layers: int = 4
    decoder_dim: int = 384

    @property
    def tokens_per_frame_group(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_tokens(self) -> int:
        return (self.num_frames // self.tubelet_size) * self.tokens_per_frame_group

    @property
    def pixels_per_token(self) -> int:
        return self.tubelet_size * self.patch_size * self.patch_size * 3


def tiny_videomae_config(num_classes: int = 5) -> VideoMAEConfig:
    return VideoMAEConfig(
        num_classes=num_classes,
        image_size=32,
        patch_size=8,
        num_frames=4,
        tubelet_size=2,
        encoder=EncoderConfig(num_layers=2, dim=64, num_heads=4, mlp_dim=128),
        decoder_layers=1,
        decoder_dim=32,
    )


class TubeletEmbed(nn.Module):
    dim: int
    patch_size: int
    tubelet_size: int
    dtype: Dtype = jnp.bfloat16
    pad_c: int = 0     # lane-fill channel padding (VideoMAEConfig.patch_pad_c)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        """[B, T, H, W, 3] -> [B, tokens, dim]."""
        p, ts = self.patch_size, self.tubelet_size
        x = pad_channels(x.astype(self.dtype), self.pad_c)
        x = nn.Conv(
            self.dim, kernel_size=(ts, p, p), strides=(ts, p, p),
            padding="VALID", dtype=self.dtype, name="proj",
        )(x)
        b = x.shape[0]
        return x.reshape(b, -1, self.dim)


class VideoMAE(nn.Module):
    cfg: VideoMAEConfig
    dtype: Dtype = jnp.bfloat16
    attn_fn: Optional[AttnFn] = None

    def setup(self):
        c = self.cfg
        self.embed = TubeletEmbed(
            c.encoder.dim, c.patch_size, c.tubelet_size, self.dtype,
            pad_c=c.patch_pad_c, name="tubelet"
        )
        self.pos_embed = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, c.num_tokens, c.encoder.dim), jnp.float32,
        )
        self.encoder = Encoder(c.encoder, self.dtype, self.attn_fn, name="encoder")
        self.head = nn.Dense(c.num_classes, dtype=jnp.float32, name="head")

    def __call__(self, clips: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        """Fine-tune / inference path: [B, T, H, W, 3] -> [B, num_classes]."""
        x = self.embed(clips) + self.pos_embed.astype(self.dtype)
        x = self.encoder(x, deterministic=not train)
        return self.head(jnp.mean(x.astype(jnp.float32), axis=1))

    def encode_visible(self, clips: jnp.ndarray, keep_mask: jnp.ndarray,
                       train: bool = True) -> jnp.ndarray:
        """MAE pretrain encoder pass over ALL tokens with masked tokens
        zeroed (static-shape variant of token dropping: on TPU a gather to
        a data-dependent token count would force dynamic shapes, so we trade
        the FLOPs of encoding masked positions for a fixed graph).
        keep_mask: [B, tokens] bool, True = visible."""
        x = self.embed(clips) + self.pos_embed.astype(self.dtype)
        x = jnp.where(keep_mask[..., None], x, jnp.zeros_like(x))
        return self.encoder(x, deterministic=not train)


class VideoMAEDecoder(nn.Module):
    """Narrow decoder reconstructing masked tubelet pixels."""

    cfg: VideoMAEConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        c = self.cfg
        dec_cfg = EncoderConfig(
            num_layers=c.decoder_layers, dim=c.decoder_dim,
            num_heads=max(1, c.decoder_dim // 64), mlp_dim=c.decoder_dim * 4,
        )
        x = nn.Dense(c.decoder_dim, dtype=self.dtype, name="dec_embed")(tokens)
        pos = self.param(
            "dec_pos", nn.initializers.normal(0.02),
            (1, c.num_tokens, c.decoder_dim), jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        x = Encoder(dec_cfg, self.dtype, name="decoder")(x, deterministic)
        return nn.Dense(c.pixels_per_token, dtype=jnp.float32, name="dec_pred")(x)


def tubelet_pixels(clips: jnp.ndarray, cfg: VideoMAEConfig) -> jnp.ndarray:
    """[B, T, H, W, 3] -> [B, tokens, pixels_per_token] ground-truth targets,
    ordered to match TubeletEmbed's conv output (t-group, h, w)."""
    b, t, h, w, _ = clips.shape
    p, ts = cfg.patch_size, cfg.tubelet_size
    x = clips.reshape(b, t // ts, ts, h // p, p, w // p, p, 3)
    x = x.transpose(0, 1, 3, 5, 2, 4, 6, 7)  # b, tg, hh, ww, ts, p, p, c
    return x.reshape(b, (t // ts) * (h // p) * (w // p), ts * p * p * 3)


def masked_pretrain_loss(
    model: VideoMAE,
    decoder: VideoMAEDecoder,
    params,
    clips: jnp.ndarray,
    keep_mask: jnp.ndarray,
) -> jnp.ndarray:
    """VideoMAE objective: MSE on normalized pixels of MASKED tokens only."""
    enc = model.apply(
        params["encoder"], clips, keep_mask, train=True,
        method=VideoMAE.encode_visible,
    )
    pred = decoder.apply(params["decoder"], enc, deterministic=False)
    target = tubelet_pixels(clips.astype(jnp.float32), model.cfg)
    mu = target.mean(axis=-1, keepdims=True)
    sd = target.std(axis=-1, keepdims=True) + 1e-6
    target = (target - mu) / sd
    err = jnp.mean((pred - target) ** 2, axis=-1)          # [B, tokens]
    masked = ~keep_mask
    return jnp.sum(err * masked) / jnp.maximum(jnp.sum(masked), 1)
