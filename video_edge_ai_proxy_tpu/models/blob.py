"""Detect-identity "blob gauge" model for ROI serving verification.

Not a learned model: a jittable measurement instrument that returns the
EXACT pixel bounding box of color-keyed blobs, used by the MOSAIC
round-trip gates (tests/test_roi.py, tools/roi_smoke.py) to prove the
pack -> detect -> scatter-back path is geometry-preserving without any
model noise in the loop. A learned detector's boxes wobble a few px per
crop placement, which would make the replay gate's IoU threshold measure
the model, not the serving path; this gauge makes a coordinate bug show
up as an exact mismatch.

Scene contract: synthetic frames are background gray (114, the
letterbox pad value) with axis-aligned blobs painted in one of
``BINS`` color keys — BGR ``(64, 255, key*BIN_WIDTH + BIN_WIDTH//2)``.
Anchor ``k`` of the output detects the bounding box of every pixel
whose red channel quantizes to bin ``k`` AND whose green channel is
bright (background/letterbox gray fails the green test, so the gray
bin can never fire on padding). One color key per stream keeps blobs
separable when many streams' crops share a canvas. The red-bin centers
are ``BIN_WIDTH`` apart with a +-12 level acceptance window, wide
enough that bf16 preprocessing error (<1 level at u8 scale) can never
flip a bin.

Implements the registry detect contract (models/registry.py,
engine/runner.py build_serving_step): ``apply(variables, x,
decode="serving")`` -> (boxes [N, A, 4] xyxy letterbox px, max_logit
[N, A], cls_ids [N, A]); class id == color bin.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

# 8 red-channel bins of 32 u8 levels each; bin 3 contains the 114-gray
# background and is excluded by the green-brightness test, not by index.
BINS = 8
BIN_WIDTH = 32
# Acceptance half-window around each bin center, in u8 levels.
_BIN_TOL = 12.0
_LOGIT_HIT = 8.0     # sigmoid(8) ~ 0.99966: far above the NMS floor
_LOGIT_MISS = -8.0


def blob_color(key: int) -> tuple:
    """BGR fill color for color bin ``key`` (paint synthetic blobs with
    this; the gauge's anchor ``key`` will report their bbox)."""
    return (64, 255, key * BIN_WIDTH + BIN_WIDTH // 2)


@dataclasses.dataclass(frozen=True)
class BlobGaugeConfig:
    num_classes: int = BINS


class BlobGauge(nn.Module):
    """See module docstring. Carries one dummy parameter so the
    registry's ``init_params`` / checkpoint plumbing work unchanged."""

    cfg: BlobGaugeConfig = BlobGaugeConfig()

    @nn.compact
    def __call__(self, x, decode=True):
        bins = self.cfg.num_classes
        bias = self.param("bias", nn.initializers.zeros, (1,))
        # f32 throughout: the gauge measures geometry, bf16 buys nothing.
        x = x.astype(jnp.float32) + bias[0] * 0.0
        n, h, w, _ = x.shape
        # preprocess_letterbox flips BGR -> RGB: channel 0 is the red key.
        red = x[..., 0] * 255.0
        green = x[..., 1]
        centers = (jnp.arange(bins, dtype=jnp.float32) * BIN_WIDTH
                   + BIN_WIDTH / 2.0)
        mask = (
            (jnp.abs(red[..., None] - centers) < _BIN_TOL)
            & (green[..., None] > 0.75)
        )                                             # [N, H, W, BINS]
        cols = jnp.arange(w, dtype=jnp.float32)[None, :, None]
        rows = jnp.arange(h, dtype=jnp.float32)[None, :, None]
        any_col = mask.any(axis=1)                    # [N, W, BINS]
        any_row = mask.any(axis=2)                    # [N, H, BINS]
        big = jnp.float32(1e9)
        x0 = jnp.min(jnp.where(any_col, cols, big), axis=1)
        x1 = jnp.max(jnp.where(any_col, cols + 1.0, -big), axis=1)
        y0 = jnp.min(jnp.where(any_row, rows, big), axis=1)
        y1 = jnp.max(jnp.where(any_row, rows + 1.0, -big), axis=1)
        present = any_col.any(axis=1)                 # [N, BINS]
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
        boxes = jnp.where(present[..., None], boxes, 0.0)
        logits = jnp.where(present, _LOGIT_HIT, _LOGIT_MISS)
        cls_ids = jnp.broadcast_to(
            jnp.arange(bins, dtype=jnp.int32)[None, :], (n, bins))
        if decode == "serving":
            return boxes, logits, cls_ids
        # decode=True parity shape (boxes, per-anchor class probs).
        probs = (jax.nn.sigmoid(logits)[..., None]
                 * jax.nn.one_hot(cls_ids, bins))
        return boxes, probs
