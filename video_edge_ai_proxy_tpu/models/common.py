"""Shared building blocks for the model zoo.

The reference ships no models at all — it is plumbing that feeds raw BGR24
frames to external CPU clients (`/root/reference/README.md:5-27`). The five
model families here are the TPU inference plane that replaces that void
(BASELINE.json configs 1-5), built MXU-first:

- NHWC layout end to end (XLA's native conv layout on TPU).
- bfloat16 compute / float32 params ("mixed" policy): matmuls and convs hit
  the MXU at bf16, normalization statistics stay fp32.
- Static shapes only; every model is shape-polymorphic *at trace time* via
  its config, never at run time.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

# SiLU is the activation of the YOLO family; convnets here default to their
# canonical activations via explicit args.
ACT: dict[str, Callable] = {
    "relu": nn.relu,
    "relu6": lambda x: jnp.minimum(nn.relu(x), 6.0),
    "silu": nn.silu,
    "gelu": nn.gelu,
    "identity": lambda x: x,
}


class _Int8Conv(nn.Module):
    """int8 x int8 conv with per-output-channel weight scales (round 15).

    Drop-in replacement for the ``nn.Conv(name="conv")`` inside ConvBN:
    declares the SAME ``kernel`` param (same shape, same f32 param dtype,
    same init), so checkpoint trees move between the fp and int8-act
    variants untouched. Two extra pieces of state/behavior:

    - ``quant/in_absmax`` — a scalar f32 running max-abs of the input
      activation, written only while the "quant" collection is mutable
      (the calibration pass, models/quantize.py calibrate_serving). The
      calibration pass itself computes in the fp dtype, so its outputs
      match the fp model exactly.
    - serving (quant frozen): the input quantizes against the calibrated
      static per-tensor scale, the kernel quantizes in-graph against its
      per-output-channel max-abs (both absmax/127, the symmetric PTQ rule
      models/quantize.py already uses for residency), and the conv runs
      int8 x int8 with ``preferred_element_type=int32`` — the MXU's
      native int8 systolic mode, 2x the bf16 MAC rate on v5e. Dequantize
      is one fused multiply by ``s_in * s_w[oc]`` feeding the f32 BN.
    """

    features: int
    kernel: int = 3
    stride: int = 1
    pad: Any = None
    groups: int = 1
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from jax import lax

        k = self.kernel
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (k, k, x.shape[-1] // self.groups, self.features),
            jnp.float32,
        )
        absmax = self.variable(
            "quant", "in_absmax", lambda: jnp.zeros((), jnp.float32)
        )
        dn = ("NHWC", "HWIO", "NHWC")
        strides = (self.stride, self.stride)
        if self.is_mutable_collection("quant"):
            # Calibration (and init): observe the input range, run fp.
            absmax.value = jnp.maximum(
                absmax.value, jnp.max(jnp.abs(x.astype(jnp.float32)))
            )
            return lax.conv_general_dilated(
                x.astype(self.dtype), kernel.astype(self.dtype), strides,
                self.pad, dimension_numbers=dn,
                feature_group_count=self.groups,
            )
        s_in = jnp.maximum(absmax.value, 1e-8) * (1.0 / 127.0)
        xq = jnp.clip(
            jnp.round(x.astype(jnp.float32) / s_in), -127, 127
        ).astype(jnp.int8)
        s_w = jnp.maximum(
            jnp.max(jnp.abs(kernel), axis=(0, 1, 2)), 1e-12
        ) * (1.0 / 127.0)
        wq = jnp.clip(jnp.round(kernel / s_w), -127, 127).astype(jnp.int8)
        y = lax.conv_general_dilated(
            xq, wq, strides, self.pad, dimension_numbers=dn,
            feature_group_count=self.groups,
            preferred_element_type=jnp.int32,
        )
        return (y.astype(jnp.float32) * (s_in * s_w)).astype(self.dtype)


class ConvBN(nn.Module):
    """Conv → BatchNorm → activation, the convnet workhorse.

    BatchNorm keeps fp32 statistics regardless of compute dtype; `train`
    toggles running-average use so the same module serves the inference
    plane (frozen stats) and fine-tuning (mutable `batch_stats`).

    ``padding`` overrides the symmetric k//2 default (the s2d stem needs
    asymmetric ((1,0),(1,0))); ``act_int8`` swaps the conv for the int8
    activation path above (serving-only — the param tree is identical, so
    fp checkpoints serve either way; fine-tuning through the int8 conv is
    unsupported).
    """

    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    act: str = "silu"
    # BN epsilon is part of the checkpoint contract: ultralytics YOLO
    # trains with 1e-3 (our default), torchvision convnets with 1e-5
    # (ResNet passes it) — a mismatch skews every channel whose running
    # variance is small, so imported weights would drift layer by layer.
    epsilon: float = 1e-3
    dtype: Dtype = jnp.bfloat16
    padding: Any = None
    act_int8: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        k = self.kernel
        # Explicit symmetric k//2 padding, NOT "SAME": identical for
        # stride 1, but at stride 2 on even inputs XLA's SAME pads
        # (0, 1) while every torch-trained checkpoint saw (1, 1) —
        # same output shape, different pixels sampled, so imported
        # weights would see shifted borders at all 5 down-samplings.
        pad = self.padding
        if pad is None:
            pad = ((k // 2, k // 2), (k // 2, k // 2))
        if self.act_int8:
            if train:
                raise NotImplementedError(
                    "act_int8 is a serving-path quantization; fine-tune "
                    "the fp variant and re-calibrate"
                )
            x = _Int8Conv(
                self.features, kernel=k, stride=self.stride, pad=pad,
                groups=self.groups, dtype=self.dtype, name="conv",
            )(x)
        else:
            x = nn.Conv(
                self.features,
                kernel_size=(k, k),
                strides=(self.stride, self.stride),
                padding=pad,
                feature_group_count=self.groups,
                use_bias=False,
                dtype=self.dtype,
                name="conv",
            )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.97,
            epsilon=self.epsilon,
            dtype=jnp.float32,
            name="bn",
        )(x.astype(jnp.float32))
        return ACT[self.act](x.astype(self.dtype))


def adaptive_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    """Global average pool [N, H, W, C] -> [N, C] in fp32 for stability."""
    return jnp.mean(x.astype(jnp.float32), axis=(1, 2))


def make_divisible(v: float, divisor: int = 8) -> int:
    """Channel rounding used by the mobile-net family width multiplier."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def round_depth(n: int, depth_multiple: float) -> int:
    """YOLO-family per-stage block-count scaling."""
    return max(1, round(n * depth_multiple))
