"""Shared building blocks for the model zoo.

The reference ships no models at all — it is plumbing that feeds raw BGR24
frames to external CPU clients (`/root/reference/README.md:5-27`). The five
model families here are the TPU inference plane that replaces that void
(BASELINE.json configs 1-5), built MXU-first:

- NHWC layout end to end (XLA's native conv layout on TPU).
- bfloat16 compute / float32 params ("mixed" policy): matmuls and convs hit
  the MXU at bf16, normalization statistics stay fp32.
- Static shapes only; every model is shape-polymorphic *at trace time* via
  its config, never at run time.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

# SiLU is the activation of the YOLO family; convnets here default to their
# canonical activations via explicit args.
ACT: dict[str, Callable] = {
    "relu": nn.relu,
    "relu6": lambda x: jnp.minimum(nn.relu(x), 6.0),
    "silu": nn.silu,
    "gelu": nn.gelu,
    "identity": lambda x: x,
}


class ConvBN(nn.Module):
    """Conv → BatchNorm → activation, the convnet workhorse.

    BatchNorm keeps fp32 statistics regardless of compute dtype; `train`
    toggles running-average use so the same module serves the inference
    plane (frozen stats) and fine-tuning (mutable `batch_stats`).
    """

    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    act: str = "silu"
    # BN epsilon is part of the checkpoint contract: ultralytics YOLO
    # trains with 1e-3 (our default), torchvision convnets with 1e-5
    # (ResNet passes it) — a mismatch skews every channel whose running
    # variance is small, so imported weights would drift layer by layer.
    epsilon: float = 1e-3
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        k = self.kernel
        x = nn.Conv(
            self.features,
            kernel_size=(k, k),
            strides=(self.stride, self.stride),
            # Explicit symmetric k//2 padding, NOT "SAME": identical for
            # stride 1, but at stride 2 on even inputs XLA's SAME pads
            # (0, 1) while every torch-trained checkpoint saw (1, 1) —
            # same output shape, different pixels sampled, so imported
            # weights would see shifted borders at all 5 down-samplings.
            padding=((k // 2, k // 2), (k // 2, k // 2)),
            feature_group_count=self.groups,
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.97,
            epsilon=self.epsilon,
            dtype=jnp.float32,
            name="bn",
        )(x.astype(jnp.float32))
        return ACT[self.act](x.astype(self.dtype))


def adaptive_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    """Global average pool [N, H, W, C] -> [N, C] in fp32 for stability."""
    return jnp.mean(x.astype(jnp.float32), axis=(1, 2))


def make_divisible(v: float, divisor: int = 8) -> int:
    """Channel rounding used by the mobile-net family width multiplier."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def round_depth(n: int, depth_multiple: float) -> int:
    """YOLO-family per-stage block-count scaling."""
    return max(1, round(n * depth_multiple))
