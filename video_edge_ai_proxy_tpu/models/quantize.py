"""Weight-only int8 quantization for the serving plane.

The reference has no models, so it has no quantization story (SURVEY.md
§2.2 — it ships raw frames to external CPU clients); an edge box that
serves models from device memory wants one. This is post-training,
weight-only, symmetric int8:

- every kernel (ndim >= 2) is stored as int8 with a float32 scale per
  output channel (max-abs / 127, the standard symmetric PTQ rule);
- 1-D leaves (biases, norm scales/statistics) stay exact — they are tiny
  and precision-critical;
- at serving time the weights are dequantized *inside* the jitted program
  (`int8 * scale -> bf16`), so HBM holds int8 (4x smaller than f32
  checkpoints, 2x smaller than bf16 residency) and XLA fuses the
  dequantize into each consumer. Compute stays bf16 on the MXU.

Round 15 adds an OPT-IN activation path for the detect family
(``engine.quantize: int8_act``): the model's convs run int8 x int8 on
the MXU's native int8 systolic mode (models/common.py ``_Int8Conv``),
which needs per-tensor input scales observed by a calibration pass —
:func:`calibrate_serving` below runs representative frames through the
model with the "quant" collection mutable and freezes the observed
max-abs ranges. Weight-only ``int8`` stays the calibration-free default
recommendation; ``int8_act`` is gated by the accuracy tolerance committed
in ``tools/bench_levers.py``.

`engine/runner.py` enables this via ``engine.quantize: int8`` in the
config. On-disk checkpoints deliberately stay full precision — the
canonical format every load path expects — so quantization is re-applied
at each warmup; only device/HBM residency shrinks. Note the consequence:
an engine running quantized can only save the int8-roundtripped values
(the exact weights are gone after warmup), so `save_checkpoint` warns
before overwriting a full-precision file.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class QuantizedTree:
    """A params pytree split into int8 payloads + their scales.

    ``q``: same structure as the source tree; quantized leaves are int8,
    skipped leaves are kept verbatim. ``scale``: same structure; f32
    per-output-channel scale arrays for quantized leaves, None markers
    (empty arrays) for skipped ones.
    """

    q: Any
    scale: Any


def _quantize_leaf(w: jnp.ndarray):
    """[..., out] kernel -> (int8 [..., out], f32 scale [out])."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=tuple(range(w.ndim - 1)))
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _should_quantize(w) -> bool:
    return hasattr(w, "ndim") and w.ndim >= 2 and w.size >= 1024


def quantize_tree(tree: Any) -> QuantizedTree:
    """Quantize every kernel-shaped leaf of a params tree (ndim >= 2 and at
    least 1024 elements — embeddings, conv and dense kernels); leave small
    or 1-D leaves (biases, norms, BN statistics) untouched."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    qs, scales = [], []
    for w in leaves:
        if _should_quantize(w):
            q, s = _quantize_leaf(jnp.asarray(w))
            qs.append(q)
            scales.append(s)
        else:
            qs.append(jnp.asarray(w))
            scales.append(jnp.zeros((0,), jnp.float32))   # marker: not quantized
    return QuantizedTree(
        q=jax.tree_util.tree_unflatten(treedef, qs),
        scale=jax.tree_util.tree_unflatten(treedef, scales),
    )


def dequantize_tree(qt: QuantizedTree, dtype=jnp.float32) -> Any:
    """Inverse of :func:`quantize_tree`; call INSIDE the jitted consumer so
    XLA fuses `int8 * scale` into each weight's first use and HBM keeps the
    int8 residency."""
    def deq(q, s):
        if q.dtype == jnp.int8 and s.size:
            return (q.astype(jnp.float32) * s).astype(dtype)
        return q

    return jax.tree_util.tree_map(deq, qt.q, qt.scale)


def quantized_nbytes(qt: QuantizedTree) -> int:
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(qt.q)) + sum(
        x.size * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(qt.scale))


def tree_nbytes(tree: Any) -> int:
    return sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(tree))


def calibrate_serving(model, spec, variables: Any, frame_batches) -> Any:
    """Calibrate the int8 activation path: observe per-conv input ranges.

    Runs each uint8 frame batch (``[B, H, W, 3]``, raw camera geometry)
    through the model's own serving preprocess + forward with the "quant"
    collection mutable, so every ``_Int8Conv`` (models/common.py) records
    the running max-abs of its input. The calibration forward computes in
    the fp dtype — outputs are the fp model's exactly — only the observed
    ranges are new. Returns ``variables`` with the frozen "quant"
    collection merged in, ready for the int8 serving graph.

    Detect-family only: the calibrated model must have been built with
    ``act_int8=True`` (otherwise there is nothing to observe and the
    returned tree simply gains an empty collection).
    """
    from ..ops.preprocess import preprocess_letterbox

    if spec.kind != "detect":
        raise ValueError(
            f"int8 activation calibration is detect-family only; "
            f"{spec.name!r} is kind={spec.kind!r}"
        )
    base = {k: v for k, v in variables.items() if k != "quant"}

    @jax.jit
    def _create(frames):
        x, _ = preprocess_letterbox(frames, spec.input_size)
        _, muts = model.apply(base, x, decode="serving", mutable=["quant"])
        return muts["quant"]

    @jax.jit
    def _observe(quant, frames):
        x, _ = preprocess_letterbox(frames, spec.input_size)
        _, muts = model.apply(
            {**base, "quant": quant}, x, decode="serving", mutable=["quant"]
        )
        return muts["quant"]

    it = iter(frame_batches)
    try:
        quant = _create(next(it))
    except StopIteration:
        raise ValueError("calibration needs at least one frame batch")
    for frames in it:
        quant = _observe(quant, frames)
    return {**base, "quant": quant}
