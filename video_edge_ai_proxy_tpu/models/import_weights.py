"""Torch-layout checkpoint importer: canonical state dicts → flax trees.

The reference feeds frames to client-owned models that arrive pre-trained
(`/root/reference/examples/opencv_display.py:19` — the client brings real
weights; the proxy never trains). The TPU inference plane must match that
capability: an operator with a published checkpoint converts it offline
(no network) and serves it. This module maps the three canonical
community layouts onto our flax module trees:

- ``yolov8n``/``yolov8s``/``tiny_yolov8`` ← ultralytics ``model.state_dict()``
  names (``model.0.conv.weight`` … ``model.22.cv3.2.2.bias``),
- ``resnet50``/``tiny_resnet`` ← torchvision names (``conv1.weight``,
  ``layer3.5.bn2.running_var``, ``fc.weight``),
- ``vit_b16``/``tiny_vit`` ← timm ViT names (``blocks.7.attn.qkv.weight``,
  ``patch_embed.proj.weight``, ``head.bias``).

Transforms applied (the whole reason a renamer isn't enough):
- conv kernels OIHW → HWIO,
- linear weights [out, in] → [in, out],
- BatchNorm weight/bias/running_mean/running_var →
  scale/bias + batch_stats mean/var.

Accounting is strict: every target leaf must be assigned exactly once and
every source tensor consumed (modulo an explicit ignore list, e.g.
ultralytics' fixed DFL arange conv and ``num_batches_tracked``), so a
layout drift fails loudly instead of serving half-imported weights.

Numerical parity prerequisites live in the models themselves: explicit
k//2 conv padding and per-family BN epsilon (``common.py::ConvBN``) —
``tests/test_import_weights.py`` proves output equality against torch
golden modules built in the source layouts.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["convert", "load_state_dict", "s2d_fold_kernel", "SUPPORTED"]

# source-key suffix -> (our leaf name, collection)
_BN_LEAF = {
    "weight": ("scale", "params"),
    "bias": ("bias", "params"),
    "running_mean": ("mean", "batch_stats"),
    "running_var": ("var", "batch_stats"),
}


def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a state dict from .npz / .safetensors / torch .pt|.pth into
    plain float32 numpy (imports are offline; fp32 is the interchange)."""
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: np.asarray(z[k], np.float32) for k in z.files}
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return {k: np.asarray(v, np.float32)
                for k, v in load_file(path).items()}
    # torch pickle (weights_only: never execute code from a checkpoint)
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    if not isinstance(obj, dict):
        raise ValueError(f"unsupported checkpoint object in {path!r}")
    # common wrappers: {'model': sd} / {'state_dict': sd}
    for wrapper in ("state_dict", "model"):
        if wrapper in obj and isinstance(obj[wrapper], dict):
            obj = obj[wrapper]
    return {
        k: np.asarray(v.detach().float().numpy() if hasattr(v, "detach")
                      else v, np.float32)
        for k, v in obj.items()
        if hasattr(v, "shape")
    }


def _strip_model_prefix(state: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """ultralytics nests the module list under 1-2 ``model.`` levels
    depending on how the dict was exported; normalize to bare indices."""
    while state and all(k.startswith("model.") for k in state):
        state = {k[len("model."):]: v for k, v in state.items()}
    return state


def _stem_pad_ok(model_cfg, have: tuple, want: tuple,
                 attr: str = "stem_pad_c", axis: int = 2) -> bool:
    """Is zero-padding a conv kernel ``have`` -> ``want`` along its
    input-channel ``axis`` sound for this model config? True only when
    the model really runs the channel-padded stem/patchify (``attr``
    non-zero, NOT the space-to-depth stem — its extra input planes carry
    real pixels) and the shapes differ solely by the missing padded
    input channels."""
    pad_c = getattr(model_cfg, attr, 0)
    if not pad_c or getattr(model_cfg, "stem", "classic") != "classic":
        return False
    return (
        len(have) == len(want) > axis
        and have[:axis] == want[:axis]
        and have[axis + 1:] == want[axis + 1:]
        and have[axis] < want[axis] == pad_c
    )


def s2d_fold_kernel(k: np.ndarray) -> np.ndarray:
    """Losslessly re-express a stride-2 3x3 stem kernel ``[3, 3, ci, co]``
    as the stride-1 2x2 kernel ``[2, 2, 4*ci, co]`` computing the SAME
    function on the space-to-depth plane (round 15 detect-stem lever).

    Derivation: classic output pixel p reads input rows ``2p-1+di`` for
    tap ``di in {0,1,2}`` (explicit (1,1) top padding). The s2d plane
    stores input row ``2r+a`` at s2d row r, block-offset a; so row
    ``2p-1+di`` lives at ``(r, a) = (p-1, 1)`` for di=0, ``(p, di-1)``
    otherwise. A 2x2 stride-1 conv with ((1,0),(1,0)) padding reads s2d
    rows ``p-1+u``, hence tap di lands at ``(u, a) = (0, 1)`` if di==0
    else ``(1, di-1)`` — same for columns. Channel slot ``(2a+b)*ci + c``
    matches ops/preprocess.space_to_depth's block flattening. The 2x2x4ci
    kernel has 16ci/9ci taps; the (u=0, a=0) and (v=0, b=0) slots are
    never read by the classic function and stay zero. Exact up to float
    summation order (same products, regrouped) — bf16-tolerance parity,
    verified by tests/test_stem_s2d.py."""
    kh, kw, ci, co = np.shape(k)
    if (kh, kw) != (3, 3):
        raise ValueError(f"s2d fold expects a 3x3 kernel, got {np.shape(k)}")
    k = np.asarray(k)
    out = np.zeros((2, 2, 4 * ci, co), k.dtype)
    for di in range(3):
        u, a = (0, 1) if di == 0 else (1, di - 1)
        for dj in range(3):
            v, b = (0, 1) if dj == 0 else (1, dj - 1)
            s = (2 * a + b) * ci
            out[u, v, s:s + ci] = k[di, dj]
    return out


def _s2d_fold_ok(model_cfg, have: tuple, want: tuple) -> bool:
    """Does ``have`` (a classic 3x3 stem kernel, possibly cpad-grown) fold
    into ``want`` (the target s2d 2x2 stem kernel) for this config? The
    target input depth is 4x the true channel count; a cpad-padded source
    (zero-input planes beyond channel want[2]//4) slices down losslessly
    first."""
    if getattr(model_cfg, "stem", "classic") != "s2d":
        return False
    return (
        len(have) == len(want) == 4
        and have[:2] == (3, 3) and want[:2] == (2, 2)
        and have[3] == want[3]
        and want[2] % 4 == 0 and have[2] >= want[2] // 4
    )


# Conv kernels the cpad levers grow, per family: (params path, config
# attr, kernel input-channel axis).
_PAD_KERNELS = (
    (("stem", "conv", "kernel"), "stem_pad_c", 2),      # ConvBN stems, HWIO
    (("patch_embed", "kernel"), "patch_pad_c", 2),      # ViT patchify, HWIO
    (("tubelet", "proj", "kernel"), "patch_pad_c", 3),  # VideoMAE, THWIO
)


def pad_stem_on_load(raw, template, model) -> dict:
    """Compat shim for checkpoints saved before a cpad lever
    (``stem_pad_c`` / ``patch_pad_c``) was adopted: zero-pad the
    stem/patchify conv kernel to the template's shape when (and only
    when) the model config says the extra input planes are zero-padding.
    Shared by the engine load path and tools/eval_detector — every
    ``load_msgpack`` consumer of imported checkpoints."""
    cfg = getattr(model, "cfg", None)
    for path, attr, axis in _PAD_KERNELS:
        try:
            node = raw["params"]
            tnode = template["params"]
            for p in path[:-1]:
                node = node[p]
                tnode = tnode[p]
            kern = node[path[-1]]
            want = np.shape(tnode[path[-1]])
        except (KeyError, TypeError):
            continue
        have = np.shape(kern)
        if have == want:
            continue
        if path[0] == "stem" and _s2d_fold_ok(cfg, have, want):
            # Classic checkpoint serving the s2d stem: slice off any cpad
            # zero-input planes, then fold 3x3/stride-2 -> 2x2/stride-1.
            node[path[-1]] = s2d_fold_kernel(
                np.asarray(kern)[:, :, : want[2] // 4, :]
            )
            from ..utils.logging import get_logger

            get_logger("models.import").info(
                "checkpoint stem kernel s2d-folded %s -> %s", have, want,
            )
            continue
        if not _stem_pad_ok(cfg, have, want, attr, axis):
            continue
        widths = [(0, 0)] * len(want)
        widths[axis] = (0, want[axis] - have[axis])
        node[path[-1]] = np.pad(np.asarray(kern), widths)
        # Loud trace: served weights now differ in shape from the on-disk
        # checkpoint; an operator debugging that must see why.
        from ..utils.logging import get_logger

        get_logger("models.import").info(
            "checkpoint %s kernel zero-padded %s -> %s (%s compat)",
            "/".join(path[:-1]), have, want, attr,
        )
    return raw


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    """torch OIHW -> flax HWIO."""
    return np.transpose(w, (2, 3, 1, 0))


def _dense_kernel(w: np.ndarray) -> np.ndarray:
    """torch [out, in] -> flax [in, out]."""
    return np.transpose(w)


# ---------------------------------------------------------------- yolo --

# our backbone/neck module name -> ultralytics module-list index
# (ultralytics/cfg/models/v8/yolov8.yaml order; 10/11/13/14/17/20 are
# parameter-free Upsample/Concat entries)
_YOLO_IDX = {
    "stem": 0, "down2": 1, "c2f_2": 2, "down3": 3, "c2f_3": 4,
    "down4": 5, "c2f_4": 6, "down5": 7, "c2f_5": 8, "sppf": 9,
    "neck_up4": 12, "neck_up3": 15, "neck_down4": 16, "neck_out4": 18,
    "neck_down5": 19, "neck_out5": 21,
}


def _yolo_key(path: Tuple[str, ...]) -> Tuple[str, Optional[Callable]]:
    """flax path (collection stripped) -> (ultralytics key, transform)."""
    mod, rest = path[0], path[1:]
    if mod == "detect":
        # box{l}_* = cv2.{l}.{0,1,2}, cls{l}_* = cv3.{l}.{0,1,2}
        head, rest = rest[0], rest[1:]
        branch = "cv2" if head.startswith("box") else "cv3"
        level = head[3]
        sub = head.split("_", 1)[1]          # cv1 | cv2 | out
        slot = {"cv1": "0", "cv2": "1", "out": "2"}[sub]
        prefix = f"22.{branch}.{level}.{slot}"
        if sub == "out":                      # plain conv w/ bias
            leaf = rest[0]
            if leaf == "kernel":
                return f"{prefix}.weight", _conv_kernel
            return f"{prefix}.bias", None
        return _convbn_leaf(prefix, rest)
    idx = _YOLO_IDX[mod]
    if mod.startswith("c2f") or mod.startswith("neck_up") or \
            mod.startswith("neck_out"):
        sub = rest[0]
        if sub.startswith("m"):               # bottleneck m{i}.cv{1,2}
            return _convbn_leaf(f"{idx}.m.{sub[1:]}.{rest[1]}", rest[2:])
        return _convbn_leaf(f"{idx}.{sub}", rest[1:])
    if mod == "sppf":
        return _convbn_leaf(f"{idx}.{rest[0]}", rest[1:])
    return _convbn_leaf(str(idx), rest)       # plain ConvBN stage


def _convbn_leaf(prefix: str,
                 rest: Tuple[str, ...]) -> Tuple[str, Optional[Callable]]:
    """(conv|bn, leaf) below a ConvBN — shared by every family."""
    sub, leaf = rest[0], rest[1]
    if sub == "conv":
        return f"{prefix}.conv.weight", _conv_kernel
    src = {"scale": "weight", "bias": "bias",
           "mean": "running_mean", "var": "running_var"}[leaf]
    return f"{prefix}.bn.{src}", None


# -------------------------------------------------------------- resnet --

def _resnet_key(path: Tuple[str, ...]) -> Tuple[str, Optional[Callable]]:
    mod, rest = path[0], path[1:]
    if mod == "stem":
        sub, leaf = rest
        if sub == "conv":
            return "conv1.weight", _conv_kernel
        src = {"scale": "weight", "bias": "bias",
               "mean": "running_mean", "var": "running_var"}[leaf]
        return f"bn1.{src}", None
    if mod == "classifier":
        if rest[0] == "kernel":
            return "fc.weight", _dense_kernel
        return "fc.bias", None
    # stage{si}_block{bi} -> layer{si+1}.{bi}
    stage, block = mod.split("_")
    prefix = f"layer{int(stage[5:]) + 1}.{int(block[5:])}"
    sub, conv_or_bn, leaf = rest
    if sub == "downsample":
        slot = "0" if conv_or_bn == "conv" else "1"
        if conv_or_bn == "conv":
            return f"{prefix}.downsample.0.weight", _conv_kernel
        src = {"scale": "weight", "bias": "bias",
               "mean": "running_mean", "var": "running_var"}[leaf]
        return f"{prefix}.downsample.{slot}.{src}", None
    # conv{j}: conv weight from .conv{j}.weight, bn from .bn{j}.*
    j = sub[4:]
    if conv_or_bn == "conv":
        return f"{prefix}.conv{j}.weight", _conv_kernel
    src = {"scale": "weight", "bias": "bias",
           "mean": "running_mean", "var": "running_var"}[leaf]
    return f"{prefix}.bn{j}.{src}", None


# ----------------------------------------------------------------- vit --

def _vit_key(path: Tuple[str, ...]) -> Tuple[str, Optional[Callable]]:
    mod, rest = path[0], path[1:]
    if mod == "cls_token":
        return "cls_token", None
    if mod == "pos_embed":
        return "pos_embed", None
    if mod == "patch_embed":
        if rest[0] == "kernel":
            return "patch_embed.proj.weight", _conv_kernel
        return "patch_embed.proj.bias", None
    if mod == "classifier":
        if rest[0] == "kernel":
            return "head.weight", _dense_kernel
        return "head.bias", None
    # encoder/block{i}/... and encoder/ln_final
    assert mod == "encoder", path
    sub, rest = rest[0], rest[1:]
    if sub == "ln_final":
        return f"norm.{_ln(rest[0])}", None
    i = int(sub[5:])
    part, rest = rest[0], rest[1:]
    if part in ("ln1", "ln2"):
        norm = "norm1" if part == "ln1" else "norm2"
        return f"blocks.{i}.{norm}.{_ln(rest[0])}", None
    if part == "attn":
        proj = {"qkv": "qkv", "out": "proj"}[rest[0]]
        if rest[1] == "kernel":
            return f"blocks.{i}.attn.{proj}.weight", _dense_kernel
        return f"blocks.{i}.attn.{proj}.bias", None
    assert part == "mlp", path
    fc = rest[0]
    if rest[1] == "kernel":
        return f"blocks.{i}.mlp.{fc}.weight", _dense_kernel
    return f"blocks.{i}.mlp.{fc}.bias", None


def _ln(leaf: str) -> str:
    return {"scale": "weight", "bias": "bias"}[leaf]


# ------------------------------------------------------------- drivers --

_FAMILIES: Dict[str, Callable] = {
    "yolov8n": _yolo_key, "yolov8s": _yolo_key, "tiny_yolov8": _yolo_key,
    "yolov8n_s2d": _yolo_key, "tiny_yolov8_s2d": _yolo_key,
    "resnet50": _resnet_key, "tiny_resnet": _resnet_key,
    "vit_b16": _vit_key, "tiny_vit": _vit_key,
}
SUPPORTED = sorted(_FAMILIES)

# source keys that have no target leaf and are expected to remain:
# num_batches_tracked (torch BN bookkeeping) and ultralytics' DFL conv,
# whose weight is the fixed arange(reg_max) our in-graph decode computes.
_IGNORABLE = ("num_batches_tracked", "dfl.conv.weight")


def convert(model_name: str, state: Dict[str, np.ndarray]):
    """state dict (canonical torch layout for ``model_name``) -> flax
    variables ``{"params": ..., "batch_stats": ...}`` ready for
    ``utils.checkpoint.save_msgpack`` / ``engine.checkpoint_path``.

    Raises ``KeyError``/``ValueError`` listing every unmapped target leaf,
    shape mismatch, or unconsumed source tensor."""
    import jax
    from flax import traverse_util

    from . import registry

    if model_name not in _FAMILIES:
        raise ValueError(
            f"no import mapping for {model_name!r}; supported: {SUPPORTED}"
        )
    key_fn = _FAMILIES[model_name]
    if key_fn is _yolo_key:
        state = _strip_model_prefix(state)

    model, template = registry.get(model_name).init_params(
        jax.random.PRNGKey(0)
    )
    model_cfg = getattr(model, "cfg", None)
    # ViT-family params are boxed in LogicallyPartitioned (sharding names);
    # the importer works on raw arrays — the engine re-boxes when it shards.
    from ..parallel.sharding import unbox

    flat = traverse_util.flatten_dict(unbox(template))

    out: Dict[Tuple[str, ...], np.ndarray] = {}
    consumed: set = set()
    problems: list = []
    for full_path, target in flat.items():
        # full_path = (collection, *module path, leaf)
        src_key, transform = key_fn(tuple(full_path[1:]))
        if src_key not in state:
            problems.append(f"missing source tensor {src_key!r} "
                            f"for {'/'.join(full_path)}")
            continue
        val = state[src_key]
        if transform is not None:
            val = transform(val)
        tgt = np.shape(target)
        if (full_path[-3:] == ("stem", "conv", "kernel")
                and _s2d_fold_ok(model_cfg, np.shape(val), tgt)):
            # s2d stem target: the stock 3x3 stride-2 stem kernel folds
            # losslessly into the 2x2 stride-1 layout (see
            # s2d_fold_kernel) — detection outputs stay numerically
            # equivalent, no retraining.
            val = s2d_fold_kernel(np.asarray(val)[:, :, : tgt[2] // 4, :])
        elif (full_path[-3:] == ("stem", "conv", "kernel")
                and _stem_pad_ok(model_cfg, np.shape(val), tgt)):
            # Channel-padded stem (YOLOv8Config.stem_pad_c): the model
            # zero-pads its INPUT planes beyond the source's 3 channels,
            # so zero weights there reproduce source outputs exactly —
            # the checkpoint-transferable lane-fill lever (BASELINE.md).
            # Gated on the TARGET CONFIG, not shape inference: the s2d
            # stem's extra input planes carry real pixels (a shape-only
            # pad would silently produce garbage there).
            val = np.pad(
                val,
                ((0, 0), (0, 0), (0, tgt[2] - np.shape(val)[2]), (0, 0)),
            )
        if np.shape(val) != np.shape(target):
            problems.append(
                f"shape mismatch for {'/'.join(full_path)}: source "
                f"{src_key!r} gives {np.shape(val)}, model wants "
                f"{np.shape(target)}"
            )
            continue
        out[full_path] = np.asarray(val, np.float32)
        consumed.add(src_key)
    leftovers = [
        k for k in state
        if k not in consumed and not k.endswith(_IGNORABLE)
    ]
    if leftovers:
        problems.append(
            f"{len(leftovers)} source tensors unconsumed (layout drift?): "
            + ", ".join(sorted(leftovers)[:8])
            + ("…" if len(leftovers) > 8 else "")
        )
    if problems:
        raise ValueError(
            f"import of {model_name!r} failed "
            f"({len(problems)} problems):\n- " + "\n- ".join(problems)
        )
    return traverse_util.unflatten_dict(out)
