"""MobileNetV2 classifier — BASELINE config 1 (single-stream classify path).

Standard inverted-residual architecture (Sandler et al. 2018) in NHWC bf16.
Depthwise convs map to XLA's grouped-conv path; the pointwise 1×1 convs are
the MXU work. The reference has no model here — config 1's job in the old
system was done by an external CPU client reading raw frames off the bus
(`/root/reference/examples/opencv_display.py:46-53`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.preprocess import pad_channels
from .common import ConvBN, Dtype, adaptive_avg_pool, make_divisible

# (expansion t, out channels c, repeats n, first stride s)
_MNV2_STAGES = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


@dataclass(frozen=True)
class MobileNetV2Config:
    num_classes: int = 1000
    width_mult: float = 1.0
    stages: Sequence[tuple] = field(default=_MNV2_STAGES)
    stem_features: int = 32
    head_features: int = 1280
    # Lane-fill channel padding for the stem conv (ops.preprocess
    # .pad_channels; cpad lever, LEVERS_r05). Zero input planes keep
    # outputs identical; import_weights zero-pads checkpoints. 0 = off.
    stem_pad_c: int = 0


def tiny_mobilenet_v2_config(num_classes: int = 10) -> MobileNetV2Config:
    """Small config for CPU tests: 2 stages, thin channels."""
    return MobileNetV2Config(
        num_classes=num_classes,
        stages=((1, 16, 1, 1), (6, 24, 2, 2)),
        stem_features=16,
        head_features=64,
    )


class InvertedResidual(nn.Module):
    features: int
    stride: int
    expand: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        in_ch = x.shape[-1]
        h = x
        hidden = in_ch * self.expand
        if self.expand != 1:
            h = ConvBN(hidden, kernel=1, act="relu6", dtype=self.dtype, name="expand")(h, train)
        h = ConvBN(
            hidden, kernel=3, stride=self.stride, groups=hidden,
            act="relu6", dtype=self.dtype, name="depthwise",
        )(h, train)
        h = ConvBN(self.features, kernel=1, act="identity", dtype=self.dtype, name="project")(h, train)
        if self.stride == 1 and in_ch == self.features:
            h = h + x
        return h


class MobileNetV2(nn.Module):
    cfg: MobileNetV2Config
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        c = self.cfg
        x = x.astype(self.dtype)
        x = pad_channels(x, c.stem_pad_c)
        x = ConvBN(
            make_divisible(c.stem_features * c.width_mult), stride=2,
            act="relu6", dtype=self.dtype, name="stem",
        )(x, train)
        for si, (t, ch, n, s) in enumerate(c.stages):
            out_ch = make_divisible(ch * c.width_mult)
            for bi in range(n):
                x = InvertedResidual(
                    out_ch, stride=s if bi == 0 else 1, expand=t,
                    dtype=self.dtype, name=f"stage{si}_block{bi}",
                )(x, train)
        head = make_divisible(c.head_features * max(1.0, c.width_mult))
        x = ConvBN(head, kernel=1, act="relu6", dtype=self.dtype, name="head")(x, train)
        x = adaptive_avg_pool(x)
        return nn.Dense(c.num_classes, dtype=jnp.float32, name="classifier")(x)
