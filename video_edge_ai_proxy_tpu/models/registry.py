"""Model registry: one name → everything the inference engine needs.

The engine (`engine/runner.py`) is model-agnostic; a `ModelSpec` bundles the
module, its input geometry, which device-side preprocess to use, and how to
turn raw outputs into wire-ready results. The five registered defaults are
the five BASELINE.json configs; registering a new family is one entry, not
an engine change.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .blob import BlobGauge, BlobGaugeConfig
from .mobilenet_v2 import MobileNetV2, MobileNetV2Config, tiny_mobilenet_v2_config
from .resnet import ResNet, ResNetConfig, tiny_resnet_config
from .videomae import VideoMAE, VideoMAEConfig, tiny_videomae_config
from .vit import ViT, ViTConfig, tiny_vit_config
from .yolov8 import YOLOv8, tiny_yolov8_config, yolov8n_config, yolov8s_config


@dataclass(frozen=True)
class ModelSpec:
    name: str
    build: Callable[[], Any]              # () -> nn.Module
    input_size: int                       # square side the model consumes
    preprocess: str                       # "classify" | "letterbox" | "clip"
    kind: str                             # "classify" | "detect" | "embed" | "video"
    clip_len: int = 0                     # >0 for video models
    description: str = ""

    def init_params(self, rng: Optional[jax.Array] = None, batch: int = 1):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        model = self.build()
        x = jnp.zeros(self.example_shape(batch), jnp.bfloat16)
        # jit the init: eager per-op dispatch costs seconds of compile time
        # per op on some backends; one fused compile is orders faster.
        return model, jax.jit(model.init)(rng, x)

    def example_shape(self, batch: int = 1) -> Tuple[int, ...]:
        s = self.input_size
        if self.clip_len:
            return (batch, self.clip_len, s, s, 3)
        return (batch, s, s, 3)


_REGISTRY: Dict[str, ModelSpec] = {}


def register(spec: ModelSpec) -> ModelSpec:
    _REGISTRY[spec.name] = spec
    return spec


def get(name: str) -> ModelSpec:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model '{name}'; registered: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name]


def names() -> list:
    return sorted(_REGISTRY)


# --- BASELINE.json configs 1-5 -------------------------------------------

register(ModelSpec(
    "mobilenet_v2", lambda: MobileNetV2(MobileNetV2Config()),
    input_size=224, preprocess="classify", kind="classify",
    description="config 1: single-stream frame classification",
))
register(ModelSpec(
    "yolov8n", lambda: YOLOv8(yolov8n_config()),
    input_size=640, preprocess="letterbox", kind="detect",
    description="config 2 + north star: batched detection",
))
register(ModelSpec(
    "yolov8n_s2d", lambda: YOLOv8(
        dataclasses.replace(yolov8n_config(), stem="s2d")
    ),
    input_size=640, preprocess="letterbox", kind="detect",
    description="north-star variant: space-to-depth stem (round 15) — "
                "2x2 stride-1 stem on the folded 320²x12 plane; stock "
                "yolov8n checkpoints transfer via the lossless kernel "
                "fold (models/import_weights.py s2d_fold_kernel), "
                "detections numerically equivalent",
))
register(ModelSpec(
    "yolov8s", lambda: YOLOv8(yolov8s_config()),
    input_size=640, preprocess="letterbox", kind="detect",
    description="small-variant detection",
))
register(ModelSpec(
    "resnet50", lambda: ResNet(ResNetConfig()),
    input_size=224, preprocess="classify", kind="embed",
    description="config 3: 16-stream re-ID feature extraction",
))
register(ModelSpec(
    "vit_b16", lambda: ViT(ViTConfig()),
    input_size=224, preprocess="classify", kind="classify",
    description="config 4: 32-stream frame tagging",
))
register(ModelSpec(
    "videomae_b", lambda: VideoMAE(VideoMAEConfig()),
    input_size=224, preprocess="clip", kind="video", clip_len=8,
    description="config 5: 8-frame clip action recognition",
))
register(ModelSpec(
    "videomae_b_long", lambda: VideoMAE(VideoMAEConfig(num_frames=64)),
    input_size=224, preprocess="clip", kind="video", clip_len=64,
    description="long-context clips: 64 frames -> 6272 tokens, attention "
                "auto-dispatches to the Pallas flash kernel",
))

# --- diagnostic gauges ----------------------------------------------------

register(ModelSpec(
    "blob_gauge", lambda: BlobGauge(BlobGaugeConfig()),
    input_size=640, preprocess="letterbox", kind="detect",
    description="detect-identity measurement gauge (models/blob.py): "
                "exact pixel bboxes of color-keyed synthetic blobs; the "
                "ROI round-trip gate (tools/roi_smoke.py) serves it to "
                "prove pack->detect->scatter-back preserves geometry",
))
register(ModelSpec(
    "tiny_blob_gauge", lambda: BlobGauge(BlobGaugeConfig()),
    input_size=64, preprocess="letterbox", kind="detect",
    description="CPU/CI twin of blob_gauge (tests/test_roi.py)",
))

# --- tiny twins (tests / CI on CPU) --------------------------------------

register(ModelSpec(
    "tiny_mobilenet_v2", lambda: MobileNetV2(tiny_mobilenet_v2_config()),
    input_size=32, preprocess="classify", kind="classify",
))
register(ModelSpec(
    "tiny_yolov8", lambda: YOLOv8(tiny_yolov8_config()),
    input_size=64, preprocess="letterbox", kind="detect",
))
register(ModelSpec(
    "tiny_yolov8_s2d", lambda: YOLOv8(
        dataclasses.replace(tiny_yolov8_config(), stem="s2d")
    ),
    input_size=64, preprocess="letterbox", kind="detect",
    description="CPU/CI twin of yolov8n_s2d (tests/test_stem_s2d.py, "
                "tools/stem_smoke.py)",
))
register(ModelSpec(
    "tiny_resnet", lambda: ResNet(tiny_resnet_config()),
    input_size=32, preprocess="classify", kind="embed",
))
register(ModelSpec(
    "tiny_vit", lambda: ViT(tiny_vit_config()),
    input_size=32, preprocess="classify", kind="classify",
))
register(ModelSpec(
    "tiny_videomae", lambda: VideoMAE(tiny_videomae_config()),
    input_size=32, preprocess="clip", kind="video", clip_len=4,
))
