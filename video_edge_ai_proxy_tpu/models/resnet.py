"""ResNet-50 feature extractor / classifier — BASELINE config 3 (16-stream
re-ID features).

Bottleneck-v1.5 (stride on the 3×3) in NHWC bf16. `features_only=True` at
call time returns the pooled 2048-d embedding instead of logits — config 3
consumes embeddings, config 1-style classification consumes logits; one set
of params serves both.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.preprocess import pad_channels
from .common import ConvBN, Dtype, adaptive_avg_pool

# torchvision ResNets train with BN eps 1e-5; matching it is required for
# imported checkpoints to reproduce source outputs (see ConvBN.epsilon).
_BN_EPS = 1e-5


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    # Lane-fill channel padding for the stem conv (see ops.preprocess
    # .pad_channels and the yolov8 cpad8 lever, LEVERS_r05): the stem
    # kernel grows [7,7,3,W]->[7,7,pad,W], extra input planes are zeros,
    # outputs identical; import_weights zero-pads checkpoints. 0 = off.
    stem_pad_c: int = 0


def tiny_resnet_config(num_classes: int = 10) -> ResNetConfig:
    return ResNetConfig(num_classes=num_classes, stage_sizes=(1, 1), width=16)


class Bottleneck(nn.Module):
    features: int      # inner width; output is 4×
    stride: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        out_ch = self.features * 4
        residual = x
        h = ConvBN(self.features, kernel=1, act="relu", epsilon=_BN_EPS,
                   dtype=self.dtype, name="conv1")(x, train)
        h = ConvBN(self.features, kernel=3, stride=self.stride, act="relu",
                   epsilon=_BN_EPS, dtype=self.dtype, name="conv2")(h, train)
        h = ConvBN(out_ch, kernel=1, act="identity", epsilon=_BN_EPS,
                   dtype=self.dtype, name="conv3")(h, train)
        if residual.shape[-1] != out_ch or self.stride != 1:
            residual = ConvBN(
                out_ch, kernel=1, stride=self.stride, act="identity",
                epsilon=_BN_EPS, dtype=self.dtype, name="downsample",
            )(x, train)
        return nn.relu(h + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, train: bool = False, features_only: bool = False
    ) -> jnp.ndarray:
        c = self.cfg
        x = x.astype(self.dtype)
        x = pad_channels(x, c.stem_pad_c)
        x = ConvBN(c.width, kernel=7, stride=2, act="relu", epsilon=_BN_EPS,
                   dtype=self.dtype, name="stem")(x, train)
        # Explicit (1, 1) padding = torch's MaxPool2d(3, 2, padding=1);
        # "SAME" would pad (0, 1) on even inputs (see ConvBN note).
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for si, n_blocks in enumerate(c.stage_sizes):
            feats = c.width * (2 ** si)
            for bi in range(n_blocks):
                x = Bottleneck(
                    feats, stride=2 if (bi == 0 and si > 0) else 1,
                    dtype=self.dtype, name=f"stage{si}_block{bi}",
                )(x, train)
        x = adaptive_avg_pool(x)
        if features_only:
            return x
        return nn.Dense(c.num_classes, dtype=jnp.float32, name="classifier")(x)
