"""YOLOv8 detector — BASELINE configs 2 & north star (16×1080p, ≥1000 fps).

Anchor-free YOLOv8 (CSP backbone with C2f blocks, SPPF, PAN-FPN neck,
decoupled DFL head) in NHWC bf16. Everything through box decode is one
jitted graph with static shapes; NMS lives in `ops/nms.py` (Pallas on TPU).

TPU notes:
- All three head levels are decoded in-graph and concatenated to the flat
  [B, A, ...] layout the NMS op consumes — no host-side glue between
  forward and postprocess.
- DFL decode (softmax-expectation over 16 bins) is a [*, 4, 16] × [16]
  contraction — trivially fused by XLA.
- The nano scaling (depth 0.33 / width 0.25) is a config, not a fork:
  s/m/l/x are the same module tree.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.boxes import dist_to_bbox
from ..ops.preprocess import pad_channels
from .common import ConvBN, Dtype, make_divisible, round_depth


@dataclass(frozen=True)
class YOLOv8Config:
    num_classes: int = 80
    depth_mult: float = 0.33      # n
    width_mult: float = 0.25      # n
    max_channels: int = 1024
    reg_max: int = 16             # DFL bins
    strides: Sequence[int] = (8, 16, 32)
    # Stem variant. "classic": stride-2 3x3 conv on [B,S,S,3] (the stock
    # architecture, the checkpoint contract). "s2d": space-to-depth stem
    # (round 15) — fold 2x2 spatial blocks into channels (3 -> 12), then a
    # stride-1 2x2 conv with asymmetric ((1,0),(1,0)) padding on the 320²
    # plane. Same output geometry, 4x the input lanes for the MXU, and —
    # unlike the rejected round-5 s2d experiment (a fresh 3x3 stem that
    # broke checkpoints and lost 0.85x) — EXACTLY the same function: every
    # classic stem kernel folds losslessly into the 2x2 layout
    # (models/import_weights.py s2d_fold_kernel), so stock checkpoints
    # transfer and detections stay numerically equivalent.
    stem: str = "classic"
    # int8 activation path (round 15): every ConvBN except the stem runs
    # int8 x int8 against calibrated per-tensor input scales and in-graph
    # per-output-channel weight scales (models/common.py _Int8Conv). The
    # param tree is identical to fp, so checkpoints serve either way after
    # a calibration pass (models/quantize.py calibrate_serving). Serving
    # only; head 1x1 out-convs and DFL/NMS decode stay fp32.
    act_int8: bool = False
    # Channel-padded stem (the one lane-fill lever that DOES transfer
    # checkpoints): zero-pad the input from 3 to this many channels before
    # the stem conv, whose kernel grows [3,3,3,C]->[3,3,pad,C]. The extra
    # input planes are zeros, so ANY weights in the extra kernel channels
    # produce identical outputs — an imported checkpoint just zero-pads
    # its stem kernel (models/import_weights.py). 0 = off.
    stem_pad_c: int = 0

    def ch(self, c: int) -> int:
        return make_divisible(min(c, self.max_channels) * self.width_mult)

    def depth(self, n: int) -> int:
        return round_depth(n, self.depth_mult)


def yolov8n_config(num_classes: int = 80) -> YOLOv8Config:
    # stem_pad_c=8: measured +3.2% end-to-end at the north-star shape
    # (two uncontended runs, 12.35/12.36 vs 12.74 ms — BASELINE.md levers
    # table), reproducible, and checkpoint-transferable (the importer
    # zero-pads the stem kernel). The round-5 s2d experiment lost 0.85x
    # AND broke checkpoints; the round-15 stem="s2d" is a different,
    # lossless fold — see YOLOv8Config.stem. pad_channels no-ops when the
    # input already has >= pad channels, so stem_pad_c=8 is inert under
    # the 12-channel s2d plane.
    return YOLOv8Config(num_classes=num_classes, stem_pad_c=8)


def yolov8s_config(num_classes: int = 80) -> YOLOv8Config:
    return YOLOv8Config(num_classes=num_classes, depth_mult=0.33,
                        width_mult=0.5, stem_pad_c=8)


def tiny_yolov8_config(num_classes: int = 4) -> YOLOv8Config:
    """Test config: 1/8 width, input 64² -> 84 anchors."""
    return YOLOv8Config(num_classes=num_classes, depth_mult=0.33, width_mult=0.125)


class Bottleneck(nn.Module):
    features: int
    shortcut: bool = True
    dtype: Dtype = jnp.bfloat16
    act_int8: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        q = self.act_int8
        h = ConvBN(self.features, kernel=3, dtype=self.dtype, act_int8=q,
                   name="cv1")(x, train)
        h = ConvBN(self.features, kernel=3, dtype=self.dtype, act_int8=q,
                   name="cv2")(h, train)
        if self.shortcut and x.shape[-1] == self.features:
            h = h + x
        return h


class C2f(nn.Module):
    """Cross-stage partial block: split, n bottlenecks, dense concat."""

    features: int
    n: int = 1
    shortcut: bool = True
    dtype: Dtype = jnp.bfloat16
    act_int8: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        hidden = self.features // 2
        q = self.act_int8
        h = ConvBN(2 * hidden, kernel=1, dtype=self.dtype, act_int8=q,
                   name="cv1")(x, train)
        parts = [h[..., :hidden], h[..., hidden:]]
        for i in range(self.n):
            parts.append(
                Bottleneck(hidden, self.shortcut, self.dtype, q, name=f"m{i}")(
                    parts[-1], train
                )
            )
        return ConvBN(self.features, kernel=1, dtype=self.dtype, act_int8=q,
                      name="cv2")(jnp.concatenate(parts, axis=-1), train)


class SPPF(nn.Module):
    """Spatial pyramid pooling (fast): 3 chained 5×5 maxpools, concat."""

    features: int
    dtype: Dtype = jnp.bfloat16
    act_int8: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        hidden = self.features // 2
        h = ConvBN(hidden, kernel=1, dtype=self.dtype, act_int8=self.act_int8,
                   name="cv1")(x, train)
        pools = [h]
        for _ in range(3):
            pools.append(nn.max_pool(pools[-1], (5, 5), strides=(1, 1), padding="SAME"))
        return ConvBN(self.features, kernel=1, dtype=self.dtype,
                      act_int8=self.act_int8, name="cv2")(
            jnp.concatenate(pools, axis=-1), train
        )


def _upsample2(x: jnp.ndarray) -> jnp.ndarray:
    """Nearest ×2 — pure reshape/broadcast, no gather."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, 2 * h, 2 * w, c)


class DetectHead(nn.Module):
    """Decoupled per-level head: box branch (4·reg_max DFL logits) and class
    branch (num_classes logits)."""

    cfg: YOLOv8Config
    level_ch: Sequence[int]
    dtype: Dtype = jnp.bfloat16
    act_int8: bool = False

    @nn.compact
    def __call__(self, feats, train: bool = False):
        import math

        c = self.cfg
        c_box = max(16, self.level_ch[0] // 4, c.reg_max * 4)
        c_cls = max(self.level_ch[0], min(c.num_classes, 100))
        q = self.act_int8
        outs = []
        for i, f in enumerate(feats):
            box = ConvBN(c_box, kernel=3, dtype=self.dtype, act_int8=q,
                         name=f"box{i}_cv1")(f, train)
            box = ConvBN(c_box, kernel=3, dtype=self.dtype, act_int8=q,
                         name=f"box{i}_cv2")(box, train)
            # DFL bin prior: decay the bias over distance bins so the
            # initial expected ltrb distance is ~1.5 strides instead of
            # the uniform-softmax 7.5. Random-init boxes then start near
            # object scale, so first-assignment IoUs (the TAL target
            # weights) are O(0.1) rather than O(0.001) — without this,
            # from-scratch fine-tunes spend hundreds of steps in a
            # background-suppression-only regime before any positive
            # signal emerges. Imported checkpoints overwrite it.
            dfl_prior = jnp.tile(
                -0.5 * jnp.arange(c.reg_max, dtype=jnp.float32), 4)
            box = nn.Conv(4 * c.reg_max, (1, 1), dtype=jnp.float32, name=f"box{i}_out",
                          bias_init=lambda *_a, v=dfl_prior: v)(
                box.astype(jnp.float32)
            )
            # Prior bias (the ultralytics Detect.bias_init scheme): start
            # class probabilities at roughly 5 objects per 640-px image
            # per level instead of sigmoid(0)=0.5 on every anchor. From
            # scratch, a zero bias makes the initial loss almost entirely
            # background BCE — the fastest descent direction is "push all
            # logits down", which outruns the positives and collapses the
            # head (see detect_loss.assign's relative-floor note).
            # Imported checkpoints overwrite these values.
            prior = math.log(5 / c.num_classes / (640 / c.strides[i]) ** 2)
            cls = ConvBN(c_cls, kernel=3, dtype=self.dtype, act_int8=q,
                         name=f"cls{i}_cv1")(f, train)
            cls = ConvBN(c_cls, kernel=3, dtype=self.dtype, act_int8=q,
                         name=f"cls{i}_cv2")(cls, train)
            cls = nn.Conv(c.num_classes, (1, 1), dtype=jnp.float32, name=f"cls{i}_out",
                          bias_init=nn.initializers.constant(prior))(
                cls.astype(jnp.float32)
            )
            outs.append((box, cls))
        return outs


def _anchor_points(h: int, w: int, stride: int):
    """Cell-center anchor points in input pixels, [h*w, 2] (x, y)."""
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) * stride
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) * stride
    gx, gy = jnp.meshgrid(xs, ys)
    return jnp.stack([gx.reshape(-1), gy.reshape(-1)], axis=-1)


def decode_level(box_logits, stride: int, reg_max: int):
    """DFL decode one level: [B, h, w, 4*reg_max] -> xyxy [B, h*w, 4] px."""
    b, h, w, _ = box_logits.shape
    logits = box_logits.reshape(b, h * w, 4, reg_max)
    probs = nn.softmax(logits, axis=-1)
    bins = jnp.arange(reg_max, dtype=jnp.float32)
    dist = jnp.einsum("bafr,r->baf", probs, bins) * stride   # ltrb, px
    return dist_to_bbox(dist, _anchor_points(h, w, stride))


class YOLOv8(nn.Module):
    cfg: YOLOv8Config
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False, decode=True):
        """[B, S, S, 3] normalized RGB -> head output, by ``decode`` mode:

        - ``True``: decoded ``(boxes [B,A,4], scores [B,A,C])``, scores are
          per-class sigmoid probabilities (the stable public contract).
        - ``False``: raw per-level ``(box_logits, cls_logits)`` pairs (the
          detection-loss path).
        - ``"serving"``: ``(boxes [B,A,4], max_logit [B,A], cls_ids [B,A])``
          — class reduction in logit space. Sigmoid is monotone, so
          ``sigmoid(max_logit)`` equals the decode=True best-class score and
          ``cls_ids`` its argmax, but the sigmoid over all A×C logits never
          happens; the serving engine applies it to the A winners only.
          Every ``kind="detect"`` registry model supports this mode — it is
          the contract `engine/runner.py` serves detectors through.
        """
        c = self.cfg
        d, ch = c.depth, c.ch
        q = c.act_int8
        x = x.astype(self.dtype)

        # Backbone
        if c.stem == "s2d":
            # Accepts either the raw [B, S, S, 3] plane (folds it here) or
            # the pre-folded [B, S/2, S/2, 12] plane straight out of
            # ops/preprocess.preprocess_letterbox_fused.
            if x.shape[-1] == 3:
                from ..ops.preprocess import space_to_depth

                x = space_to_depth(x)
            x = pad_channels(x, c.stem_pad_c)
            # Stride-1 2x2 conv, pad ((1,0),(1,0)): the lossless fold of
            # the classic stride-2 3x3 conv onto the s2d plane — output
            # pixel p of the classic stem reads input rows 2p-1..2p+1,
            # which land in s2d rows p-1 (offset 1) and p (offsets 0/1);
            # the leading pad supplies the p-1 = -1 zero row exactly like
            # the classic conv's top padding. Taps the classic kernel
            # never reads are zero in the folded kernel
            # (models/import_weights.py s2d_fold_kernel). Kept fp even
            # under act_int8 (first-layer exemption, standard PTQ rule).
            x = ConvBN(ch(64), kernel=2, stride=1, padding=((1, 0), (1, 0)),
                       dtype=self.dtype, name="stem")(x, train)              # P1
        else:
            # Lane-fill: zero input planes cost bandwidth but let XLA
            # tile the stem conv with full input-channel vectors.
            x = pad_channels(x, c.stem_pad_c)
            x = ConvBN(ch(64), stride=2, dtype=self.dtype, name="stem")(x, train)   # P1
        x = ConvBN(ch(128), stride=2, dtype=self.dtype, act_int8=q,
                   name="down2")(x, train)                                   # P2
        x = C2f(ch(128), d(3), True, self.dtype, q, name="c2f_2")(x, train)
        x = ConvBN(ch(256), stride=2, dtype=self.dtype, act_int8=q,
                   name="down3")(x, train)                                   # P3
        p3 = C2f(ch(256), d(6), True, self.dtype, q, name="c2f_3")(x, train)
        x = ConvBN(ch(512), stride=2, dtype=self.dtype, act_int8=q,
                   name="down4")(p3, train)                                  # P4
        p4 = C2f(ch(512), d(6), True, self.dtype, q, name="c2f_4")(x, train)
        x = ConvBN(ch(1024), stride=2, dtype=self.dtype, act_int8=q,
                   name="down5")(p4, train)                                  # P5
        x = C2f(ch(1024), d(3), True, self.dtype, q, name="c2f_5")(x, train)
        p5 = SPPF(ch(1024), self.dtype, q, name="sppf")(x, train)

        # PAN-FPN neck
        x = jnp.concatenate([_upsample2(p5), p4], axis=-1)
        n4 = C2f(ch(512), d(3), False, self.dtype, q, name="neck_up4")(x, train)
        x = jnp.concatenate([_upsample2(n4), p3], axis=-1)
        n3 = C2f(ch(256), d(3), False, self.dtype, q, name="neck_up3")(x, train)  # out P3
        x = ConvBN(ch(256), stride=2, dtype=self.dtype, act_int8=q,
                   name="neck_down4")(n3, train)
        o4 = C2f(ch(512), d(3), False, self.dtype, q, name="neck_out4")(
            jnp.concatenate([x, n4], axis=-1), train
        )                                                                            # out P4
        x = ConvBN(ch(512), stride=2, dtype=self.dtype, act_int8=q,
                   name="neck_down5")(o4, train)
        o5 = C2f(ch(1024), d(3), False, self.dtype, q, name="neck_out5")(
            jnp.concatenate([x, p5], axis=-1), train
        )                                                                            # out P5

        levels = [n3, o4, o5]
        head_out = DetectHead(
            c, [f.shape[-1] for f in levels], self.dtype, q, name="detect"
        )(levels, train)

        if decode is False:
            return head_out

        boxes, cls_flat = [], []
        for (box_l, cls_l), stride in zip(head_out, c.strides):
            boxes.append(decode_level(box_l, stride, c.reg_max))
            b_, h_, w_, _ = cls_l.shape
            cls_flat.append(cls_l.reshape(b_, h_ * w_, c.num_classes))
        boxes = jnp.concatenate(boxes, axis=1)
        cls_flat = jnp.concatenate(cls_flat, axis=1)
        if decode == "serving":
            return (boxes, cls_flat.max(axis=-1),
                    cls_flat.argmax(axis=-1).astype(jnp.int32))
        return boxes, nn.sigmoid(cls_flat)
