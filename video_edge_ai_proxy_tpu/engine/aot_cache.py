"""Persistent AOT prewarm cache (r19): spawn-time cold start killer.

The XLA persistent compile cache (``EngineConfig.compile_cache_dir``,
wired in ``runner.warmup``) already makes a *restart* cheap — but a
freshly *spawned* fleet member still has to know WHICH programs to
compile before taking traffic, and ROUTER_r01 had to reset its
conservation ledger post-warmup because a member compiling in-tick
overwrites frames (latest-frame-wins) for tens of seconds. This module
adds the missing half: a versioned **prewarm manifest** JSON living
next to the XLA cache payload that records the program set — one entry
per ``(model, stem, geometry, bucket)`` serving step a member has ever
compiled — so a spawned member pointed at the shared cache dir replays
the whole set at boot (every compile a cache hit) and serves its first
migrated frame within one router scrape interval (ROADMAP item 4).

Fallback contract: a manifest whose ``version`` or ``jaxlib`` stamp
does not match the running process is *ignored* (clean compile, fresh
manifest on the next record) — never an exception. The XLA cache keys
include the jaxlib/XLA fingerprint on their own; the manifest stamp
exists so we never burn boot time replaying a program list whose cache
entries are guaranteed misses.

Stdlib-only except for :func:`configure` (which touches jax.config and
is only called from the engine warmup path); the manifest helpers are
safe to import from control-plane code.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from ..utils.logging import get_logger

log = get_logger("engine.aot_cache")

MANIFEST_VERSION = 1
MANIFEST_NAME = "prewarm_manifest.json"

# One process-wide lock: several engines in one test process may share a
# cache dir; cross-process writers are covered by the atomic rename.
_manifest_lock = threading.Lock()


def _jaxlib_stamp() -> str:
    """Version stamp binding a manifest to the compiler that filled the
    XLA cache next to it. jax import lives inside the function per the
    serving-path convention (manifest readers stay backend-free until
    someone actually asks for the stamp)."""
    try:
        import jaxlib

        return str(jaxlib.version.__version__)
    except Exception:  # pragma: no cover - jaxlib always ships jax
        return "unknown"


def manifest_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, MANIFEST_NAME)


def configure(cache_dir: str) -> bool:
    """Point the jax persistent compilation cache at ``cache_dir``.

    Same wiring the plain ``compile_cache_dir`` path uses (lower the
    persistence threshold only when still at the jax default, reset the
    cache object so the directory binds even if something compiled
    first); returns False instead of raising when jax refuses.
    """
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        if jax.config.jax_persistent_cache_min_compile_time_secs == 1.0:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5
            )
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:
            log.warning(
                "could not reset the XLA compilation cache; programs "
                "compiled before warmup may persist elsewhere",
                exc_info=True,
            )
        return True
    except Exception:
        log.exception("AOT cache configure failed; continuing uncached")
        return False


def mesh_spec(mesh) -> List[list]:
    """Canonical manifest form of a device mesh: sorted ``[axis, size]``
    pairs for axes of size > 1; empty = single-chip. Accepts None, a
    ``jax.sharding.Mesh`` (its ``.shape`` mapping), or an already-built
    pair list — stdlib-only either way, so manifest readers stay
    backend-free."""
    if mesh is None:
        return []
    shape = getattr(mesh, "shape", None)
    if shape is not None and hasattr(shape, "items"):
        pairs = shape.items()
    else:
        pairs = mesh
    return sorted([str(a), int(n)] for a, n in pairs if int(n) > 1)


def _mesh_key(prog: Dict[str, Any]) -> tuple:
    return tuple((a, n) for a, n in mesh_spec(prog.get("mesh")))


def _program_key(prog: Dict[str, Any]) -> tuple:
    return (
        str(prog.get("model") or ""),
        str(prog.get("stem") or "classic"),
        int(prog.get("h", 0)),
        int(prog.get("w", 0)),
        int(prog.get("bucket", 0)),
        # r17 mesh-native serving: sharded and single-chip compiles of
        # the same geometry are distinct programs. Pre-r17 manifests
        # simply lack the key (= single-chip), so they stay readable.
        _mesh_key(prog),
    )


def load_manifest(cache_dir: str) -> Optional[List[Dict[str, Any]]]:
    """Read the prewarm manifest; None = nothing usable (missing,
    unparseable, or version/jaxlib mismatch — all of which mean "clean
    compile", never a crash)."""
    path = manifest_path(cache_dir)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        log.warning("unreadable prewarm manifest %s; ignoring", path,
                    exc_info=True)
        return None
    if not isinstance(data, dict):
        log.warning("prewarm manifest %s is not a mapping; ignoring", path)
        return None
    if data.get("version") != MANIFEST_VERSION:
        log.warning(
            "prewarm manifest %s version %r != %d; clean compile",
            path, data.get("version"), MANIFEST_VERSION,
        )
        return None
    stamp = _jaxlib_stamp()
    if data.get("jaxlib") != stamp:
        log.warning(
            "prewarm manifest %s built under jaxlib %r, running %r; "
            "clean compile", path, data.get("jaxlib"), stamp,
        )
        return None
    programs = data.get("programs")
    if not isinstance(programs, list):
        return None
    out: List[Dict[str, Any]] = []
    seen = set()
    for prog in programs:
        if not isinstance(prog, dict):
            continue
        try:
            key = _program_key(prog)
        except (TypeError, ValueError):
            continue
        if key in seen or key[4] <= 0:
            continue
        seen.add(key)
        entry = {"model": key[0] or None, "stem": key[1],
                 "h": key[2], "w": key[3], "bucket": key[4]}
        if key[5]:
            entry["mesh"] = [[a, n] for a, n in key[5]]
        out.append(entry)
    return out


def prewarm_entries(programs: List[Dict[str, Any]],
                    mesh=None) -> List[list]:
    """Manifest programs -> ``cfg.prewarm``-shaped 5-element entries
    (``[h, w, bucket, model, stem]``; model "" = engine default).

    ``mesh`` filters to the programs recorded under that mesh spec (a
    ``jax.sharding.Mesh``, a pair list, or None = single-chip): a
    spawned mesh member replays sharded programs, a single-chip member
    replays single-chip ones, and a stale manifest from the other world
    yields no entries — clean compile, never a wrong-sharding replay."""
    want = tuple((a, n) for a, n in mesh_spec(mesh))
    return [
        [p["h"], p["w"], p["bucket"], p["model"] or "", p["stem"]]
        for p in programs
        if _mesh_key(p) == want
    ]


def record_program(
    cache_dir: str,
    *,
    model: Optional[str],
    stem: str,
    src_hw: tuple,
    bucket: int,
    mesh=None,
) -> None:
    """Merge one compiled serving-step program into the manifest
    (read-modify-write under the process lock, atomic rename so a
    concurrently spawning member never reads a torn file). A stale or
    mismatched manifest on disk is replaced, not merged into.
    ``mesh`` (Mesh / pair list / None) stamps sharded programs; the
    key is omitted entirely for single-chip so pre-r17 manifests and
    new single-chip ones stay byte-compatible."""
    prog = {
        "model": model or None,
        "stem": stem or "classic",
        "h": int(src_hw[0]),
        "w": int(src_hw[1]),
        "bucket": int(bucket),
    }
    spec = mesh_spec(mesh)
    if spec:
        prog["mesh"] = spec
    with _manifest_lock:
        try:
            existing = load_manifest(cache_dir) or []
            keys = {_program_key(p) for p in existing}
            if _program_key(prog) in keys:
                return
            existing.append(prog)
            os.makedirs(cache_dir, exist_ok=True)
            path = manifest_path(cache_dir)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(
                    {
                        "version": MANIFEST_VERSION,
                        "jaxlib": _jaxlib_stamp(),
                        "programs": existing,
                    },
                    fh,
                    indent=1,
                    sort_keys=True,
                )
            os.replace(tmp, path)
        except Exception:
            # Recording is best-effort: a read-only cache dir costs the
            # next spawn a compile, never this member its boot.
            log.warning("could not record prewarm program %r in %s",
                        prog, cache_dir, exc_info=True)
