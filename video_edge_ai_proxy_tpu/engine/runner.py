"""TPU inference engine: the plane the reference doesn't have.

The reference ships raw BGR24 frames to external CPU clients and calls it a
day (`/root/reference/README.md:5-27`); results only re-enter the system if
the client pushes `Annotate` events. This engine closes that loop on-device
(BASELINE.json north star): collector output crosses PCIe as uint8, and one
jitted program per (bucket, source-geometry) does preprocess → forward →
postprocess (Pallas NMS for detectors) on the TPU. Results fan out to

- gRPC `Inference` subscribers (serve/grpc_api.py), and
- the annotation uplink queue, as the same `AnnotateRequest` protos an
  external ML client would have sent — so the reference's cloud pipeline
  (`examples/annotation.py` shape) keeps working with zero client code.

Latency pipeline: JAX dispatch is async — the engine thread submits each
tick's batches and hands them to a dedicated drain thread that blocks on
the device outputs and emits the moment the device finishes (event-driven
drain). H2D/compute for tick N+1 overlaps D2H/postprocess for tick N
(double buffering, SURVEY.md §7 hard part 2) WITHOUT parking results
until the next tick boundary — the r4-measured full-tick drain deferral
(~tick_ms of p50) is gone. The drain queue is depth-2: beyond that the
engine thread blocks, which is the natural backpressure when the device
(or the dev tunnel) is slower than the tick rate. Collector buffers
backing in-flight batches are strict-leased and released by the drain
thread after emit, so a deep pipeline can never alias host frames.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..bus.interface import FrameBus, FrameMeta
from ..obs import registry as obs_registry, tracer
from ..obs.spans import trace_id_of
from ..obs.perf import PerfTracker
from ..obs.prof import Profiler
from ..obs.slo import SLOEngine, default_slos
from ..obs.watch import Watchdog
from ..ops.nms import batched_nms
from ..ops.preprocess import (
    frame_quality_stats, preprocess_classify, preprocess_clip,
    preprocess_letterbox, preprocess_letterbox_fused, unletterbox_boxes,
)
from ..proto import pb
from ..resilience.ladder import RUNGS, DegradationLadder
from ..utils.config import EngineConfig
from ..utils.logging import get_logger, reset_log_context, set_log_context
from .classes import class_name
from .collector import BatchGroup, CanvasPacker, Collector, pad_to_bucket

log = get_logger("engine.runner")

TOP_K_CLASSES = 5


def _rebox(template, values):
    """Re-attach flax AxisMetadata boxes (logical sharding names) from
    ``template`` onto the raw arrays in ``values`` — the inverse of
    ``parallel.sharding.unbox`` for checkpoint restore."""
    import flax.linen as nn
    import jax

    return jax.tree_util.tree_map(
        lambda box, val: box.replace_boxed(val)
        if isinstance(box, nn.meta.AxisMetadata) else val,
        template, values,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )


def build_serving_step(model, spec, *, quality_thumb: int = 0):
    """The per-tick device program for one model kind: uint8 frames in,
    postprocessed results out. SINGLE source of truth — the engine compiles
    it per (geometry, bucket), bench.py times it, __graft_entry__ exposes
    it, so all three always run the identical program.

    With ``quality_thumb`` > 0 (engine.quality_thumb config) the returned
    step takes an optional third argument — the previous tick's [N, th, tw]
    f32 luma thumbnails (omitted → zeros, so two-arg callers still work) —
    and its output gains ``quality_stats`` / ``quality_thumbs``
    (ops/preprocess.py frame_quality_stats), so per-frame health statistics
    ride the existing result transfer. The default two-argument signature
    is byte-identical to before, which keeps bench.py, __graft_entry__ and
    the replay goldens pinning the same program; ``device_checksum`` keys
    off the detect/embed/classify signature keys and ignores the extras.
    Clip-input specs (5-d frames) never carry stats — their streams get
    detections-only verdicts (obs/quality.py)."""
    import jax

    size = spec.input_size

    if spec.kind == "detect":
        # Stem-variant dispatch (round 15): an s2d-stem model gets the
        # fused letterbox+normalize+s2d megakernel — the 1080p uint8
        # plane is read exactly once and the stem consumes the folded
        # 320²x12 plane directly. The classic path below stays
        # byte-identical (replay checksums pin it bit-for-bit).
        fused = getattr(getattr(model, "cfg", None), "stem", "classic") == "s2d"

        def raw(variables, frames_u8):
            if fused:
                x, lb = preprocess_letterbox_fused(frames_u8, size)
            else:
                x, lb = preprocess_letterbox(frames_u8, size)
            # decode="serving" (models/yolov8.py): class reduction happens
            # in logit space inside the model; sigmoid is monotone, so
            # applying it to the per-anchor winners here gives the same
            # scores as decode=True's full sigmoid at a fraction of the
            # elementwise work.
            boxes, max_logit, cls_ids = model.apply(
                variables, x, decode="serving"
            )
            b, s, c, valid = batched_nms(
                boxes, jax.nn.sigmoid(max_logit), cls_ids
            )
            b = unletterbox_boxes(b, lb)
            return {"boxes": b, "scores": s, "classes": c, "valid": valid}
    elif spec.kind == "embed":
        def raw(variables, frames_u8):
            x = preprocess_classify(frames_u8, (size, size))
            emb = model.apply(variables, x, features_only=True)
            return {"embedding": emb}
    else:  # classify | video
        pre = preprocess_clip if spec.clip_len else preprocess_classify

        def raw(variables, frames_u8):
            import jax.numpy as jnp

            x = pre(frames_u8, (size, size))
            logits = model.apply(variables, x)
            probs = jax.nn.softmax(logits, axis=-1)
            top_p, top_i = jax.lax.top_k(
                probs, min(TOP_K_CLASSES, probs.shape[-1])
            )
            return {"top_probs": top_p, "top_ids": top_i.astype(jnp.int32)}

    if not quality_thumb or spec.clip_len:
        return raw

    thumb_hw = (quality_thumb, quality_thumb)

    def with_stats(variables, frames_u8, prev_thumbs=None, _raw=raw):
        import jax.numpy as jnp

        out = dict(_raw(variables, frames_u8))
        if prev_thumbs is None:
            # Two-arg call (existing callers, warm-start): diff against a
            # zero thumbnail; the host tracker discards the first diff
            # sample anyway (obs/quality.py first-sample rule).
            prev_thumbs = jnp.zeros(
                (frames_u8.shape[0],) + thumb_hw, jnp.float32
            )
        stats, thumbs = frame_quality_stats(frames_u8, prev_thumbs, thumb_hw)
        out["quality_stats"] = stats
        out["quality_thumbs"] = thumbs
        return out

    return with_stats


_RUNG_IDX = {r: i for i, r in enumerate(RUNGS)}

# Once-per-process memo for _note_feature_disabled: engine restarts within
# one process (tests, soak harnesses) would otherwise re-log every
# construction, and dashboards only need the gauge, not the log scrape.
_FEATURES_NOTED: set = set()


def _note_feature_disabled(feature: str, reason: str) -> None:
    """Surface an auto-disabled engine feature as a gauge
    (``vep_engine_feature_disabled{feature,reason}`` == 1) plus ONE
    process-lifetime log line — fleet dashboards watch the metric, not
    per-startup warnings."""
    obs_registry.gauge(
        "vep_engine_feature_disabled",
        "1 when an engine feature auto-disabled itself (see reason label)",
        ("feature", "reason"),
    ).labels(feature, reason).set(1.0)
    key = (feature, reason)
    if key not in _FEATURES_NOTED:
        _FEATURES_NOTED.add(key)
        log.info("%s: disabled (%s); vep_engine_feature_disabled gauge set",
                 feature, reason)


def admitted_streams(
    inferred: Sequence[str], deprioritized: Sequence[str] = (),
) -> List[str]:
    """Degradation-ladder rung 3 (admission_pause): admit a deterministic
    half of the streams — the first half of the sorted id list, so the
    SAME streams stay admitted across ticks (stable batches, no
    membership thrash) and recovery resumes the rest. One stream never
    pauses (shedding the whole fleet is an outage, not a degradation).

    ``deprioritized`` streams (quality-unhealthy: black/frozen per
    obs/quality.py — their frames carry no recoverable signal) sort
    BEHIND every healthy stream, making them the first-shed candidates;
    with no deprioritized set the result is byte-identical to before."""
    dep = set(deprioritized)
    ids = sorted(inferred, key=lambda d: (d in dep, d))
    if len(ids) <= 1:
        return ids
    return sorted(ids[: (len(ids) + 1) // 2])


def shed_stale(group: BatchGroup, now_ms: float, max_staleness_ms: float,
               buckets: Sequence[int], shards: int = 1):
    """Degradation-ladder rung 1: drop frames older than the staleness
    bound from a collected group BEFORE dispatch (oldest-first by
    construction — only stale rows leave). Fresh rows compact in place
    within the pooled buffer view (the lease is untouched) and the view
    re-slices to the smallest covering bucket. Returns ``(group, shed)``;
    group is None when every row was stale (caller releases the lease).
    Frames without a publish timestamp are treated as fresh. Shed rows
    close their lineage with a terminal ``dropped`` span (r14 bugfix:
    the per-stream ring used to keep the span open forever, so trace
    export and stage_breakdown undercounted drops)."""
    keep = [
        i for i, m in enumerate(group.metas)
        if not m.timestamp_ms or now_ms - m.timestamp_ms <= max_staleness_ms
    ]
    shed = len(group.metas) - len(keep)
    if shed == 0:
        return group, 0
    if tracer.enabled:
        kept = set(keep)
        for i, m in enumerate(group.metas):
            if i not in kept and tracer.sampled(m.packet):
                tracer.record(
                    group.device_ids[i], "dropped", m.packet,
                    reason="stale_shed", trace_id=trace_id_of(
                        m, group.device_ids[i]))
    if not keep:
        return None, shed
    if group.rows is not None and shards > 1:
        return _compact_sharded(group, keep, buckets, shards), shed
    for new_i, old_i in enumerate(keep):
        if new_i != old_i:
            group.frames[new_i] = group.frames[old_i]
    group.device_ids = [group.device_ids[i] for i in keep]
    group.metas = [group.metas[i] for i in keep]
    n = len(keep)
    bucket = next(b for b in sorted(buckets) if b >= n)
    view = group.frames[:bucket]
    if bucket != n:
        view[n:] = 0
    group.frames = view
    group.bucket = bucket
    return group, shed


def _compact_sharded(group: BatchGroup, keep: List[int],
                     buckets: Sequence[int], shards: int) -> BatchGroup:
    """Keep-list compaction for shard-segmented groups (r17), shared by
    rung-1 stale shedding and the ROI full-row path: surviving rows
    compact WITHIN their shard's segment (a row must never migrate to
    another chip's slice), and the group re-slices to the smallest
    bucket whose per-shard segment covers the fullest shard. Compaction
    runs low-to-high global row, so every move reads an untouched
    source (same in-place discipline as the identity-layout path)."""
    seg_src = group.bucket // shards
    per: Dict[int, List[int]] = {}
    for i in keep:
        per.setdefault(group.rows[i] // seg_src, []).append(i)
    k_max = max(len(v) for v in per.values())
    bucket = next(
        b for b in sorted(buckets)
        if b % shards == 0 and b // shards >= k_max
    )
    seg = bucket // shards
    moves = []       # (dst_row, slot i) sorted by source row below
    for s, slots in per.items():
        for j, i in enumerate(slots):
            moves.append((s * seg + j, i))
    # seg <= seg_src, so dst <= src slotwise within a shard and shards
    # only move down: processing in ascending source-row order never
    # overwrites a pending source.
    moves.sort(key=lambda m: group.rows[m[1]])
    occupied = set()
    for dst, i in moves:
        src = group.rows[i]
        if dst != src:
            group.frames[dst] = group.frames[src]
        occupied.add(dst)
    group.device_ids = [group.device_ids[i] for _, i in moves]
    group.metas = [group.metas[i] for _, i in moves]
    group.rows = [dst for dst, _ in moves]
    view = group.frames[:bucket]
    for r in range(bucket):
        if r not in occupied:
            view[r] = 0
    group.frames = view
    group.bucket = bucket
    return group


@dataclass
class StreamStats:
    frames: int = 0
    last_latency_ms: float = 0.0
    ema_latency_ms: float = 0.0
    last_batch: int = 0
    # Per-stream device attribution (r9): padding waste and device time
    # of the batches that served this stream, so /api/v1/stats can say
    # which streams ride under-filled (expensive) buckets.
    padded_slots: int = 0          # zero-padded slots in the last batch
    device_ms_ema: float = 0.0
    device_ms_initialized: bool = False
    # Monotonic time of the last emitted result — the availability-SLO
    # signal (obs/slo.py): an inferred stream that stops emitting goes
    # "unavailable" after slo_availability_window_s.
    last_emit_mono: float = 0.0
    # A first frame CAN legitimately measure 0.0 ms (synthetic sources
    # stamp publish-time wall clock; sub-ms emit rounds to 0) — the seed
    # flag, not the value, decides whether the EMA re-seeds.
    ema_initialized: bool = False

    def note_latency(self, latency_ms: float) -> None:
        self.last_latency_ms = latency_ms
        if self.ema_initialized:
            self.ema_latency_ms = (
                0.9 * self.ema_latency_ms + 0.1 * latency_ms)
        else:
            self.ema_latency_ms = latency_ms
            self.ema_initialized = True

    def note_device(self, device_ms: float, padded_slots: int) -> None:
        self.padded_slots = padded_slots
        if self.device_ms_initialized:
            self.device_ms_ema = 0.9 * self.device_ms_ema + 0.1 * device_ms
        else:
            self.device_ms_ema = device_ms
            self.device_ms_initialized = True


@dataclass(frozen=True)
class StreamStatsView:
    """Immutable point-in-time copy handed out by `stats()`. The live
    `StreamStats` objects are mutated by the drain thread; sharing them
    with API handlers let a caller read torn (or worse, mutate engine)
    state."""

    frames: int = 0
    last_latency_ms: float = 0.0
    ema_latency_ms: float = 0.0
    last_batch: int = 0
    # r9 per-stream device attribution. `bucket` is the padded size of
    # the last batch that served the stream (same number last_batch has
    # always carried, named for the API surface the ISSUE specifies).
    bucket: int = 0
    padded_slots: int = 0
    device_ms_ema: float = 0.0


@dataclass
class _Inflight:
    """A dispatched (not yet drained) device batch."""

    group: BatchGroup
    outputs: Any              # tree of jax.Arrays (async)
    t_submit: float
    t_collect: float = 0.0    # wall s the collector returned this group
                              # (stage_trace only; 0 when tracing is off)


class _TimedStep:
    """Callable wrapper around a jitted serving step that AOT-compiles on
    first call, timing the compile wall-clock and capturing XLA cost
    analysis (FLOPs/bytes) into the engine's :class:`PerfTracker` — the
    per-cache-miss attribution behind the ``vep_compile_*`` families.

    The jit path stays the source of truth: when ``lower().compile()``
    is unsupported, or the AOT executable later rejects its inputs
    (avals drift, e.g. params re-placed onto a mesh), the wrapper
    permanently falls back to calling the plain jitted function, where
    jax's own cache handles compilation. Harness wrappers that decorate
    ``InferenceEngine._step`` (replay/harness.py device-stall fault)
    keep working: ``_step`` still returns a plain callable.
    """

    __slots__ = ("_jit", "_aot", "_perf", "_model", "_src_hw", "_bucket",
                 "_on_success", "_on_compiled")

    def __init__(self, jit_fn, perf: PerfTracker, model: str,
                 src_hw: tuple, bucket: int, on_first_success=None,
                 on_compiled=None):
        self._jit = jit_fn
        self._aot = None          # None = not compiled; False = jit path
        self._perf = perf
        self._model = model
        self._src_hw = src_hw
        self._bucket = bucket
        # Fired once, after the first call that compiled AND executed
        # without raising — the AOT manifest record hook. Keyed on
        # success so a program whose compile reliably fails is never
        # recorded (and re-failed) on every future spawn's boot.
        self._on_success = on_first_success
        # Fired once with the AOT executable right after note_compile —
        # the r21 HBM plane's memory_analysis() tap. Never fires on the
        # jit fallback (no executable handle to analyze there).
        self._on_compiled = on_compiled

    def __call__(self, variables, *args):
        out = self._invoke(variables, *args)
        if self._on_success is not None:
            cb, self._on_success = self._on_success, None
            cb()
        return out

    def _invoke(self, variables, *args):
        if self._aot is None:
            t0 = time.perf_counter()
            try:
                compiled = self._jit.lower(variables, *args).compile()
            except Exception:
                # No AOT on this backend/version: time the first jit call
                # instead (includes one execution — an upper bound, still
                # the right order of magnitude for compile-storm triage).
                self._aot = False
                t0 = time.perf_counter()
                out = self._jit(variables, *args)
                self._perf.note_compile(
                    self._model, self._src_hw, self._bucket,
                    time.perf_counter() - t0, cost={})
                return out
            self._perf.note_compile(
                self._model, self._src_hw, self._bucket,
                time.perf_counter() - t0, compiled=compiled)
            if self._on_compiled is not None:
                cb, self._on_compiled = self._on_compiled, None
                cb(compiled)
            self._aot = compiled
        if self._aot is not False:
            try:
                return self._aot(variables, *args)
            except Exception:
                self._aot = False
        return self._jit(variables, *args)


def _build_cascade_head(model, score_w, score_b):
    """Temporal-head program body (CASCADE): uint8 clips -> VideoMAE
    logits + pooled clip features + logistic anomaly score, one fused
    program per (model, geometry, bucket) in the engine step cache.

    Features, per clip slot: [0] temporal diff energy — mean absolute
    luma difference between consecutive frames ([0,1] scale; exactly 0
    for a pixel-static track, the zero-false-positive anchor), [1] clip
    luma variance, [2] the head's max softmax probability. The logistic
    ``sigmoid(w . f + b)`` is the flagship event model; the VideoMAE
    logits ride the event payload for downstream consumers. f32 feature
    math and softmax (CLAUDE.md numerics convention — the VideoMAE
    encoder itself computes in bf16 internally)."""
    import jax
    import jax.numpy as jnp

    w = jnp.asarray((tuple(score_w) + (0.0, 0.0, 0.0))[:3], jnp.float32)
    b = jnp.float32(score_b)

    def head(variables, clips):
        x = clips.astype(jnp.float32) / 255.0
        logits = model.apply(variables, x, train=False).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        luma = x.mean(axis=-1)
        diff_energy = jnp.abs(luma[:, 1:] - luma[:, :-1]).mean(
            axis=(1, 2, 3))
        luma_var = jnp.var(luma, axis=(1, 2, 3))
        top_prob = probs.max(axis=-1)
        feats = jnp.stack([diff_energy, luma_var, top_prob], axis=-1)
        score = jax.nn.sigmoid(feats @ w + b)
        return {"event_score": score, "features": feats, "logits": logits}

    return head


class _ThumbPool:
    """Device-resident per-stream quality-thumbnail state (ROADMAP item
    5 host-work fold): one [capacity, th, tw] f32 device array plus a
    host slot map, replacing the per-dispatch host ``jnp.stack`` of
    zero rows the old ``_gather_thumbs`` built. The previous tick's
    thumbnails for a batch are a device-side ``jnp.take`` keyed by slot
    indices; this tick's rows scatter back with ``.at[idx].set`` —
    thumbnail state never crosses back to host, and the dispatch loop
    ships only a [bucket] int32 index vector.

    Row 0 is a permanent zero row: first-seen streams (and padded batch
    slots) gather it, preserving the zero-reference/first-diff contract
    ``frame_quality_stats`` documents. Dict-like surface (``__iter__``/
    ``__len__``/``pop``) so the tick loop's debounced per-stream GC
    treats it exactly like the tracker/annotation state dicts. All
    methods run on the tick thread (same single-writer discipline the
    old per-stream dict had).
    """

    __slots__ = ("side", "device", "_slots", "_free", "_pool", "_capacity",
                 "_high")

    _GROW = 64    # rows added per capacity growth (keeps re-pads rare)

    def __init__(self, side: int, device=None):
        self.side = int(side)
        # r17: a sharded parent pins each sub-pool to its mesh slice's
        # lead device, so gathers/scatters stay chip-local. None keeps
        # the legacy default-device placement bit-identical.
        self.device = device
        self._slots: Dict[str, int] = {}   # device_id -> pool row (>= 1)
        self._free: List[int] = []
        self._pool = None                  # lazy: jax import stays off the
        self._capacity = 0                 # control plane (CLAUDE.md)
        self._high = 0                     # highest row ever assigned

    def __bool__(self) -> bool:
        return bool(self._slots)

    def __iter__(self):
        return iter(list(self._slots))

    def __len__(self) -> int:
        return len(self._slots)

    def pop(self, device_id: str, default=None):
        """Forget a stream (tick-loop GC): its row returns to the free
        list. The stale row contents are unreachable — nothing gathers a
        row until scatter() reassigns it, which overwrites it first."""
        row = self._slots.pop(device_id, None)
        if row is not None:
            self._free.append(row)
        return default

    def _ensure(self, rows: int) -> None:
        import jax.numpy as jnp

        if self._pool is None:
            cap = max(self._GROW, rows)
            pool = jnp.zeros((cap, self.side, self.side), jnp.float32)
            if self.device is not None:
                import jax

                pool = jax.device_put(pool, self.device)
            self._pool = pool
            self._capacity = cap
        elif rows > self._capacity:
            grow = -(-(rows - self._capacity) // self._GROW) * self._GROW
            # Padding a committed array computes on (and stays on) its
            # device, so the shard pinning survives growth.
            self._pool = jnp.pad(self._pool, ((0, grow), (0, 0), (0, 0)))
            self._capacity += grow

    def gather_indices(self, device_ids, bucket: int, rows=None) -> np.ndarray:
        """[bucket] int32 gather rows for a batch, slot order: each
        known stream's row, row 0 (zeros) for first-seen streams and
        padded slots. ``rows`` (shard-segmented layouts) maps slot i to
        its batch row; None keeps the legacy identity order. This
        vector is the only host->device bytes the quality path still
        ships per batch."""
        idx = np.zeros(bucket, np.int32)
        for i, did in enumerate(device_ids):
            r = i if rows is None else rows[i]
            idx[r] = self._slots.get(did, 0)
        return idx

    def gather(self, idx: np.ndarray):
        """Previous-tick [bucket, th, tw] rows as a device-side gather."""
        import jax.numpy as jnp

        self._ensure(1)
        return jnp.take(self._pool, jnp.asarray(idx), axis=0)

    def scatter(self, device_ids, thumbs, rows=None) -> None:
        """Store this tick's [>=n, th, tw] device rows (the step output,
        still async) for next tick's diff; assigns pool rows on first
        sight. ``rows`` names each stream's source row inside ``thumbs``
        (shard-segmented layouts); None = slot order, legacy path."""
        import jax.numpy as jnp

        pool_rows = []
        for did in device_ids:
            row = self._slots.get(did)
            if row is None:
                row = self._free.pop() if self._free else self._high + 1
                self._high = max(self._high, row)
                self._slots[did] = row
            pool_rows.append(row)
        if not pool_rows:
            return
        self._ensure(max(pool_rows) + 1)
        idx = jnp.asarray(np.asarray(pool_rows, np.int32))
        if rows is None:
            src = thumbs[:len(pool_rows)]
        else:
            src = jnp.take(
                thumbs, jnp.asarray(np.asarray(rows, np.int32)), axis=0)
        self._pool = self._pool.at[idx].set(src)

    def nbytes(self) -> int:
        """Device bytes held by the thumbnail ring right now (0 before
        first scatter) — obs/hbm.py ``register_pool`` tap. Capacity-
        based like the track-state ring: grown rows stay allocated after
        their streams GC. Metadata only, no transfer."""
        return int(self._pool.nbytes) if self._pool is not None else 0


class _ShardedThumbPool:
    """Per-mesh-slice thumbnail state for mesh serving (r17 tentpole
    leg 3): one ``_ThumbPool`` per dp shard, each pinned to its slice's
    lead device, speaking the collector's shard-segmented row layout
    (``group.rows``). ``gather`` assembles the per-shard device takes
    into one dp-sharded [bucket, th, tw] array (the same sharding the
    frames carry, so the compiled step sees one stable signature);
    ``scatter`` splits the step's sharded thumbnail output back per
    slice via its addressable shards — a stream's t-1 thumbnail lives
    on the chip that serves its frames, and no thumbnail bytes ever
    cross the host or a chip boundary. Dict-like surface mirrors
    ``_ThumbPool`` for the tick loop's per-stream GC."""

    __slots__ = ("side", "shards", "_mesh", "_shard_of", "_subs")

    def __init__(self, side: int, *, mesh, shards: int, shard_of):
        from ..temporal.state_pool import shard_devices

        self.side = int(side)
        self.shards = int(shards)
        self._mesh = mesh
        self._shard_of = shard_of
        self._subs = [
            _ThumbPool(side, device=d)
            for d in shard_devices(mesh, self.shards)
        ]

    def __bool__(self) -> bool:
        return any(bool(sub) for sub in self._subs)

    def __iter__(self):
        ids: List[str] = []
        for sub in self._subs:
            ids.extend(sub)
        return iter(ids)

    def __len__(self) -> int:
        return sum(len(sub) for sub in self._subs)

    def pop(self, device_id: str, default=None):
        self._subs[self._shard_of(device_id) % self.shards].pop(device_id)
        return default

    def gather_indices(self, device_ids, bucket: int, rows=None):
        """Per-shard [seg] int32 local gather rows (list, one array per
        shard). Row r of the batch lives in shard r // seg at local row
        r % seg — the collector's segmented layout."""
        seg = max(1, bucket // self.shards)
        per = [np.zeros(seg, np.int32) for _ in range(self.shards)]
        for i, did in enumerate(device_ids):
            r = i if rows is None else rows[i]
            per[r // seg][r % seg] = self._subs[r // seg]._slots.get(did, 0)
        return per

    def gather(self, idx):
        """Previous-tick [bucket, th, tw] thumbnails as one dp-sharded
        array: a chip-local take per shard, assembled without any
        cross-chip movement."""
        import jax.numpy as jnp

        from ..parallel import assemble_sharded, batch_sharding

        pieces = []
        for s, sub in enumerate(self._subs):
            sub._ensure(1)
            pieces.append(jnp.take(sub._pool, jnp.asarray(idx[s]), axis=0))
        bucket = sum(int(p.shape[0]) for p in pieces)
        return assemble_sharded(
            pieces, (bucket, self.side, self.side),
            batch_sharding(self._mesh, 3),
        )

    def scatter(self, device_ids, thumbs, rows=None) -> None:
        """Route this tick's sharded [bucket, th, tw] step output into
        the per-shard pools: each shard scatters from its own
        addressable slice (chip-local), with a sliced-view fallback
        when the compiled output's layout hides a shard."""
        bucket = int(thumbs.shape[0])
        seg = max(1, bucket // self.shards)
        by_shard: Dict[int, List[tuple]] = {}
        for i, did in enumerate(device_ids):
            r = i if rows is None else rows[i]
            by_shard.setdefault(r // seg, []).append((r % seg, did))
        pieces: Dict[int, Any] = {}
        for sh in getattr(thumbs, "addressable_shards", ()):
            if int(sh.data.shape[0]) != seg:
                continue   # unexpected output layout: fallback below
            start = sh.index[0].start or 0
            pieces.setdefault(start // seg, sh.data)
        for s, pairs in sorted(by_shard.items()):
            piece = pieces.get(s)
            if piece is None:
                piece = thumbs[s * seg:(s + 1) * seg]
            self._subs[s].scatter(
                [did for _, did in pairs], piece,
                rows=[r for r, _ in pairs],
            )

    def nbytes(self) -> Dict[str, int]:
        """Per-shard thumbnail ring bytes ``{shard: bytes}`` — the
        obs/hbm.py sharded ``register_pool`` shape (each sub-pool's
        figure is exact against its own ring's ``.nbytes``)."""
        return {str(s): sub.nbytes() for s, sub in enumerate(self._subs)}


class _Prefetched:
    """Handle for one batch placement in flight on the transfer thread."""

    __slots__ = ("group", "ready", "placed", "error", "transfer_s",
                 "overlapped_s", "slot")

    def __init__(self, group: BatchGroup):
        self.group = group
        self.ready = threading.Event()
        self.placed = None
        self.error: Optional[BaseException] = None
        self.transfer_s = 0.0
        self.overlapped_s = 0.0   # transfer wall time with >=1 batch in flight
        self.slot = 0             # which of the key's two input slots


class _PrefetchStage:
    """Dedicated H2D transfer stage (ROADMAP item 5 tentpole): a
    depth-2 in-queue — the per-(model, geometry, bucket) double-buffered
    input slots — feeding one transfer thread that places each collected
    batch with a real async ``jax.device_put``. The copy of batch t+1
    runs while the tick thread dispatches batch t and the device
    computes it, instead of serializing inside the dispatch loop (the
    pre-r12 behavior: single-device placement was a passthrough and the
    whole uint8 plane crossed synchronously inside the step call).
    ``block_until_ready`` on the placed array bounds the transfer window
    AND guarantees the pooled host buffer is no longer being read when
    the handle resolves — the lease-return failure path relies on that.

    Slot parity per key is bookkeeping for attribution (at most DEPTH
    placements of a key are ever outstanding); the HBM itself is
    recycled by XLA through the donated frames argument (see ``_step``).
    """

    DEPTH = 2

    def __init__(self, place_fn, busy_fn, shards: int = 1):
        self._place = place_fn       # host frames -> device array
        self._busy = busy_fn         # True when >=1 dispatched batch in flight
        # r17: under mesh serving each placement fans out one async
        # device_put per dp slice; slot parity tracks per (shard, model,
        # geometry, bucket) so attribution stays per-chip even though
        # the shard-segmented group advances all slices together.
        self.shards = int(shards)
        self._q: "queue.Queue[Optional[_Prefetched]]" = queue.Queue(
            maxsize=self.DEPTH)
        self._thread: Optional[threading.Thread] = None
        self._slots: Dict[tuple, int] = {}

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="tpu-engine-xfer", daemon=True)
        self._thread.start()

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        if self._thread is None:
            return
        try:
            self._q.put(None, timeout=5)
        except queue.Full:
            log.warning("transfer queue full at stop; abandoning thread")
        self._thread.join(timeout=10)

    def nbytes(self) -> int:
        """Device bytes currently parked in the prefetch stage: placed-
        and-undispatched batches sitting in the depth-2 in-queue — the
        obs/hbm.py ``register_pool`` tap for the double-buffered input
        slots. Snapshots the queue under its own mutex (the stdlib-
        sanctioned way to size a live Queue); handles not yet placed (or
        errored) count 0. Metadata reads only."""
        with self._q.mutex:
            pending = list(self._q.queue)
        total = 0
        for pre in pending:
            placed = getattr(pre, "placed", None)
            if placed is None:
                continue
            parts = placed if isinstance(placed, (list, tuple)) else (placed,)
            for part in parts:
                total += int(getattr(part, "nbytes", 0) or 0)
        return total

    def reset(self, shards: int) -> None:
        """Survivor-mesh failover (engine/fault.py): parity slots keyed
        on the old shard count are meaningless once the mesh shrinks, so
        drop them wholesale and restart attribution at slot 0. The tick
        thread owns both submission and failover, and the failover path
        waits every in-flight handle before calling this, so the queue
        is empty and no key can be mid-flight."""
        self.shards = max(1, int(shards))
        self._slots.clear()

    def submit(self, group: BatchGroup, stop_event) -> Optional[_Prefetched]:
        """Queue a placement; blocks (in interruptible slices) while both
        slots are occupied — same bounded-pipeline stance as the drain
        queue. Returns None on shutdown (caller returns the lease)."""
        pre = _Prefetched(group)
        n_keys = self.shards if group.rows is not None else 1
        keys = [(s, group.model, group.src_hw, group.bucket)
                for s in range(n_keys)]
        pre.slot = self._slots.get(keys[0], 0)
        for key in keys:
            self._slots[key] = self._slots.get(key, 0) ^ 1
        while not stop_event.is_set():
            try:
                self._q.put(pre, timeout=0.1)
                return pre
            except queue.Full:
                continue
        return None

    def _loop(self) -> None:
        while True:
            pre = self._q.get()
            if pre is None:
                return
            busy = self._busy()
            t0 = time.perf_counter()
            try:
                placed = self._place(pre.group.frames)
                if hasattr(placed, "block_until_ready"):
                    placed.block_until_ready()
                pre.placed = placed
            except BaseException as exc:   # surfaced on the tick thread
                pre.error = exc
            pre.transfer_s = time.perf_counter() - t0
            if busy or self._busy():
                # Device work was in flight while this copy ran: the
                # whole window was hidden behind compute.
                pre.overlapped_s = pre.transfer_s
            pre.ready.set()


def _group_slots(group: BatchGroup) -> int:
    """Stream slots a batch group will emit when healthy — the unit the
    FaultLedger (engine/fault.py) conserves. Coast groups emit one
    result per coast entry, canvas groups one per distinct crop stream
    (``_emit_canvas`` seeds its results dict from crop device_ids), and
    classic groups one per occupied slot."""
    if group.coast:
        return len(group.coast)
    if group.crops:
        return len({c.device_id for c in group.crops})
    return len(group.device_ids)


class _RoiGate:
    """Per-stream motion-gate state for MOSAIC ROI serving (cfg.roi).

    Classification inputs are both *feedback* signals: the previous
    tick's device thumbnail diff energy (ops/preprocess.py
    frame_quality_stats, observed host-side in ``_emit``) and the
    stream's IoUTracker state (updated in ``_emit`` from the previous
    detections). The verdict per detect stream per tick:

    - ``full``  — refresh cadence due, or no gating signal yet, or
      motion with no tracks to localize it: run the classic full frame
      (also the only slots that refresh quality stats, so the diff
      signal can never starve itself).
    - ``idle``  — diff energy below ``roi_idle_diff``: no device work;
      the tracker coasts one frame (misses age so stale tracks expire)
      and its predicted boxes emit with decayed confidence.
    - ``roi``   — motion with live tracks: crops around the predicted
      track boxes join the shared canvases.

    Dict-like protocol (``__iter__``/``__len__``/``pop``) so the
    engine's debounced stream GC treats it exactly like the tracker /
    thumbnail state maps. All access runs under the engine's
    ``_state_lock`` (tick-thread classify + GC, drain-thread feedback).
    """

    def __init__(self, idle_diff: float, full_interval_ms: float):
        self.idle_diff = float(idle_diff)
        self.full_interval_s = full_interval_ms / 1000.0
        self._streams: Dict[str, dict] = {}

    def __bool__(self) -> bool:
        return bool(self._streams)

    def __iter__(self):
        return iter(self._streams)

    def __len__(self) -> int:
        return len(self._streams)

    def pop(self, device_id: str, default=None):
        return self._streams.pop(device_id, default)

    def state(self, device_id: str) -> dict:
        return self._streams.setdefault(
            device_id, {"diff": None, "full_at": 0.0})

    def note_diff(self, device_id: str, diff: float) -> None:
        self.state(device_id)["diff"] = float(diff)

    def note_full(self, device_id: str, now: float) -> None:
        self.state(device_id)["full_at"] = now

    def classify(self, device_id: str, tracker, now: float) -> str:
        st = self.state(device_id)
        if not st["full_at"] \
                or now - st["full_at"] >= self.full_interval_s:
            return "full"
        if st["diff"] is not None and st["diff"] < self.idle_diff:
            return "idle"
        if tracker is not None and tracker.live_tracks:
            return "roi"
        return "full"


class InferenceEngine:
    """Owns the model, the compiled step cache, and the engine thread."""

    # Tracker GC debounce: longer than any worker-restart ring re-create
    # gap, far shorter than "stream is really gone" timescales.
    _TRACKER_GC_GRACE_S = 10.0

    # Per-stream model failure breaker: first retry after this long,
    # doubling per consecutive failure up to the cap. Class attributes so
    # tests can shrink them without monkeypatching module globals.
    BAD_MODEL_BACKOFF_S = 30.0
    BAD_MODEL_BACKOFF_MAX_S = 600.0

    def __init__(
        self,
        bus: FrameBus,
        cfg: Optional[EngineConfig] = None,
        *,
        annotations=None,                    # AnnotationQueue or None
        spec=None,                           # ModelSpec override (tests)
        model_resolver=None,                 # device_id -> model name or ""
        annotation_policy_resolver=None,     # device_id -> policy or ""
        archiver=None,                       # .submit(GopSegment) duck type
        journal=None,                        # shared DecisionJournal or None
    ):
        self._bus = bus
        self._cfg = cfg or EngineConfig()
        self._journal_arg = journal
        self._annotations = annotations
        # Cascade event archive sink (ingest/archive.py SegmentArchiver
        # duck type): "enter" events submit the track's recent tile
        # history as a clip segment. None = no archive taps.
        self._archiver = archiver
        self._spec = spec
        self._model = None
        self._variables = None
        self._mesh = None
        # Per-stream model selection (StreamProcess.inference_model): other
        # registry models load lazily on first use; name -> (spec, model,
        # variables). The default model also lives here under its name.
        self._model_resolver = model_resolver
        self._ann_policy_resolver = annotation_policy_resolver
        self._models: Dict[str, tuple] = {}
        # Per-model failure circuit breaker: name -> {"failures", "retry_at"
        # (monotonic), "error"}. Entries half-open after an exponential
        # backoff so a transient init failure (OOM during a contention
        # spike) does not disable the model until process restart; a model
        # that keeps failing backs off harder instead of starving every
        # healthy stream with multi-second re-init attempts per tick.
        self._bad_models: Dict[str, dict] = {}
        self._conf_threshold = 0.0   # calibrated at warmup from ckpt meta
        self._step_cache: Dict[tuple, Any] = {}
        # AOT prewarm cache (r19, engine/aot_cache.py): when enabled the
        # cache dir carries a prewarm manifest alongside the XLA payload;
        # _prewarm_required/_done back /api/v1/stats "prewarm" (the
        # fleet tier's "warming" member state — scraped-alive but not
        # yet holding its program set; obs/fleet.py).
        self._aot_dir = (
            (self._cfg.aot_cache_dir or "")
            if getattr(self._cfg, "aot_cache", False) else ""
        )
        self._prewarm_required = len(self._cfg.prewarm)
        self._prewarm_done = 0
        # With the AOT cache on, the true program set is unknown until
        # start() unions the manifest in — and REST binds before start(),
        # so a scrape during warmup must read "warming" even when
        # cfg.prewarm is empty (the harness's spawn path boots with no
        # --prewarm flags). Without the cache the config list IS the set.
        self._prewarm_started = not self._aot_dir
        self._collector: Optional[Collector] = None
        self._subscribers: List[tuple] = []   # (queue, device_id filter set|None)
        self._sub_lock = threading.Lock()
        # Set by stop() BEFORE the subscriber end-sentinels go out: a
        # wedged drain thread that wakes up later must not emit results
        # after a subscriber already saw its None (ADVICE r5 #5).
        self._fanout_closed = False
        self._stats: Dict[str, StreamStats] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Event-driven drain: the engine thread queues dispatched batches;
        # the drain thread blocks on device outputs and emits immediately.
        # Depth 2 = classic double buffering; a full queue back-pressures
        # the tick loop instead of growing the in-flight set unboundedly.
        self._drain_q: "queue.Queue[Optional[_Inflight]]" = queue.Queue(
            maxsize=2
        )
        self._drain_thread: Optional[threading.Thread] = None
        # _emit mutates tracker/annotation state from the drain thread
        # while the tick loop GCs the same dicts — one lock covers both.
        self._state_lock = threading.Lock()
        self.ticks = 0
        self.batches = 0
        self.last_tick_monotonic = 0.0
        self._trackers: Dict[str, Any] = {}      # device_id -> IoUTracker
        self._tracker_absent: Dict[str, float] = {}  # id -> absent-since
        # Annotation emit policy state: device_id -> {"sig": {key: conf},
        # "last_ms": int} (cfg.annotation_emit; GC'd with the trackers).
        self._ann_state: Dict[str, dict] = {}
        self._ann_policy_warned: set = set()  # (device_id, bad policy)
        self.annotations_suppressed = 0
        # Results dropped on slow subscribers (queue full in _publish):
        # total + per-stream, surfaced in /metrics and /api/v1/stats so a
        # client that cannot keep up is visible, not silently starved
        # (annotation suppression already has this treatment).
        self.subscriber_drops = 0
        self.subscriber_drops_by_stream: Dict[str, int] = {}
        # stage_trace: per-frame stage timestamps (wall s), bounded deque
        # of dicts — see tools/bench_latency.py for the consumer.
        import collections

        self.stage_records: collections.deque = collections.deque(
            maxlen=4096
        )
        self._probe_cache: tuple = (0.0, None)   # (monotonic, ok | None)
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_spawn_lock = threading.Lock()
        self._probe_fn = None                    # jitted once, reused
        # Unified metrics (obs/metrics.py): handles held here so hot-path
        # observations skip the registry name lookup. Unlabeled families
        # bind their singleton child eagerly — the sample then renders (as
        # 0) from the first scrape, not from the first event. The registry
        # is process-global — /metrics renders these directly.
        # Control-plane decision journal (obs/journal.py, r23): built
        # FIRST so every plane below can record causally-linked audit
        # events. cfg.journal=False leaves it None — no hooks anywhere,
        # /api/v1/journal answers 400, replay bit-identical (test-pinned
        # kill switch, fault convention). A journal passed to the ctor
        # (the head process sharing one journal with router/supervisor)
        # wins over building a fresh one.
        self.journal = None
        if self._cfg.journal:
            if self._journal_arg is not None:
                self.journal = self._journal_arg
            else:
                from ..obs.journal import DecisionJournal

                self.journal = DecisionJournal(self._cfg.journal_capacity)
        self.watchdog = Watchdog(journal=self.journal)
        self._m_ticks = obs_registry.counter(
            "vep_engine_ticks_total", "Engine ticks completed").labels()
        self._m_batches = obs_registry.counter(
            "vep_engine_batches_total", "Device batches dispatched").labels()
        self._m_frames = obs_registry.counter(
            "vep_stream_frames_total", "Inference results per stream",
            ("stream",))
        self._m_latency = obs_registry.histogram(
            "vep_stream_latency_ms",
            "End-to-end frame latency, bus publish to result emit (ms)",
            ("stream",))
        self._m_device = obs_registry.histogram(
            "vep_device_batch_ms",
            "Batch submit to host fetch complete (ms)", ("model",))
        self._m_occupancy = obs_registry.histogram(
            "vep_batch_occupancy_pct",
            "Real frames per padded batch slot (percent)").labels()
        self._m_cache_miss = obs_registry.counter(
            "vep_step_cache_misses_total",
            "Serving-step cache misses (each triggers an XLA compile)"
        ).labels()
        self._m_cache_hit = obs_registry.counter(
            "vep_step_cache_hits_total", "Serving-step cache hits").labels()
        self._m_drain_depth = obs_registry.gauge(
            "vep_drain_queue_depth",
            "Dispatched batches waiting on the device drain thread").labels()
        self._m_sub_drops = obs_registry.counter(
            "vep_stream_subscriber_dropped_total",
            "Results dropped on slow subscribers per stream", ("stream",))
        self._m_late = obs_registry.counter(
            "vep_frames_late_total",
            "Results slower end-to-end than engine.obs_late_ms",
            ("stream",))
        # Recompile-storm detection state (tick loop only).
        self._miss_seen = 0.0
        self._miss_streak = 0
        # Overload degradation ladder (resilience/ladder.py): observed
        # once per tick with drain-queue depth + previous tick duration;
        # the returned rung gates shedding / bucket cap / admission.
        # Shares the engine watchdog so a degraded excursion logs once.
        self.ladder: Optional[DegradationLadder] = None
        if self._cfg.ladder:
            self.ladder = DegradationLadder(
                escalate_after_s=self._cfg.ladder_escalate_after_s,
                recover_after_s=self._cfg.ladder_recover_after_s,
                watchdog=self.watchdog,
                journal=self.journal,
            )
        self.shed_frames = 0
        # r23 journal edge state: open shed-excursion event seq + its
        # accumulated frame count, and the last journaled ROI mode per
        # stream (transitions are journaled on the edge, not per tick).
        self._shed_seq: Optional[int] = None
        self._shed_excursion_frames = 0
        self._roi_mode: Dict[str, str] = {}
        self._m_shed = obs_registry.counter(
            "vep_ladder_shed_frames_total",
            "Frames shed by the degradation ladder (stale at dispatch)",
        ).labels()
        self._last_tick_dur_s = 0.0
        # Backpressure discriminator for the prefetch pipeline (tick
        # thread only): with cfg.prefetch the depth-2 drain queue is
        # legitimately FULL in healthy saturated serving (that is the
        # double buffer doing its job), so raw qsize no longer means
        # "device behind". The signal that still does is the tick
        # thread having had to BLOCK handing a batch to the drain
        # thread (_enqueue_drain found the queue full) — a device that
        # keeps up absorbs the handoff without blocking.
        self._drain_blocked = False
        self._bp_depth = 0
        # Live device-performance attribution (obs/perf.py): compile
        # cost per (model, geometry, bucket) fed from _step misses,
        # per-batch device time / padding waste / MFU fed from _emit.
        self.perf = PerfTracker(peak_tflops=self._cfg.peak_tflops)
        # SLO burn-rate engine (obs/slo.py): per-frame latency events
        # from _emit, per-tick fps + availability samples from the tick
        # loop; evaluated at most every slo_eval_interval_s. The
        # aggregate burn verdict feeds the ladder as extra pressure
        # (cfg.slo_ladder).
        self.slo: Optional[SLOEngine] = None
        self._slo_latency = self._slo_fps = self._slo_avail = None
        self._slo_burning = False
        self._slo_episodes = 0
        self._slo_next_eval = 0.0
        if self._cfg.slo:
            self.slo = SLOEngine(
                default_slos(
                    latency_ms=self._cfg.slo_latency_ms,
                    target_fps=self._cfg.slo_target_fps,
                    warmup_s=self._cfg.slo_warmup_s,
                ),
                watchdog=self.watchdog,
                journal=self.journal,
            )
            self._slo_latency = self.slo.get("detect_latency_p50")
            self._slo_fps = self.slo.get("aggregate_fps")
            self._slo_avail = self.slo.get("stream_availability")
        # Triggered device profiling (obs/prof.py): bounded jax.profiler
        # captures on demand (REST/gRPC) or fired once per SLO episode /
        # ladder escalation from _watch_tick. cfg.prof=False disables the
        # subsystem entirely (the REST endpoint answers 400).
        self.prof: Optional[Profiler] = None
        if self._cfg.prof:
            self.prof = Profiler(
                self._cfg.prof_dir
                or os.path.join(tempfile.gettempdir(), "vep_prof"),
                retention_bytes=self._cfg.prof_retention_bytes,
                trigger=self._cfg.prof_trigger,
                trigger_ms=self._cfg.prof_trigger_ms,
                trigger_min_interval_s=(
                    self._cfg.prof_trigger_min_interval_s),
                max_ms=self._cfg.prof_max_ms,
                tracer=tracer,
                journal=self.journal,
                snapshot_fn=self._prof_snapshot,
            )
        # Output-quality observability (obs/quality.py): host verdict
        # state machines + drift scores fed from _emit; the device side
        # (frame statistics folded into the serving step) additionally
        # needs per-stream thumbnail state — per mesh shard under
        # engine.mesh (r17), one pool on the single chip otherwise.
        # cfg.quality=False disables the whole plane (the REST endpoint
        # answers 400, same kill-switch convention as slo/prof).
        self.quality = None
        self.canary = None
        self._canary_thread: Optional[threading.Thread] = None
        # Device-resident thumbnail pool (dict-like: stream -> pool row).
        # Under a mesh, warmup swaps in the sharded twin once the mesh
        # exists (_ShardedThumbPool: one _ThumbPool per dp slice).
        self._thumbs = _ThumbPool(self._cfg.quality_thumb)
        self._quality_device = False
        # Data-parallel serving state (r17 tentpole leg 1): shard count
        # and the stream->shard map, set by warmup once the mesh shape
        # is known. 1/None = single-chip layout everywhere.
        self._shards = 1
        self._shard_of = None
        # Spatially-multiplexed ROI serving (MOSAIC, ROADMAP item 1):
        # motion gate state + shelf packer, built at warmup (the packer
        # needs the effective bucket list). cfg.roi=False leaves both
        # None — every batch then takes the classic full-frame path
        # bit-identically (test-pinned kill switch). Under engine.mesh
        # (r17) canvases pack per mesh slice, so the scatter-back
        # routing table stays shard-local and ROI serving runs on-mesh.
        self._roi: Optional[_RoiGate] = None
        self._packer: Optional[CanvasPacker] = None
        if self._cfg.roi:
            self._roi = _RoiGate(
                self._cfg.roi_idle_diff, self._cfg.roi_full_interval_ms)
        # Temporal cascade serving (CASCADE, ROADMAP item 2): tracker-
        # keyed device clip rings + cadence-1/N temporal head
        # (temporal/scheduler.py). cascade=False leaves it None — every
        # batch takes today's stateless path bit-identically (test-
        # pinned kill switch, roi=False convention). Under engine.mesh
        # the scheduler swaps its pool for the sharded twin
        # (configure_mesh in warmup) so clip state lives per chip.
        self._cascade = None
        if self._cfg.cascade:
            from ..temporal import CascadeScheduler

            self._cascade = CascadeScheduler(
                model=self._cfg.cascade_model,
                every_n=self._cfg.cascade_every_n,
                crop=self._cfg.cascade_crop,
                clip_len=self._cfg.cascade_clip_len,
                threshold=self._cfg.cascade_threshold,
                enter_n=self._cfg.cascade_enter_n,
                exit_n=self._cfg.cascade_exit_n,
                ttl_ticks=self._cfg.cascade_track_ttl_ticks,
                perf=self.perf,
            )
            self._cascade.head = self._cascade_head
        # Capacity attribution plane (obs/capacity.py): the per-stream
        # device-time ledger + headroom forecast fed from the same
        # _emit measurements obs/perf.py aggregates, evaluated off the
        # tick (throttled). cfg.capacity=False leaves it None — no tap
        # anywhere in the emit path, /api/v1/capacity answers 400, and
        # serving stays bit-identical (test-pinned kill switch, same
        # convention as roi/cascade).
        self.capacity = None
        if self._cfg.capacity:
            from ..obs.capacity import CapacityTracker

            self.capacity = CapacityTracker(
                tick_ms=self._cfg.tick_ms,
                fast_window_s=self._cfg.capacity_fast_window_s,
                slow_window_s=self._cfg.capacity_slow_window_s,
                util_objective=self._cfg.capacity_util_objective,
                eval_interval_s=self._cfg.capacity_eval_interval_s,
            )
        # H2D prefetch stage (cfg.prefetch): placement of collected
        # batches moves off the tick thread onto a dedicated transfer
        # thread, double-buffered at depth 2 to match the drain pipeline.
        # "busy" (the hidden-transfer attribution signal) keys off the
        # drain queue's unfinished-task count: put in _enqueue_drain,
        # task_done after _emit — exactly the submitted-but-not-yet-
        # drained window during which device compute is in flight.
        self._xfer: Optional[_PrefetchStage] = None
        if self._cfg.prefetch:
            self._xfer = _PrefetchStage(
                self._place_device,
                lambda: self._drain_q.unfinished_tasks > 0,
            )
        if self._cfg.quality:
            from ..obs.quality import QualityTracker

            self.quality = QualityTracker(
                black_luma=self._cfg.quality_black_luma,
                black_var=self._cfg.quality_black_var,
                freeze_diff=self._cfg.quality_freeze_diff,
                enter_s=self._cfg.quality_enter_s,
                exit_s=self._cfg.quality_exit_s,
                flatline_s=self._cfg.quality_flatline_s,
                window_s=self._cfg.quality_window_s,
                drift_threshold=self._cfg.quality_drift_threshold,
                on_transition=self._on_quality_transition,
            )
            # r17: device frame statistics run under the mesh too — the
            # thumbnail pool shards per dp slice (warmup).
            self._quality_device = self._cfg.quality_thumb > 0
        # HBM attribution plane (obs/hbm.py, r21): the memory mirror of
        # the capacity plane — compiled-program footprints tapped at the
        # same _TimedStep cache-miss site obs/perf.py uses, plus live
        # byte ledgers for every device/host pool the engine owns. The
        # register_pool callables close over self attributes, so the
        # warmup swaps to sharded twins (and the collector being built
        # later) stay tracked with no re-registration. cfg.hbm=False
        # leaves it None — no compile tap, no pool callables,
        # /api/v1/hbm answers 400, serving bit-identical (test-pinned
        # kill switch, capacity convention).
        self.hbm = None
        if self._cfg.hbm:
            from ..obs.hbm import HbmTracker

            self.hbm = HbmTracker(
                budget_bytes=self._cfg.hbm_budget_bytes,
                fast_window_s=self._cfg.hbm_fast_window_s,
                slow_window_s=self._cfg.hbm_slow_window_s,
                util_objective=self._cfg.hbm_util_objective,
                eval_interval_s=self._cfg.hbm_eval_interval_s,
                pressure_horizon_s=self._cfg.hbm_pressure_horizon_s,
            )
            self.hbm.register_pool(
                "thumbs",
                lambda: self._thumbs.nbytes() if self._thumbs is not None
                else 0)
            self.hbm.register_pool(
                "track_state",
                lambda: self._cascade.pool_nbytes()
                if self._cascade is not None else 0)
            self.hbm.register_pool(
                "prefetch",
                lambda: self._xfer.nbytes() if self._xfer is not None else 0)
            self.hbm.register_pool(
                "collector_host",
                lambda: self._collector.pool_nbytes()
                if self._collector is not None else 0)
        # Device-fault domain (engine/fault.py, r22): per-dispatch
        # deadline/error watchdog + FaultLedger conservation proof +
        # bounded-time survivor-mesh failover. cfg.fault=False leaves it
        # None — no tap in the dispatch/drain paths, /api/v1/faults
        # answers 400, serving bit-identical (test-pinned kill switch,
        # capacity/hbm convention).
        self.faults = None
        if self._cfg.fault:
            from .fault import FaultPlane

            self.faults = FaultPlane(
                deadline_ms=self._cfg.fault_dispatch_deadline_ms,
                hysteresis=self._cfg.fault_hysteresis,
                failover_budget_ms=self._cfg.fault_failover_budget_ms,
                probe_timeout_ms=self._cfg.fault_probe_timeout_ms,
                journal=self.journal,
            )

    @property
    def cascade(self):
        """The cascade scheduler, or None when cfg.cascade is off (the
        REST endpoint keys its 400 on this, r9 convention)."""
        return self._cascade

    # -- lifecycle --

    def warmup(self) -> None:
        """Build model + params and compile nothing yet (steps compile per
        observed shape; call `compile_for` to prewarm a given geometry)."""
        import jax

        from ..models import registry

        if self._aot_dir:
            # AOT prewarm cache (r19): the manifest and the XLA payload
            # share one dir, so the persistent cache binds there instead
            # of compile_cache_dir — same wiring, plus mkdir.
            from . import aot_cache

            aot_cache.configure(self._aot_dir)
        elif self._cfg.compile_cache_dir:
            # Persistent XLA compile cache: a restarted server re-loads
            # compiled programs instead of paying tens of seconds to
            # minutes per (geometry, bucket) again (SURVEY.md §5.4).
            jax.config.update(
                "jax_compilation_cache_dir", self._cfg.compile_cache_dir
            )
            if jax.config.jax_persistent_cache_min_compile_time_secs == 1.0:
                # Lower the jax-default persistence threshold so mid-size
                # serving programs cache too — but never clobber a value
                # the operator set (env/config before boot).
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.5
                )
            try:
                # The cache object binds its directory on first use; if
                # anything compiled before warmup (another engine, a
                # preloaded model), the config change alone is ignored.
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc,
                )

                _cc.reset_cache()
            except Exception:
                log.warning(
                    "could not reset the XLA compilation cache; programs "
                    "compiled before warmup may persist elsewhere",
                    exc_info=True,
                )
        if self._spec is None:
            self._spec = registry.get(self._cfg.model)
        # Detect-family variant axes (round 15): cfg.stem / int8_act
        # rewrite the spec's build BEFORE init so the whole lifecycle
        # (checkpoint templates, prewarm, serving steps) sees one model.
        self._spec = self._variant_spec(self._spec)
        self._model, self._variables = self._spec.init_params(
            jax.random.PRNGKey(0)
        )
        # Calibrated per-checkpoint serving threshold (selftrain loop
        # writes it into checkpoint metadata): detections below it never
        # leave the engine. 0.0 = no calibration -> NMS's own floor only.
        self._conf_threshold = 0.0
        ckpt = self._cfg.checkpoint_path
        if ckpt:
            from ..parallel.sharding import unbox
            from ..utils.checkpoint import load_msgpack_with_meta

            if os.path.exists(ckpt):
                # Checkpoints are UNBOXED raw trees (the canonical format
                # tools/import_weights.py writes and save_checkpoint
                # mirrors); restore against an unboxed template, then
                # re-box so ViT-family logical sharding names survive for
                # mesh serving.
                from ..models.import_weights import pad_stem_on_load

                raw, meta = load_msgpack_with_meta(
                    ckpt, jax.tree.map(np.asarray, unbox(self._variables))
                )
                # Pre-stem_pad_c checkpoints: zero-pad the stem kernel
                # (config-gated — never fires for the s2d stem, whose
                # extra input planes carry real pixels).
                raw = pad_stem_on_load(
                    raw, unbox(self._variables), self._model
                )
                # Host tree for now: placement happens ONCE below (mesh
                # sharding or single-chip put). An eager device_put here
                # would materialize the full tree on one chip first —
                # exactly what sharded serving of big models must avoid.
                self._variables = _rebox(self._variables, raw)
                log.info("loaded engine params from %s", ckpt)
                thr = (meta or {}).get("conf_threshold")
                if thr is not None:
                    self._conf_threshold = float(thr)
                    log.info(
                        "serving at calibrated conf_threshold=%.3f "
                        "(checkpoint metadata)", self._conf_threshold,
                    )
            else:
                log.warning("checkpoint %s missing; using random init", ckpt)
        self._variables = self._maybe_calibrate(
            self._spec, self._model, self._variables
        )
        self._variables = self._maybe_quantize(self._variables)
        buckets = tuple(self._cfg.batch_buckets)
        if self._cfg.mesh:
            # Multi-chip serving: batch axis sharded over dp; params
            # placed by _place_variables (replicated for dp-only meshes
            # and conv trees, SHARDED per logical axis names when the
            # mesh has tp/fsdp/sp/ep — big/long-context transformers).
            # Buckets must divide evenly across dp so every chip gets
            # identical static shapes.
            from ..parallel import factor_mesh, make_mesh

            if isinstance(self._cfg.mesh, str):
                if self._cfg.mesh != "auto":
                    raise ValueError(
                        f"engine.mesh: unknown value {self._cfg.mesh!r} — "
                        "use 'auto', an axis dict like {'dp': 4}, or empty "
                        "for single-chip"
                    )
                # Serving profile: every visible device on the batch axis.
                self._mesh = factor_mesh(prefer=("dp",))
            else:
                n_need = 1
                for v in self._cfg.mesh.values():
                    n_need *= v
                self._mesh = make_mesh(
                    **self._cfg.mesh, devices=jax.devices()[:n_need]
                )
            dp = self._mesh.shape["dp"]
            buckets = tuple(b for b in buckets if b % dp == 0) or (dp,)
            self._variables = self._place_variables(self._variables)
            self._model = self._maybe_seq_parallel(self._model)
            # r17 mesh-native serving: everything downstream of the
            # collector addresses batches in the shard-segmented row
            # layout (shard s owns rows [s*seg, (s+1)*seg)). The stream
            # -> shard map is the collector's stable crc32 hash so a
            # stream's ROI/cascade/thumbnail state lives where its
            # frames land, tick after tick.
            from .collector import stream_shard

            self._shards = dp
            self._shard_of = lambda did: stream_shard(did, dp)
            if self._xfer is not None:
                self._xfer.shards = dp
            if self._quality_device:
                self._thumbs = _ShardedThumbPool(
                    self._cfg.quality_thumb, mesh=self._mesh, shards=dp,
                    shard_of=self._shard_of,
                )
            if self._cascade is not None:
                self._cascade.configure_mesh(
                    mesh=self._mesh, shards=dp, shard_of=self._shard_of,
                )
            if self.faults is not None:
                # Shard -> device-name strings for XLA-error attribution
                # (a raw device error names the chip, not the shard).
                from ..temporal.state_pool import shard_devices

                self.faults.configure(shards=dp, shard_devices={
                    s: [str(d)]
                    for s, d in enumerate(shard_devices(self._mesh, dp))
                })
            log.info(
                "engine mesh: %s (buckets -> %s)",
                dict(zip(self._mesh.axis_names, self._mesh.devices.shape)),
                buckets,
            )
        else:
            # Single chip: a checkpoint-loaded tree is host numpy at this
            # point — place it once so the serving step isn't re-shipping
            # params every tick. (No-op for random-init device arrays.)
            self._variables = jax.device_put(self._variables)
        self._models[self._spec.name] = (self._spec, self._model, self._variables)
        self._buckets = buckets   # effective (mesh-filtered) buckets
        if self._roi is not None:
            # Canvas count per tick can never exceed the largest batch
            # bucket (the packed group must still pad to a known bucket).
            self._packer = CanvasPacker(
                side=self._cfg.roi_canvas,
                gap=self._cfg.roi_gap,
                max_canvases=min(self._cfg.roi_max_canvases,
                                 max(buckets)),
                min_crop=self._cfg.roi_min_crop,
            )
        self._collector = Collector(
            self._bus,
            buckets=buckets,
            clip_len=self._spec.clip_len,
            active_window_s=self._cfg.active_window_s,
            model_of=self._stream_model,
            default_model=self._spec.name,
            interest_of=self._stream_interest,
            # In-flight batches outlive the tick that built them (drain
            # queue); pooled buffers must stay valid until the drain
            # thread releases them.
            strict_lease=True,
            # r17: per-shard batch slices — the collector emits groups in
            # the shard-segmented row layout (group.rows set) so each dp
            # slice receives exactly its streams' frames.
            shards=self._shards,
        )
        if self.hbm is not None and not self._cfg.hbm_budget_bytes:
            # Resolve the real device budget now that the backend is up:
            # device.memory_stats() reports bytes_limit on the TPU; the
            # CPU twin (no memory stats) keeps the synthetic default so
            # forecasts stay meaningful in tests/soaks.
            try:
                stats = jax.devices()[0].memory_stats() or {}
                limit = int(stats.get("bytes_limit", 0) or 0)
            except Exception:
                limit = 0
            if limit > 0:
                self.hbm.set_budget(limit)
        log.info(
            "engine ready: model=%s kind=%s input=%d backend=%s",
            self._spec.name, self._spec.kind, self._spec.input_size,
            jax.default_backend(),
        )

    def _variant_spec(self, spec):
        """Apply the engine's detect-family variant axes — ``cfg.stem``
        ("s2d": space-to-depth stem + fused preprocess) and
        ``cfg.quantize="int8_act"`` (int8 activation convs) — by rewriting
        the spec's build to clone the model with the overridden config.
        Classic/fp configs pass through untouched (the spec object is the
        SAME one, so replay checksums and step-cache identity are
        unchanged). Models whose config lacks the fields (e.g. the
        BlobGauge diagnostic) serve unmodified with a warning."""
        if spec.kind != "detect":
            return spec
        import dataclasses

        stem = getattr(self._cfg, "stem", "classic") or "classic"
        if stem not in ("classic", "s2d"):
            raise ValueError(
                f"engine.stem={stem!r} unsupported ('classic' or 's2d')"
            )
        overrides = {}
        if stem != "classic":
            overrides["stem"] = stem
        if self._cfg.quantize == "int8_act":
            overrides["act_int8"] = True
        if not overrides:
            return spec
        cfg = getattr(spec.build(), "cfg", None)
        try:
            fields = {f.name for f in dataclasses.fields(cfg)}
        except TypeError:
            fields = set()
        missing = sorted(set(overrides) - fields)
        if missing:
            log.warning(
                "model '%s' config has no %s field(s); serving the stock "
                "variant", spec.name, "/".join(missing),
            )
            return spec

        def build(_base=spec.build, _ov=dict(overrides)):
            m = _base()
            return m.clone(cfg=dataclasses.replace(m.cfg, **_ov))

        return dataclasses.replace(spec, build=build)

    def _maybe_calibrate(self, spec, model, variables):
        """cfg.quantize="int8_act": one-shot activation-range calibration
        (models/quantize.py calibrate_serving) over deterministic synthetic
        frames at engine boot. The pass runs the FP forward — it only
        observes per-conv max-abs input ranges into the "quant" collection
        the int8 serving graph then consumes. Deployments wanting
        data-matched ranges re-calibrate offline (tools/bench_levers.py
        calibrates on its own frame set and accuracy-gates the result)."""
        if self._cfg.quantize != "int8_act":
            return variables
        if spec.kind != "detect" or not getattr(
            getattr(model, "cfg", None), "act_int8", False
        ):
            return variables
        from ..models.quantize import calibrate_serving

        rng = np.random.default_rng(0)
        s = spec.input_size
        batches = [
            rng.integers(0, 256, (2, s, s, 3), np.uint8) for _ in range(2)
        ]
        variables = calibrate_serving(model, spec, dict(variables), batches)
        log.info(
            "engine activations calibrated for int8 serving "
            "(%d synthetic batches at %d²)", len(batches), s,
        )
        return variables

    def _maybe_quantize(self, variables):
        """cfg.quantize="int8": weight-only PTQ (models/quantize.py) — int8
        device/checkpoint residency, dequantize fused into the jitted step.
        No calibration data needed, so it is safe at engine boot.
        cfg.quantize="int8_act" keeps the same int8 weight residency and
        additionally runs calibrated int8 activation convs (the model was
        built with act_int8=True by _variant_spec; calibration happened in
        _maybe_calibrate)."""
        if not self._cfg.quantize:
            return variables
        if self._cfg.quantize not in ("int8", "int8_act"):
            raise ValueError(
                f"engine.quantize={self._cfg.quantize!r} unsupported "
                "(only 'int8' weight-only and 'int8_act' calibrated "
                "activation quantization exist)"
            )
        from ..models.quantize import quantize_tree, quantized_nbytes, tree_nbytes

        before = tree_nbytes(variables)
        qt = quantize_tree(variables)
        log.info(
            "engine params quantized int8 (%s): %.1f MB -> %.1f MB",
            "weight-only" if self._cfg.quantize == "int8" else
            "weights + calibrated activations",
            before / 1e6, quantized_nbytes(qt) / 1e6,
        )
        return qt

    def _maybe_seq_parallel(self, model):
        """Long-context serving: when the mesh carries a sequence axis
        (sp > 1), transformer-family models re-instantiate with the
        ring-attention ``attn_fn`` so the [T, T] attention tiles shard
        over sp instead of materializing per chip — the serving-side
        twin of parallel.with_ring_attention (params are unchanged;
        attn_fn is not a parameter). Conv models pass through."""
        if self._mesh is None or self._mesh.shape.get("sp", 1) <= 1:
            return model
        import dataclasses

        if not any(f.name == "attn_fn" for f in dataclasses.fields(model)):
            return model
        from ..parallel import with_ring_attention

        log.info("serving with ring attention over sp=%d",
                 self._mesh.shape["sp"])
        return with_ring_attention(
            type(model), model.cfg, self._mesh, dtype=model.dtype
        )

    def _place_variables(self, variables):
        """Put a model's variables onto the serving mesh. With model
        axes configured (tp/fsdp/sp/ep > 1) and full-precision weights,
        transformer params shard per their flax logical axis names
        ("embed"/"qkv"/"mlp"/"expert"; conv trees carry none and
        replicate) — big/long-context models (ViT-B, VideoMAE-64) fit and
        serve across chips with XLA inserting the collectives
        (scaling-book recipe, parallel/sharding.py rules). dp-only meshes
        and int8 weight trees (already tiny) replicate. ONE decision for
        the default model and every per-stream extra."""
        import jax

        from ..parallel import replicated

        model_axes = any(
            self._mesh.shape.get(a, 1) > 1 for a in ("tp", "fsdp", "sp", "ep")
        )
        if model_axes and not self._cfg.quantize:
            from ..parallel.sharding import place_params

            return place_params(self._mesh, variables)
        return jax.device_put(variables, replicated(self._mesh))

    def _ensure_model(self, name: str):
        """(spec, model, variables) for a registry model, lazily built.
        Only the default model reads cfg.checkpoint_path; per-stream extras
        start from init (their checkpoints belong to a later config)."""
        entry = self._models.get(name)
        if entry is None:
            import jax

            from ..models import registry

            spec = self._variant_spec(registry.get(name))
            model, variables = spec.init_params(jax.random.PRNGKey(0))
            variables = self._maybe_calibrate(spec, model, variables)
            variables = self._maybe_quantize(variables)
            if self._mesh is not None:
                variables = self._place_variables(variables)
                model = self._maybe_seq_parallel(model)
            entry = (spec, model, variables)
            self._models[name] = entry
            log.info("engine loaded extra model '%s' (kind=%s)", name, spec.kind)
        return entry

    def _stream_model(self, device_id: str):
        """Collector resolver: (model name, clip_len) or None for default."""
        if self._model_resolver is None:
            return None
        name = self._model_resolver(device_id)
        if name == "none":
            # Operator switched inference off for this stream
            # (StreamProcess.inference_model: "none"); the collector gates
            # it out of batches and keep_streams_hot.
            return "none", 0
        if not name or name == self._spec.name:
            return None
        bad = self._bad_models.get(name)
        if bad is not None and time.monotonic() < bad["retry_at"]:
            return None
        try:
            spec, _, _ = self._ensure_model(name)
        except Exception as exc:
            # Unknown name OR a model that fails to build (OOM, bug): either
            # way confine the damage to this stream's model choice — a
            # per-tick re-attempt of a failing multi-second init would
            # starve every healthy stream. The breaker half-opens after an
            # exponential backoff (next attempt is the probe) rather than
            # disabling the model until restart.
            failures = (bad["failures"] if bad else 0) + 1
            backoff = min(
                self.BAD_MODEL_BACKOFF_S * (2 ** (failures - 1)),
                self.BAD_MODEL_BACKOFF_MAX_S,
            )
            self._bad_models[name] = {
                "failures": failures,
                "retry_at": time.monotonic() + backoff,
                "error": f"{type(exc).__name__}: {exc}",
            }
            log.exception(
                "stream %s model '%s' unavailable (failure %d); using "
                "default, retrying in %.0fs",
                device_id, name, failures, backoff,
            )
            return None
        if bad is not None:
            self._bad_models.pop(name, None)
            log.info("model '%s' recovered after %d failure(s)",
                     name, bad["failures"])
        return name, spec.clip_len

    # -- profiling (SURVEY.md §5.1: the reference has no tracing at all) --

    def _prof_snapshot(self) -> dict:
        """Engine state frozen into every capture bundle (obs/prof.py):
        the perf/SLO numbers that were true while the trace ran."""
        snap = {
            "ticks": self.ticks,
            "batches": self.batches,
            "perf": self.perf.snapshot(),
        }
        if self.slo is not None:
            snap["slo"] = self.slo.snapshot()
        if self.ladder is not None:
            snap["rung"] = self.ladder.rung
        return snap

    def start_profile(self, log_dir: str) -> None:
        """Begin an unbounded jax.profiler trace.

        Deprecated: thin delegate kept for signature compatibility; the
        capture path lives in obs/prof.py (``self.prof``), which shares
        one busy flag with the bounded ``/api/v1/profile?ms=N`` captures
        and the burn triggers. Prefer ``self.prof.capture(ms)``.
        """
        if self.prof is None:
            raise RuntimeError("profiling disabled (engine.prof=False)")
        self.prof.start(log_dir)

    def stop_profile(self) -> None:
        """Stop the trace begun by :meth:`start_profile` (deprecated
        delegate; see start_profile)."""
        if self.prof is None:
            raise RuntimeError("profiling disabled (engine.prof=False)")
        self.prof.stop()

    def save_checkpoint(self, path: Optional[str] = None) -> str:
        """Persist current params (msgpack, atomic)."""
        import jax

        from ..utils.checkpoint import save_msgpack

        if self._variables is None:
            raise RuntimeError(
                "save_checkpoint before warmup would overwrite the "
                "checkpoint with empty params; call warmup() first"
            )
        path = path or self._cfg.checkpoint_path
        if not path:
            raise ValueError("no checkpoint path configured")
        variables = self._variables
        if self._cfg.quantize:
            # Checkpoints stay full-precision (the canonical format every
            # load path expects); quantization re-applies at next warmup.
            # The exact pre-quantization weights are gone, so this write is
            # LOSSY relative to whatever the engine originally loaded —
            # overwriting a trained f32 checkpoint bakes in up to
            # absmax/254 per-element error. Warn, don't silently clobber.
            from ..models.quantize import dequantize_tree

            log.warning(
                "save_checkpoint from a quantized engine writes int8-"
                "roundtripped weights (lossy vs the originally loaded "
                "params); keep a copy of the source checkpoint"
            )
            variables = dequantize_tree(variables)
        # Unboxed raw trees on disk — one canonical format shared with
        # tools/import_weights.py (see the load path in warmup).
        from ..parallel.sharding import unbox

        save_msgpack(path, jax.tree.map(np.asarray, unbox(variables)))
        return path

    def start(self) -> None:
        if self._model is None:
            self.warmup()
        entries = [list(g) for g in self._cfg.prewarm]
        if self._aot_dir:
            # AOT prewarm cache (r19): union the manifest's recorded
            # program set into the configured prewarm list — every
            # compile below is then a persistent-cache hit on a member
            # sharing the dir, so a spawned member holds its programs
            # within one scrape interval. A mismatched/absent manifest
            # is just an empty union (clean compile).
            from . import aot_cache

            def _ekey(e):
                try:
                    return (int(e[0]), int(e[1]), int(e[2]),
                            str(e[3]) if len(e) >= 4 and e[3] else "")
                except (TypeError, ValueError, IndexError):
                    return None

            seen = {k for k in (_ekey(e) for e in entries) if k}
            programs = aot_cache.load_manifest(self._aot_dir) or []
            # r17: replay only programs recorded under THIS mesh spec —
            # a stale single-chip manifest on a mesh boot (or vice
            # versa) contributes nothing and degrades to clean compile.
            for entry in aot_cache.prewarm_entries(programs,
                                                   mesh=self._mesh):
                key = _ekey(entry)
                if key is not None and key not in seen:
                    seen.add(key)
                    entries.append(entry)
            if programs:
                log.info(
                    "AOT prewarm manifest: %d recorded programs, "
                    "%d total prewarm entries", len(programs), len(entries),
                )
        # Prewarm progress backs the fleet tier's "warming" state: a
        # member is scraped-alive but must not take migrated traffic (or
        # be retired) until complete. Skipped/failed entries still count
        # as done — log-and-continue must not wedge a member in warming.
        self._prewarm_required = len(entries)
        self._prewarm_done = 0
        self._prewarm_started = True   # the entry list is now final
        for geom in entries:
            # Log-and-continue like every other per-item path here: a bad
            # prewarm entry must not abort server boot, and buckets must be
            # ones the collector can actually dispatch (post mesh filter).
            try:
                # [h, w, bucket], [h, w, bucket, model] or
                # [h, w, bucket, model, stem]: the optional 4th element
                # prewarms a non-default model's program; the optional 5th
                # pins the stem variant the entry was written for (config
                # files survive engine.stem flips — a mismatched entry is
                # skipped below instead of compiling a program the engine
                # can never serve, its params being the other variant's).
                model = None
                if len(geom) >= 4:
                    model = str(geom[3])
                stem = str(geom[4]) if len(geom) >= 5 else None
                h, w, bucket = (int(v) for v in geom[:3])
                if bucket not in self._buckets:
                    log.warning(
                        "prewarm bucket %d not in effective buckets %s; "
                        "skipping", bucket, self._buckets,
                    )
                    continue
                log.info("prewarming program for %dx%d bucket=%d model=%s",
                         h, w, bucket, model or self._spec.name)
                self.compile_for((h, w), bucket, model, stem=stem)
            except Exception:
                log.exception("prewarm entry %r failed; continuing", geom)
            finally:
                self._prewarm_done += 1
        if self._xfer is not None:
            self._xfer.start()
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="tpu-engine-drain", daemon=True
        )
        self._drain_thread.start()
        self._thread = threading.Thread(
            target=self._run, name="tpu-engine", daemon=True
        )
        self._thread.start()
        if self.quality is not None and self._cfg.quality_canary:
            try:
                self._start_canary()
            except Exception:
                log.exception(
                    "canary start failed; integrity loop disabled")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._canary_thread is not None:
            self._canary_thread.join(timeout=10)
        if self._xfer is not None:
            # After the tick thread: nothing submits anymore, and any
            # handle the tick thread abandoned mid-wait has resolved.
            self._xfer.stop()
        if self._drain_thread is not None:
            # Sentinel AFTER the tick loop stops producing: everything
            # queued before it still drains (no result is dropped on a
            # clean stop), then the drain thread exits. Bounded put: a
            # wedged device keeps the depth-2 queue full with the drain
            # thread stuck inside a fetch — shutdown must not block
            # forever on the sentinel (the daemon thread is abandoned
            # after the bounded join, like every other stop step here).
            try:
                self._drain_q.put(None, timeout=10)
            except queue.Full:
                log.warning(
                    "drain queue full at stop (wedged device fetch?); "
                    "abandoning drain thread"
                )
            self._drain_thread.join(timeout=10)
        with self._sub_lock:
            # Close the fan-out before the end-sentinels: an abandoned
            # (wedged) drain thread that later finishes its fetch would
            # otherwise _publish into queues whose consumers already saw
            # None — a post-sentinel result a client can never attribute.
            self._fanout_closed = True
            for q, _ in self._subscribers:
                q.put(None)
            self._subscribers.clear()

    # -- output-quality plane (obs/quality.py) --

    def _start_canary(self) -> None:
        """Arm the canary integrity loop: an engine-owned publisher
        replays the committed golden trace (cfg.quality_canary) into the
        bus at low cadence under cfg.quality_canary_stream, and the drain
        thread folds each emitted slot's host checksum into the
        CanaryChecker, which compares once per trace loop. The canary
        rides the normal serving path end to end — bus, collector,
        device program, NMS, drain — so a silent numerics regression
        anywhere on that path moves the fold and fires the
        ``canary_integrity`` SLO + watchdog."""
        from ..obs.quality import CanaryChecker
        from ..obs.slo import BurnRateSLO, integrity_slo
        from ..replay.player import TracePlayer

        player = TracePlayer(self._cfg.quality_canary)
        if not player.devices:
            raise ValueError(
                f"canary trace {self._cfg.quality_canary!r} has no streams")
        events = player.frame_events(player.devices[0])
        if not events:
            raise ValueError(
                f"canary trace {self._cfg.quality_canary!r} has no frames")
        slo = None
        if self.slo is not None:
            slo = self.slo.add(BurnRateSLO(
                integrity_slo(warmup_s=self._cfg.slo_warmup_s)))
        self.canary = CanaryChecker(
            loop_len=len(events),
            stream=self._cfg.quality_canary_stream,
            golden=self._cfg.quality_canary_golden or None,
            watchdog=self.watchdog,
            slo=slo,
        )
        self._canary_thread = threading.Thread(
            target=self._canary_loop, args=(events,),
            name="tpu-engine-canary", daemon=True,
        )
        self._canary_thread.start()

    def _canary_loop(self, events: list) -> None:
        """Low-cadence golden-replay publisher (dedicated thread). Frames
        re-enter through the public bus API like any camera's; publish
        failures (bus flap, ring full) are logged once per failure run
        and otherwise skipped — the checker voids incomplete cycles, so
        dropped canary frames can never manufacture a false mismatch."""
        from ..replay.player import meta_for
        from ..replay.trace import decode_frame

        name = self._cfg.quality_canary_stream
        period = 1.0 / max(self._cfg.quality_canary_fps, 0.1)
        frame0 = decode_frame(events[0])
        i = 0
        alive = False
        warned = False
        while not self._stop.wait(period):
            ev = events[i % len(events)]
            i += 1
            try:
                if not alive:
                    self._bus.create_stream(name, frame0.nbytes)
                    alive = True
                frame = decode_frame(ev)
                meta = meta_for(
                    ev, frame, timestamp_ms=int(time.time() * 1000))
                self._bus.publish(name, frame, meta)
                warned = False
            except Exception as exc:
                alive = False
                if not warned:
                    log.warning("canary publish failed: %s", exc)
                    warned = True

    def _on_quality_transition(self, stream: str, old: str,
                               new: str) -> None:
        """Verdict transitions become uplink alert events on the same
        AnnotateRequest channel the reference's cloud consumes
        (examples/annotation.py shape): type="quality", the verdict as
        object_type — black/frozen/flatline onsets AND recoveries reach
        the cloud side without anything scraping /metrics."""
        if self._annotations is None:
            return
        req = pb.AnnotateRequest(
            device_name=stream,
            type="quality",
            start_timestamp=int(time.time() * 1000),
            object_type=new,
            confidence=1.0,
            ml_model="obs.quality",
            ml_model_version=old,
        )
        try:
            self._annotations.publish(req.SerializeToString())
        except Exception:
            log.exception("quality alert publish failed")

    # -- results fan-out --

    def _stream_interest(self, device_id: str) -> bool:
        """Does anything consume inference results for this stream right
        now? The annotation uplink is standing interest (the engine is its
        producer, feeding the cloud the reference's clients fed,
        examples/annotation.py); otherwise a live subscriber must cover
        the stream. With neither, inferring would compute results nobody
        reads — the collector gates the stream out (SURVEY §2.3 P6).
        The canary stream's consumer is the integrity checker itself:
        always of interest while the loop is armed, or its golden-replay
        frames would never reach the device on a quiet engine."""
        if self.canary is not None and device_id == self.canary.stream:
            return True
        if self._annotations is not None:
            return True
        with self._sub_lock:
            return any(
                ids is None or device_id in ids
                for _, ids in self._subscribers
            )

    def subscribe(self, device_ids=None, context=None, timeout: float = 0.5):
        """Blocking iterator of pb.InferenceResult for gRPC serving."""
        q: queue.Queue = queue.Queue(maxsize=256)
        ids = set(device_ids) if device_ids else None
        with self._sub_lock:
            self._subscribers.append((q, ids))
        try:
            while not self._stop.is_set():
                if context is not None and not context.is_active():
                    return
                try:
                    item = q.get(timeout=timeout)
                except queue.Empty:
                    continue
                if item is None:
                    return
                yield item
        finally:
            with self._sub_lock:
                self._subscribers = [
                    (sq, si) for sq, si in self._subscribers if sq is not q
                ]

    def stats(self) -> Dict[str, StreamStatsView]:
        # Snapshot copies, never the live objects: the drain thread keeps
        # mutating StreamStats after this returns, and handing out live
        # references let API callers observe mid-update state (or corrupt
        # engine accounting by writing through them).
        return {
            device_id: StreamStatsView(
                frames=st.frames,
                last_latency_ms=st.last_latency_ms,
                ema_latency_ms=st.ema_latency_ms,
                last_batch=st.last_batch,
                bucket=st.last_batch,
                padded_slots=st.padded_slots,
                device_ms_ema=st.device_ms_ema,
            )
            for device_id, st in list(self._stats.items())
        }

    def prewarm_status(self) -> Dict[str, Any]:
        """Prewarm progress for /api/v1/stats (r19): the fleet tier
        derives the "warming" member state from ``complete`` — a
        spawned member is scraped-alive the moment REST binds but must
        not take migrated traffic until its program set compiled. A
        member with nothing to prewarm is complete from boot — UNLESS
        the AOT cache is on: then the program set is the manifest union
        computed inside start(), after the (potentially long) warmup, so
        "complete" holds False until that list exists (or 0>=0 during
        warmup would let the router place onto a mid-ramp member)."""
        required = self._prewarm_required
        done = self._prewarm_done
        return {
            "required": required,
            "done": done,
            "complete": self._prewarm_started and done >= required,
            "aot_cache": bool(self._aot_dir),
        }

    def _run_probe(self) -> None:
        """Device round-trip on a dedicated thread; writes the cache when
        (if) the runtime answers."""
        try:
            import jax
            import jax.numpy as jnp

            if self._probe_fn is None:
                self._probe_fn = jax.jit(jnp.add)
            ok = int(self._probe_fn(jnp.int32(1), jnp.int32(1))) == 2
        except Exception:
            log.exception("device health probe failed")
            ok = False
        self._probe_cache = (time.monotonic(), ok)

    def health(self, probe_ttl_s: float = 5.0,
               probe_wait_s: float = 2.0) -> dict:
        """TPU-side health (SURVEY.md §5.3 — the rebuild adds device
        liveness and compile-cache warmth on top of the reference's
        container-level health): engine-thread liveness, last-tick age, a
        round-trip device probe, and how many programs are compiled.

        The probe (a tiny jitted add) runs on ONE dedicated thread and its
        result is cached ``probe_ttl_s`` — a wedged runtime must neither
        leak a new blocked thread per poll nor hang the caller, so polls
        wait at most ``probe_wait_s`` and a probe that cannot answer by
        then reports ``device_ok=False`` until it does.

        ``stale`` compares the last completed tick against
        cfg.health_stale_after_s, which must stay larger than any
        legitimate in-tick XLA compile (first frame of a new geometry
        compiles inside the tick; see cfg.prewarm to move that to boot) —
        it flags a wedged loop, not a busy one.
        """
        import jax

        tick_alive = self._thread is not None and self._thread.is_alive()
        drain_alive = (
            self._drain_thread is not None and self._drain_thread.is_alive()
        )
        # Every stage of the pipeline must live: a dead drain thread backs
        # the queue up and silently stops every emission even while ticks
        # keep completing; a dead transfer thread starves every dispatch
        # at the placement pop the same way.
        xfer_alive = self._xfer is None or self._xfer.alive()
        alive = tick_alive and drain_alive and xfer_alive
        now = time.monotonic()
        age = (now - self.last_tick_monotonic) if self.last_tick_monotonic else None
        with self._probe_spawn_lock:
            # Check-then-spawn under a lock, inputs re-read inside it:
            # concurrent /healthz polls must not each start a probe thread
            # (one would become untracked), and a poll that waited on the
            # lock must see the probe the winner's thread just completed.
            now = time.monotonic()
            ts, ok = self._probe_cache
            if (ok is None or now - ts > probe_ttl_s) and (
                self._probe_thread is None or not self._probe_thread.is_alive()
            ):
                self._probe_thread = threading.Thread(
                    target=self._run_probe, name="tpu-health-probe", daemon=True
                )
                self._probe_thread.start()
        if self._probe_thread is not None and self._probe_thread.is_alive():
            self._probe_thread.join(timeout=probe_wait_s)
        _, ok = self._probe_cache
        if self._probe_thread is not None and self._probe_thread.is_alive():
            # Probe outstanding past its wait budget: the runtime is not
            # answering. A stale cached success must not mask that — report
            # unhealthy until the probe actually returns.
            ok = False
        stale_after = self._cfg.health_stale_after_s
        stale = age is not None and age > stale_after
        # Per-stream models currently tripped by the failure breaker:
        # operators see WHY a stream silently serves the default model and
        # when the next half-open retry is due. Informational — does not
        # flip `healthy` (the default model still serves every stream).
        disabled = {
            name: {
                "failures": bad["failures"],
                "retry_in_s": round(max(0.0, bad["retry_at"] - now), 1),
                "error": bad["error"],
            }
            for name, bad in list(self._bad_models.items())
        }
        return {
            "disabled_models": disabled,
            "healthy": bool(alive and ok and not stale),
            "engine_thread_alive": tick_alive,
            "drain_thread_alive": drain_alive,
            "transfer_thread_alive": (
                self._xfer.alive() if self._xfer is not None else None),
            "tick_age_s": round(age, 3) if age is not None else None,
            "tick_stale": stale,
            "device_ok": bool(ok),
            "backend": jax.default_backend(),
            "devices": len(jax.devices()),
            "programs_compiled": len(self._step_cache),
            "model": self._spec.name if self._spec else None,
            "ticks": self.ticks,
        }

    # -- compiled step construction --

    def compile_for(self, src_hw: tuple, bucket: int,
                    model: Optional[str] = None, *,
                    stem: Optional[str] = None) -> None:
        """Prewarm the program for one (source geometry, bucket) — of
        the default model, or of any registry model a stream resolves to
        (``model``; 4-element cfg.prewarm entries). Multi-family fleets
        otherwise pay each extra model's compile stall on its first
        mid-soak frame (the stall r11's harness worked around by
        prewarming downshift buckets for the default model only).

        ``stem`` pins the stem variant a prewarm entry expects
        (5-element cfg.prewarm entries): the engine's stem is a warmup
        decision — params are folded/initialized for exactly one
        variant — so an entry written for the OTHER variant is skipped
        with a warning rather than compiled into an unservable program."""
        effective = getattr(self._cfg, "stem", "classic") or "classic"
        if stem is not None and stem != effective:
            log.warning(
                "prewarm entry pinned stem=%r but engine serves stem=%r; "
                "skipping %sx%s bucket=%d",
                stem, effective, src_hw[0], src_hw[1], bucket,
            )
            return
        spec, _, variables = self._ensure_model(model or self._spec.name)
        shape = (bucket,) + (
            (spec.clip_len,) if spec.clip_len else ()
        ) + tuple(src_hw) + (3,)
        args = [self._place(np.zeros(shape, np.uint8))]
        if self._quality_device and not spec.clip_len:
            side = self._cfg.quality_thumb
            thumbs = np.zeros((bucket, side, side), np.float32)
            # Under a mesh the serving thumbnails arrive dp-sharded (the
            # sharded pool's gather); prewarm with the same sharding or
            # the first real batch would compile a second program.
            args.append(self._place(thumbs) if self._mesh is not None
                        else thumbs)
        self._step(src_hw, bucket, model)(variables, *args)

    def _place(self, frames: np.ndarray):
        """Shard the batch dim over dp when serving on a mesh; pass through
        numpy (implicit single-device transfer) otherwise. Tick-thread
        fallback path — with cfg.prefetch the transfer thread uses
        `_place_device` instead, which always performs the real copy."""
        if self._mesh is None:
            return frames
        from ..parallel import batch_sharding, shard_put

        return shard_put(frames, batch_sharding(self._mesh, frames.ndim))

    def _place_device(self, frames: np.ndarray):
        """Real async H2D placement for the prefetch stage: single-chip
        batches device_put explicitly (the legacy passthrough deferred
        the copy into the step call, serializing it on the tick thread),
        mesh batches shard over dp via ``shard_put`` — one async
        ``device_put`` per mesh slice, issued back-to-back so the S
        copies overlap instead of staging through a single host->chip0
        transfer (r17 tentpole leg 2)."""
        if self._mesh is None:
            import jax

            return jax.device_put(frames)
        from ..parallel import batch_sharding, shard_put

        return shard_put(frames, batch_sharding(self._mesh, frames.ndim))

    def _step(self, src_hw: tuple, bucket: int, model: Optional[str] = None):
        model = model or self._spec.name
        # The key carries the stem-variant axis (round 15): cfg.stem picks
        # a different compiled program (fused vs classic preprocess, 2x2
        # vs 3x3 stem) for the SAME model name — recording it keys every
        # cached program by what it actually computes, so introspection
        # and any future runtime stem flip can never alias the variants.
        key = (model, getattr(self._cfg, "stem", "classic"), src_hw, bucket)
        fn = self._step_cache.get(key)
        if fn is not None:
            self._m_cache_hit.inc()
        else:
            self._m_cache_miss.inc()
            import jax

            spec, mod, _ = self._ensure_model(model)
            raw = build_serving_step(
                mod, spec,
                quality_thumb=(self._cfg.quality_thumb
                               if self._quality_device else 0),
            )
            if self._cfg.quantize:
                from ..models.quantize import dequantize_tree

                base = raw

                def raw(qv, *args, _base=base):
                    # Dequantize inside the program: XLA fuses int8*scale
                    # into each weight's first consumer, HBM stays int8.
                    return _base(dequantize_tree(qv), *args)
            # Donate the frames slot (argnum 1) so XLA reuses the input
            # HBM allocation for outputs instead of allocating a fresh
            # one per tick — aliasing only, numerics (and the replay
            # goldens) are untouched. The thumbnail argument is never
            # donated: its buffer is a gather view of the device-resident
            # pool. "auto" donates only where the backend implements it
            # (the CPU test backend would warn per call and copy anyway).
            donate = ()
            if self._cfg.donate_frames == "on" or (
                    self._cfg.donate_frames == "auto"
                    and jax.default_backend() == "tpu"):
                donate = (1,)
            # Compile attribution (obs/perf.py): the wrapper AOT-compiles
            # on first call, recording wall time + XLA cost analysis per
            # (model, geometry, bucket) — this is the only cache-miss
            # site, so every compile in the process is accounted.
            record = None
            if self._aot_dir:
                # Every serving step lands in the prewarm manifest (this
                # is the only miss site, so the recorded set IS the
                # program set a member must hold) — but only once its
                # FIRST call compiles and executes successfully, or a
                # reliably-failing (geometry, bucket, model) would be
                # replayed (and re-fail) on every future spawn's boot.
                # record_program is internally best-effort (never raises).
                from . import aot_cache

                def record(_dir=self._aot_dir, _model=model,
                           _stem=getattr(self._cfg, "stem", "classic"),
                           _hw=src_hw, _bucket=bucket,
                           _mesh=self._mesh):
                    aot_cache.record_program(
                        _dir, model=_model, stem=_stem,
                        src_hw=_hw, bucket=_bucket, mesh=_mesh)

            fn = _TimedStep(jax.jit(raw, donate_argnums=donate),
                            self.perf, model, src_hw, bucket,
                            on_first_success=record,
                            on_compiled=self._hbm_compile_tap(
                                model, src_hw, bucket))
            self._step_cache[key] = fn
        return fn

    def _hbm_compile_tap(self, model: str, src_hw: tuple, bucket: int):
        """``on_compiled`` callback for a :class:`_TimedStep`: records
        the program's ``memory_analysis()`` footprint (argument/output/
        temp/code bytes, donated aliasing credited) into the HBM plane
        under its (model, stem, geometry, bucket, mesh) key. None when
        cfg.hbm is off — the wrapper then carries no callback at all,
        keeping the kill-switch path bit-identical and free."""
        if self.hbm is None:
            return None
        stem = getattr(self._cfg, "stem", "classic")
        mesh = f"dp{self._shards}" if self._mesh is not None else ""

        def tap(compiled, _model=model, _hw=src_hw, _bucket=bucket,
                _stem=stem, _mesh=mesh):
            from ..obs.perf import memory_summary

            try:
                self.hbm.note_program(
                    _model, _hw, _bucket, memory_summary(compiled),
                    stem=_stem, mesh=_mesh)
            except Exception:     # footprint attribution must never
                log.debug(        # take down the drain thread
                    "hbm compile tap failed", exc_info=True)

        return tap

    # -- engine loop --

    def _run(self) -> None:
        tick_s = self._cfg.tick_ms / 1000.0
        inferred: List[str] = []
        while not self._stop.is_set():
            t0 = time.monotonic()
            # The loop must outlive any single bad batch: a dead engine
            # thread would leave subscribers blocked forever (same
            # log-and-keep-going stance as the reference's worker loops,
            # rtsp_to_rtmp.py:186-187).
            try:
                # Device-fault failover (engine/fault.py, r22): shards
                # marked pending by the dispatch error path or the stall
                # probe fail over HERE, at the top of the tick — the one
                # point where this thread owns every mesh-coupled
                # structure and no dispatch is mid-flight on it.
                if self.faults is not None and self.faults.pending():
                    self._execute_failover()
                # Degradation ladder: one observe per tick (queue depth +
                # last tick's duration vs budget); the rung gates the
                # stages below. Closed-ladder overhead is one comparison.
                # Effective backpressure depth for this tick: raw drain
                # qsize without prefetch; with prefetch, a full queue
                # counts only when the tick thread actually blocked on
                # the handoff since the last observation (see
                # _drain_blocked above).
                depth = self._drain_q.qsize()
                if self._xfer is not None and not self._drain_blocked:
                    depth = min(depth, 1)
                self._drain_blocked = False
                self._bp_depth = depth
                rung = "normal"
                if self.ladder is not None:
                    rung = self.ladder.observe(
                        queue_depth=depth,
                        tick_lag_s=self._last_tick_dur_s,
                        tick_budget_s=tick_s,
                        # SLO-level pressure: a sustained multi-window
                        # budget burn (obs/slo.py) starts shedding before
                        # queues physically back up.
                        slo_burning=(self._slo_burning
                                     and self._cfg.slo_ladder),
                        # Memory-level pressure (r21, obs/hbm.py): shed/
                        # stretch BEFORE the allocator OOMs — a byte
                        # forecast inside the horizon is as real as a
                        # queue backing up. One cached-dict read.
                        hbm_pressure=(self.hbm is not None
                                      and self.hbm.pressure()),
                    )
                    self._apply_rung_cap(rung)
                if self._cascade is not None:
                    # Cadence stretch under pressure (r23): shed
                    # temporal-head FLOPs while the ladder is degraded.
                    # ``inferred`` still holds last tick's stream list —
                    # exactly the streams whose cadence is changing.
                    self._apply_cascade_stretch(rung, inferred)
                if rung == "normal" and self._shed_seq is not None:
                    # Shed excursion closes when the ladder recovers
                    # (edge-triggered journaling, never per-tick).
                    self._close_shed_excursion()
                # One bus enumeration per tick, threaded everywhere.
                present, inferred = self._collector.partition()
                if rung == "admission_pause":
                    # Rung 3: only the admitted half competes for device
                    # slots; the paused half's workers stop decoding too
                    # (keep_streams_hot skips them). Quality-unhealthy
                    # streams (black/frozen — frames with no recoverable
                    # signal) are the first-shed candidates; the canary
                    # is never shed (shedding the integrity probe during
                    # degradation is when its signal matters most).
                    dep: frozenset = frozenset()
                    if (self.quality is not None
                            and self._cfg.quality_ladder):
                        dep = self.quality.unhealthy()
                    canary = (self.canary.stream
                              if self.canary is not None else None)
                    if canary is not None:
                        dep = dep - {canary}
                    admitted = admitted_streams(inferred, dep)
                    if (canary is not None and canary in inferred
                            and canary not in admitted):
                        admitted.append(canary)
                    inferred = admitted
                self._collector.keep_streams_hot(device_ids=inferred)
                groups = self._collector.collect(device_ids=inferred)
                if rung != "normal" and groups:
                    # Rung 1+: stale frames leave before they cost device
                    # time (shed oldest-first with a staleness bound).
                    groups = self._shed_stale_groups(groups)
                if self._roi is not None and groups:
                    # MOSAIC: motion-gate detect streams, pack active
                    # crops onto shared canvases, coast gated-idle
                    # streams (ROADMAP item 1).
                    groups = self._roi_transform(groups)
                t_collect = time.time() if self._cfg.stage_trace else 0.0
                self._dispatch(groups, t_collect)
                if self._cascade is not None:
                    # CASCADE: scatter harvested track tiles, run the
                    # temporal head on cadence ticks, fan out events
                    # (uplink / archive / metrics / spans). A pure tap —
                    # the detect path above never branches on it.
                    self._cascade_tick()
                # Scope per-stream tracker state to streams that still
                # exist: a long-lived engine with churning device_ids must
                # not accumulate IoUTracker entries forever. Absence is
                # debounced (grace period) because a restarting worker
                # re-creates its ring unlink-then-create — one sample in
                # that window must not reset the stream's track-id
                # numbering (invariant in _assign_tracks).
                if self._trackers or self._ann_state or self._thumbs \
                        or (self._roi is not None and self._roi) \
                        or (self._cascade is not None and self._cascade):
                    now = time.monotonic()
                    # GC keys on bus PRESENCE, not on inference_streams():
                    # a live stream gated >grace (inference_model toggled
                    # to "none") must keep its tracker, or re-enabling
                    # would restart track-id numbering and reuse ids
                    # already uplinked for other objects.
                    present = set(present)
                    roi_ids = set(self._roi) if self._roi is not None \
                        else set()
                    casc_ids = set(self._cascade) \
                        if self._cascade is not None else set()
                    with self._state_lock:
                        for d in (set(self._trackers) | set(self._ann_state)
                                  | set(self._thumbs) | roi_ids
                                  | casc_ids):
                            if d in present:
                                self._tracker_absent.pop(d, None)
                                continue
                            since = self._tracker_absent.setdefault(d, now)
                            if now - since > self._TRACKER_GC_GRACE_S:
                                self._trackers.pop(d, None)
                                # Annotation-policy state rides the same
                                # debounced GC: a worker-restart ring gap
                                # must not reset on_change/min_interval
                                # state, but a re-added stream must not
                                # diff against a months-old signature.
                                self._ann_state.pop(d, None)
                                # Quality state too: the device-resident
                                # thumbnail and the verdict machine both
                                # restart cleanly when the stream does
                                # (the tracker re-discards its first
                                # zero-reference diff).
                                self._thumbs.pop(d, None)
                                # ROI gate state restarts with the
                                # stream (first frame re-gates to full).
                                if self._roi is not None:
                                    self._roi.pop(d, None)
                                    self._roi_mode.pop(d, None)
                                # Cascade track state goes with the
                                # stream: device slots free, event
                                # machines clear without firing.
                                if self._cascade is not None:
                                    self._cascade.pop(d, None)
                                if self.quality is not None:
                                    self.quality.forget(d)
                                del self._tracker_absent[d]
            except Exception:
                if self._stop.is_set():
                    # Shutdown races (e.g. a prefetched placement abandoned
                    # mid-dispatch) are expected here — not an error.
                    log.info("engine tick aborted by shutdown")
                else:
                    log.exception("engine tick failed; continuing")
            self.ticks += 1
            self._m_ticks.inc()
            self.last_tick_monotonic = time.monotonic()
            # Tick staleness signal for the ladder: how long the work
            # phase (partition/collect/dispatch) ran, excluding the
            # assembly window that absorbs the remaining budget.
            self._last_tick_dur_s = self.last_tick_monotonic - t0
            self._watch_tick(tick_s, inferred)
            try:
                # Tick remainder = incremental assembly: copy next tick's
                # frames into their batch slots as they arrive (doorbell-
                # woken) instead of sleeping then doing the whole frame
                # plane at collect() time. Falls back to a plain wait on
                # doorbell-less buses.
                self._collector.assemble_until(
                    t0 + tick_s, device_ids=inferred,
                    stop_event=self._stop,
                )
            except Exception:
                log.exception("window assembly failed; continuing")
                elapsed = time.monotonic() - t0
                if elapsed < tick_s:
                    self._stop.wait(tick_s - elapsed)

    def _probe_shards(self) -> List[int]:
        """Default stall probe (engine/fault.py): one tiny H2D+D2H
        round-trip per shard lead device, each bounded by
        ``fault_probe_timeout_ms``. A wedged chip cannot answer — its
        worker thread stays stuck in the fetch (daemon, abandoned) and
        the shard reports faulted. Probes run concurrently so the whole
        sweep is one timeout, not shards-many. ``faults.probe_fn``
        (tests, the chaos soak) replaces this wholesale."""
        import jax

        from ..temporal.state_pool import shard_devices

        timeout_s = self.faults.probe_timeout_ms / 1000.0
        leads = shard_devices(self._mesh, self._shards)
        done = [threading.Event() for _ in leads]

        def roundtrip(dev, ev):
            try:
                x = jax.device_put(np.ones((8,), np.float32), dev)
                if float(np.asarray(x).sum()) == 8.0:
                    ev.set()
            except Exception:
                log.debug("shard probe failed", exc_info=True)

        threads = [
            threading.Thread(target=roundtrip, args=(dev, ev), daemon=True)
            for dev, ev in zip(leads, done)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout_s
        bad: List[int] = []
        for s, ev in enumerate(done):
            if not ev.wait(max(0.0, deadline - time.monotonic())):
                bad.append(s)
        return bad

    def _execute_failover(self) -> None:
        """Survivor-mesh failover (tentpole, engine/fault.py): executed
        at the top of the tick, the one point where this thread owns
        every mesh-coupled structure and nothing is mid-dispatch.
        Bounded end to end by ``fault_failover_budget_ms`` (best-effort:
        each leg is bounded, an over-budget run completes and is
        reported as such rather than abandoned half-swapped).

        Order matters: (1) flush the drain pipeline so no in-flight
        batch still references the old mesh's arrays; (2) rebuild the
        mesh over the survivors IN OLD ORDER — surviving shards keep
        their physical device, which is what lets ``make_repin`` keep
        their stream pins (>= 90% gate holds by construction); (3)
        re-place params, counted-reset the sharded carry state
        (thumbnails, cascade tracks — a dead chip's rows are gone;
        state rebuilds from the stream in ticks, and the ledger records
        the reset instead of pretending), re-pin the collector; (4)
        record + prewarm the survivor-mesh program variants so the AOT
        manifest warms the NEXT failover too."""
        t0 = time.monotonic()
        budget_s = self.faults.failover_budget_ms / 1000.0
        pending = self.faults.pending()
        if self._mesh is None:
            log.error("fault pending with no mesh; clearing: %s", pending)
            self.faults.clear_pending("no_mesh")
            return
        if any(self._mesh.shape.get(a, 1) > 1
               for a in ("fsdp", "sp", "tp", "ep", "pp")):
            # Model-sharded meshes cannot lose a chip without losing
            # parameter shards — failover is a dp-replication feature.
            log.error(
                "device fault on a model-sharded mesh %s; survivor "
                "failover requires dp-only replication — not failing over",
                dict(self._mesh.shape),
            )
            self.faults.clear_pending("unsupported_mesh")
            return
        devs = list(np.asarray(self._mesh.devices).reshape(-1))
        dead = sorted(s for s in pending if 0 <= int(s) < len(devs))
        if not dead:
            log.error("pending fault shards %s out of range; clearing",
                      pending)
            self.faults.clear_pending("unattributed")
            return
        survivors = [d for s, d in enumerate(devs) if s not in set(dead)]
        if not survivors:
            log.error("all %d shards faulted; no survivor mesh — engine "
                      "keeps the old mesh and the faults stay visible in "
                      "/api/v1/faults", len(devs))
            self.faults.clear_pending("no_survivors")
            return
        kinds = sorted(set(pending.values()))
        log.warning(
            "FAILOVER: shards %s faulted (%s); rebuilding dp%d -> dp%d",
            dead, ",".join(kinds), len(devs), len(survivors),
        )
        # (1) Bounded drain flush: in-flight batches hold old-mesh
        # arrays (and pooled-buffer leases). Half the budget at most —
        # a wedged chip's fetch never finishes, and its batch is the
        # drain thread's to drop (drain_error, counted).
        flush_deadline = t0 + budget_s / 2.0
        while self._drain_q.unfinished_tasks \
                and time.monotonic() < flush_deadline \
                and not self._stop.is_set():
            time.sleep(0.01)
        if self._drain_q.unfinished_tasks:
            log.warning(
                "drain pipeline did not flush within %.0f ms; proceeding "
                "(stuck batches drop as drain_error)",
                budget_s * 500.0,
            )
        from ..parallel import make_mesh
        from ..temporal.state_pool import shard_devices
        from .collector import make_repin

        old_shards = self._shards
        old_shard_of = self._shard_of
        old_keys = list(self._step_cache.keys())
        # Stream census BEFORE the swap: pin = home shard's device under
        # the old routing, kept = that device survived (same stream ->
        # same chip after the swap, by survivor ordering).
        streams = list(self._collector.inference_streams())
        kept = sum(1 for did in streams
                   if old_shard_of(did) % old_shards not in set(dead))
        new_shards = len(survivors)
        new_mesh = make_mesh(dp=new_shards, devices=survivors)
        repin = make_repin(old_shard_of, old_shards, dead)
        new_buckets = tuple(
            b for b in self._cfg.batch_buckets if b % new_shards == 0
        ) or (new_shards,)
        # (2) The swap. Step cache first: every cached program was
        # compiled for the old mesh's sharding.
        self._step_cache.clear()
        self._mesh = new_mesh
        self._shards = new_shards
        self._shard_of = repin
        self._buckets = new_buckets
        if self._xfer is not None:
            self._xfer.reset(new_shards)
        # (3) Params back onto the survivor mesh. dp-only means fully
        # replicated — every survivor holds a complete copy, so
        # re-placement never needs the dead chip's buffers.
        for name in list(self._models):
            spec, mod, variables = self._models[name]
            try:
                variables = self._place_variables(variables)
            except Exception:
                log.exception(
                    "re-placing model '%s' on the survivor mesh failed; "
                    "keeping old placement (XLA will re-shard lazily)",
                    name,
                )
            self._models[name] = (spec, mod, variables)
            if self._spec is not None and name == self._spec.name:
                self._variables = variables
        evacuated: Dict[str, int] = {}
        if isinstance(self._thumbs, _ShardedThumbPool):
            evacuated["quality_thumbs"] = len(self._thumbs)
            self._thumbs = _ShardedThumbPool(
                self._cfg.quality_thumb, mesh=new_mesh, shards=new_shards,
                shard_of=repin,
            )
        if self._cascade is not None:
            try:
                evacuated.update(self._cascade.repin_mesh(
                    mesh=new_mesh, shards=new_shards, shard_of=repin,
                ))
            except Exception:
                log.exception("cascade re-pin failed; state dropped")
        self._collector.repin(
            shards=new_shards, shard_of=repin, buckets=new_buckets,
        )
        self.faults.configure(shards=new_shards, shard_devices={
            s: [str(d)]
            for s, d in enumerate(shard_devices(new_mesh, new_shards))
        })
        # (4) AOT: stamp the survivor-mesh variants of every program the
        # old mesh served into the manifest, then prewarm whatever the
        # manifest already holds for THIS mesh spec (a previous failover
        # to the same survivor count recorded them — warm hit).
        aot = {"recorded": 0, "prewarmed": 0}
        if self._aot_dir:
            from . import aot_cache

            seen = set()
            for (model, stem, hw, _bucket) in old_keys:
                for b in new_buckets:
                    if (model, stem, hw, b) in seen:
                        continue
                    seen.add((model, stem, hw, b))
                    aot_cache.record_program(
                        self._aot_dir, model=model, stem=stem,
                        src_hw=hw, bucket=b, mesh=new_mesh,
                    )
                    aot["recorded"] += 1
            programs = aot_cache.load_manifest(self._aot_dir) or []
            for entry in aot_cache.prewarm_entries(programs,
                                                   mesh=new_mesh):
                try:
                    h, w, bucket = (int(v) for v in entry[:3])
                    if bucket not in self._buckets:
                        continue
                    self.compile_for(
                        (h, w), bucket, str(entry[3]) or None,
                        stem=str(entry[4]) if entry[4] else None,
                    )
                    aot["prewarmed"] += 1
                except Exception:
                    log.exception("survivor prewarm %r failed; continuing",
                                  entry)
        failover_ms = (time.monotonic() - t0) * 1000.0
        event = {
            "ts": time.time(),
            "tick": self.ticks,
            "kinds": kinds,
            "shards_dead": dead,
            "survivors": new_shards,
            "failover_ms": failover_ms,
            "over_budget": failover_ms > self.faults.failover_budget_ms,
            "evacuated": evacuated,
            "streams": {
                "total": len(streams),
                "kept": kept,
                "repinned": len(streams) - kept,
            },
            "aot": aot,
        }
        self.faults.note_failover(event)
        log.warning(
            "FAILOVER complete in %.0f ms: dp%d over %s; %d/%d stream "
            "pins kept, evacuated=%s, aot=%s",
            failover_ms, new_shards, [str(d) for d in survivors],
            kept, len(streams), evacuated, aot,
        )

    def _dispatch(self, groups: List[BatchGroup], t_collect: float) -> None:
        """Dispatch one tick's collected groups to the device.

        With cfg.prefetch the placement of group g+1 (and g+2) runs on
        the transfer thread while this thread dispatches group g and the
        device computes earlier batches — H2D accounting (ROADMAP item 5
        evidence) then times the REAL async device_put on the transfer
        thread, and splits off the hidden share: the copy wall time that
        overlapped in-flight device work, plus whatever share this
        thread did not have to wait out at the pop. Without prefetch the
        legacy synchronous path remains (mesh: real device_put; single
        device: numpy handoff whose transfer hides inside the async
        dispatch) — either way bytes-per-frame stays exact.

        A dispatch failure aborts the tick; every group not yet handed
        to the drain thread (this one AND the ones after it, including
        batches still in flight on the transfer thread) must return its
        lease, or a persistently failing model leaks one pooled buffer
        per tick until the pool failsafe churns. Prefetched leases are
        returned only after their transfer handle resolves — the copy
        may still be reading the pooled host buffer.
        """
        trace_on = tracer.enabled
        if self.faults is not None:
            # FaultLedger conservation: every stream slot entering the
            # device pipeline is counted in here and counted out in the
            # emit paths (or as a reasoned drop) — the balance the
            # failover gates check.
            for g in groups:
                self.faults.ledger.note_dispatched(_group_slots(g))
        if self._roi is not None and groups:
            # Tracker-coasted groups (gated-idle streams): no device
            # work, but they ride the drain queue so per-stream emit
            # ordering against earlier in-flight batches is preserved.
            rest = []
            for g in groups:
                if g.coast is not None:
                    self._enqueue_drain(
                        _Inflight(g, None, time.time(), t_collect))
                else:
                    rest.append(g)
            groups = rest
        handles: List[Optional[_Prefetched]] = []

        def _top_up(upto: int) -> None:
            while len(handles) < min(len(groups), upto):
                handles.append(
                    self._xfer.submit(groups[len(handles)], self._stop)
                )
        if self._xfer is not None and groups:
            _top_up(_PrefetchStage.DEPTH)
        for gi, group in enumerate(groups):
            try:
                step = self._step(group.src_hw, group.bucket, group.model)
                _, _, variables = self._ensure_model(
                    group.model or self._spec.name
                )
                if self._xfer is not None:
                    _top_up(gi + 1 + _PrefetchStage.DEPTH)
                    pre = handles[gi]
                    if pre is None:   # shutdown aborted the submission
                        raise RuntimeError(
                            "engine stopping; prefetch submission aborted")
                    t_wait = time.perf_counter()
                    while not pre.ready.wait(timeout=0.1):
                        if self._stop.is_set():
                            raise RuntimeError(
                                "engine stopping; prefetched placement "
                                "abandoned")
                    wait_s = time.perf_counter() - t_wait
                    if pre.error is not None:
                        raise pre.error
                    placed = pre.placed
                    h2d_s = pre.transfer_s
                    # Hidden share: fully overlapped when device work was
                    # in flight during the copy; otherwise the part this
                    # thread did not spend blocked on the handle (it was
                    # dispatching earlier groups meanwhile).
                    hidden_s = max(pre.overlapped_s,
                                   max(0.0, pre.transfer_s - wait_s))
                else:
                    t_h2d = time.perf_counter()
                    placed = self._place(group.frames)
                    h2d_s = time.perf_counter() - t_h2d
                    hidden_s = 0.0
                idx = None
                aux_nbytes = 0
                # Canvas groups (group.crops) never carry quality state:
                # their synthetic _canvas<i> ids must not claim thumbnail
                # pool rows, and a canvas "frame" has no per-stream diff
                # meaning anyway (full-frame refreshes keep the signal).
                if self._quality_device and group.frames.ndim == 4 \
                        and group.crops is None:
                    idx = self._thumbs.gather_indices(
                        group.device_ids, group.bucket, rows=group.rows)
                    aux_nbytes = (
                        sum(int(a.nbytes) for a in idx)
                        if isinstance(idx, list) else int(idx.nbytes)
                    )
                self.perf.note_h2d(
                    group.model or self._spec.name, group.bucket,
                    group.nbytes + aux_nbytes, h2d_s, hidden_s=hidden_s,
                )
                if idx is not None:
                    # Quality-carrying step (3-arg): previous-tick
                    # thumbnails arrive as a device-side gather from the
                    # resident pool (no host rows cross); this tick's
                    # rows scatter back for the next diff. The pop keeps
                    # them out of _emit's D2H fetch.
                    outputs = dict(step(
                        variables, placed, self._thumbs.gather(idx),
                    ))
                    self._thumbs.scatter(
                        group.device_ids, outputs.pop("quality_thumbs"),
                        rows=group.rows)
                else:
                    outputs = step(variables, placed)
                    if group.crops is not None and isinstance(outputs, dict):
                        # Quality-carrying steps still compute stats for
                        # the canvas batch (same compiled program); they
                        # are meaningless per-stream — drop them before
                        # _emit's D2H fetch.
                        outputs = dict(outputs)
                        outputs.pop("quality_stats", None)
                        outputs.pop("quality_thumbs", None)
            except Exception as exc:
                shard = None
                if self.faults is not None:
                    # Classify before the lease sweep: an XLA error that
                    # names a shard's device (or carries fault_shard)
                    # arms the failover the next tick picks up, and the
                    # dropped slots below are attributed to it.
                    shard = self.faults.note_error(exc, self.ticks)
                reason = ("device_fault" if shard is not None
                          else "dispatch_error")
                for gj in range(gi, len(groups)):
                    if gj < len(handles) and handles[gj] is not None:
                        # Bounded: block_until_ready in the transfer loop
                        # keeps this short, and an unresolved handle means
                        # the copy may still be reading the host buffer.
                        handles[gj].ready.wait(timeout=5.0)
                    self._collector.release(groups[gj])
                    if self.faults is not None:
                        self.faults.note_dropped(
                            _group_slots(groups[gj]), reason)
                    if trace_on:
                        for did, m in zip(groups[gj].device_ids,
                                          groups[gj].metas):
                            if tracer.sampled(m.packet):
                                tracer.record(
                                    did, "dropped", m.packet,
                                    reason=reason,
                                    trace_id=trace_id_of(m, did),
                                )
                raise
            self.batches += 1
            self._m_batches.inc()
            self._m_occupancy.observe(
                100.0 * len(group.device_ids) / group.bucket
            )
            t_submit = time.time()
            if trace_on:
                for did, meta in zip(group.device_ids, group.metas):
                    if tracer.sampled(meta.packet):
                        tracer.record(
                            did, "submit", meta.packet,
                            ts=t_submit, bucket=group.bucket,
                            trace_id=trace_id_of(meta, did),
                        )
            self._enqueue_drain(
                _Inflight(group, outputs, t_submit, t_collect)
            )

    def _apply_rung_cap(self, rung: str) -> None:
        """bucket_downshift and above: hide the largest batch bucket so
        new batches run the next-smaller (cheaper, typically
        already-compiled) device program; below it the cap clears.
        Keyed on the rung NAME, not a raw index — r16 inserted
        shed_to_fleet between shed and bucket_downshift, and a
        fleet-shedding engine must NOT also be shrinking its programs
        (horizontal re-placement engages before vertical degradation)."""
        cap = None
        if _RUNG_IDX[rung] >= _RUNG_IDX["bucket_downshift"] \
                and len(self._buckets) > 1:
            cap = self._buckets[-2]
        self._collector.set_bucket_cap(cap)

    def _shed_stale_groups(self, groups: List[BatchGroup]) -> List[BatchGroup]:
        """Apply rung 1 shedding to this tick's groups (see shed_stale);
        fully-stale groups return their pooled-buffer lease here."""
        now_ms = time.time() * 1000.0
        out: List[BatchGroup] = []
        tick_shed = 0
        for group in groups:
            kept, shed = shed_stale(
                group, now_ms, self._cfg.shed_staleness_ms, self._buckets,
                shards=self._shards,
            )
            if shed:
                self.shed_frames += shed
                tick_shed += shed
                self._m_shed.inc(shed)
            if kept is None:
                self._collector.release(group)
            else:
                out.append(kept)
        # r23 journal: one shed excursion event per degraded episode
        # (opened on the first frame actually dropped, closed when the
        # ladder recovers in _run), caused by the ladder transition that
        # engaged shedding — never one event per tick.
        if tick_shed and self.journal is not None:
            if self._shed_seq is None:
                self._shed_seq = self.journal.record(
                    "engine", "shed_open",
                    subject=("engine", "dispatch"),
                    trigger={"frames": tick_shed,
                             "staleness_ms": self._cfg.shed_staleness_ms},
                    cause=(self.ladder.last_transition_seq
                           if self.ladder is not None else None))
                self._shed_excursion_frames = 0
            self._shed_excursion_frames += tick_shed
        return out

    def _close_shed_excursion(self) -> None:
        """Close the open shed excursion (ladder back at normal)."""
        if self.journal is not None and self._shed_seq is not None:
            self.journal.record(
                "engine", "shed_close", subject=("engine", "dispatch"),
                trigger={"frames": self._shed_excursion_frames},
                cause=self._shed_seq)
        self._shed_seq = None
        self._shed_excursion_frames = 0

    def _apply_cascade_stretch(self, rung: str, streams) -> None:
        """Cascade cadence stretch (r23): while the degradation ladder
        sits at shed or deeper, the temporal head dispatches every
        ``every_n * cascade_stretch_factor`` ticks instead of every
        ``every_n`` — head FLOPs shed before streams do. Journaled on
        the EDGE only (engage/release), with a per-stream event so
        ``/api/v1/why?stream=S`` resolves the stream's cadence back
        through the ladder transition to the SLO burn that drove it."""
        factor = (self._cfg.cascade_stretch_factor
                  if rung != "normal" else 1)
        if not self._cascade.set_stretch(factor):
            return
        action = ("cascade_stretch" if factor > 1
                  else "cascade_unstretch")
        seq = None
        if self.journal is not None:
            cause = (self.ladder.last_transition_seq
                     if self.ladder is not None else None)
            trigger = {"rung": rung, "factor": factor,
                       "every_n": self._cascade.every_n}
            seq = self.journal.record(
                "engine", action, subject=("cascade", "head"),
                trigger=trigger, cause=cause)
            for sid in sorted(set(streams or [])):
                self.journal.record(
                    "engine", action, subject=("stream", str(sid)),
                    trigger=dict(trigger), cause=cause)
        log.info("cascade cadence %s: every_n %d x%d (rung %s)",
                 "stretched" if factor > 1 else "restored",
                 self._cascade.every_n, factor, rung,
                 extra={"vep_actor": "engine",
                        "vep_subject": "cascade:head",
                        "vep_journal_seq": seq})

    # -- MOSAIC ROI serving (cfg.roi; ROADMAP item 1) --

    def _roi_transform(self, groups: List[BatchGroup]) -> List[BatchGroup]:
        """Motion-gate each detect group's rows and rewrite the tick's
        work: ``full`` rows stay classic full frames (compacted in place,
        shed_stale discipline — the lease rides with them), ``roi`` rows
        become crops shelf-packed onto shared canvases (one synthetic
        canvas group per tick, lease-free copies), ``idle`` rows become a
        tracker-coasted group with no device work at all.

        Ordering matters twice: crops blit (copy) out of the pooled
        buffer BEFORE full rows compact (compaction moves rows upward
        within the same view), and classification runs under
        ``_state_lock`` because the drain thread feeds the gate (diff
        energy, full-frame stamps) and trackers concurrently. Groups
        that are not full-frame detect batches (clip inputs, embed/
        classify models, already-transformed groups) pass through
        untouched — with cfg.roi=False this method is never called and
        the classic path is bit-identical (test-pinned)."""
        out: List[BatchGroup] = []
        for group in groups:
            model = group.model or self._spec.name
            entry = self._models.get(model)
            spec = entry[0] if entry is not None else None
            if (spec is None or spec.kind != "detect"
                    or group.frames.ndim != 4
                    or group.crops is not None or group.coast is not None):
                out.append(group)
                continue
            now = time.monotonic()
            full_rows: List[int] = []
            coast: List[tuple] = []
            reqs: List[tuple] = []    # CanvasPacker requests
            req_row: List[int] = []   # request index -> group row
            roi_edges: List[tuple] = []   # r23 journal: mode transitions
            with self._state_lock:
                for i, device_id in enumerate(group.device_ids):
                    t_entry = self._trackers.get(device_id)
                    tracker = (
                        t_entry[1]
                        if t_entry is not None and t_entry[0] == spec.name
                        else None
                    )
                    verdict = self._roi.classify(device_id, tracker, now)
                    if (self.journal is not None
                            and self._roi_mode.get(device_id) != verdict):
                        roi_edges.append(
                            (device_id,
                             self._roi_mode.get(device_id), verdict))
                        self._roi_mode[device_id] = verdict
                    if verdict == "idle":
                        coast.append((
                            device_id, group.metas[i],
                            self._coasted_detections(tracker, spec),
                        ))
                        continue
                    rects = (self._track_rois(tracker)
                             if verdict == "roi" else [])
                    if rects:
                        # Frames live at the GLOBAL row under the
                        # shard-segmented layout, not the slot index —
                        # blitting by slot cuts another stream's pixels
                        # whenever per-shard occupancy is unequal.
                        fr = (group.rows[i] if group.rows is not None
                              else i)
                        for rect in rects:
                            reqs.append((device_id, group.metas[i],
                                         group.frames[fr], rect))
                            req_row.append(i)
                    else:
                        full_rows.append(i)
            # Journal ROI gate transitions outside the state lock —
            # edge-triggered (a stream flipping full/roi/idle), so gate
            # steady state records nothing.
            for device_id, prev, verdict in roi_edges:
                self.journal.record(
                    "engine", "roi_mode",
                    subject=("stream", str(device_id)),
                    trigger={"mode": verdict, "prev": prev or "none"})
            if not coast and not reqs:
                # Everything full: the group passes through untouched.
                # Still count the verdicts — synchronized refresh ticks
                # (streams primed together expire together) would
                # otherwise vanish from gated_stream_pct.
                self.perf.note_roi_gate(0, 0, len(group.device_ids))
                out.append(group)
                continue
            placements: list = []
            cgroup: Optional[BatchGroup] = None
            n_used = 0
            if reqs and self._shards > 1 and group.rows is not None:
                # r17 mesh serving: canvases pack per mesh slice so the
                # scatter-back routing table stays shard-local.
                cgroup, placements, full_rows, n_used = (
                    self._pack_canvases_sharded(group, reqs, req_row,
                                                full_rows))
            elif reqs:
                canvases, placements, overflow = self._packer.pack(reqs)
                if overflow:
                    # Crops that did not fit fall back to the full-frame
                    # path. ALL of a spilled stream's placements leave
                    # the routing table too — a stream must never emit
                    # twice in one tick, so its already-placed crops'
                    # canvas detections drop as unrouted (rare, counted).
                    spill = {reqs[ri][0] for ri in overflow}
                    placements = [p for p in placements
                                  if p.device_id not in spill]
                    spill_rows = sorted(
                        {req_row[ri] for ri in range(len(reqs))
                         if reqs[ri][0] in spill})
                    full_rows = sorted(set(full_rows) | set(spill_rows))
            self.perf.note_roi_gate(
                len(coast), len({p.device_id for p in placements}),
                len(full_rows))
            if placements and cgroup is None:
                side = self._packer.side
                n_used = 1 + max(p.canvas for p in placements)
                metas = []
                for ci in range(n_used):
                    pts = [p.meta.timestamp_ms or 0
                           for p in placements if p.canvas == ci]
                    # Latency accounting for the canvas batch follows its
                    # oldest member; per-stream latency uses each crop's
                    # own meta at scatter-back.
                    metas.append(FrameMeta(
                        width=side, height=side, channels=3,
                        timestamp_ms=min(pts) if pts else 0,
                    ))
                cgroup = pad_to_bucket(BatchGroup(
                    src_hw=(side, side),
                    device_ids=[f"_canvas{ci}" for ci in range(n_used)],
                    frames=canvases[:n_used],
                    metas=metas,
                    model=group.model,
                    crops=placements,
                ), self._buckets)
            if cgroup is not None:
                out.append(cgroup)
                self.perf.note_roi_pack(
                    len(placements), n_used,
                    CanvasPacker.area_fraction(placements, n_used,
                                               self._packer.side))
            if coast:
                out.append(BatchGroup(
                    src_hw=group.src_hw,
                    device_ids=[c[0] for c in coast],
                    frames=np.empty((0,) + group.frames.shape[1:],
                                    group.frames.dtype),
                    metas=[c[1] for c in coast],
                    bucket=0,
                    model=group.model,
                    coast=coast,
                ))
            if full_rows and group.rows is not None and self._shards > 1:
                out.append(_compact_sharded(
                    group, full_rows, self._buckets, self._shards))
            elif full_rows:
                for new_i, old_i in enumerate(full_rows):
                    if new_i != old_i:
                        group.frames[new_i] = group.frames[old_i]
                group.device_ids = [group.device_ids[i] for i in full_rows]
                group.metas = [group.metas[i] for i in full_rows]
                n = len(full_rows)
                bucket = next(b for b in sorted(self._buckets) if b >= n)
                view = group.frames[:bucket]
                if bucket != n:
                    view[n:] = 0
                group.frames = view
                group.bucket = bucket
                out.append(group)
            else:
                # No full rows survive: the pooled buffer goes back now
                # (canvases and coast groups hold copies, not views).
                self._collector.release(group)
        return out

    def _pack_canvases_sharded(self, group: BatchGroup, reqs, req_row,
                               full_rows):
        """MOSAIC packing under mesh serving (r17 tentpole leg 3): each
        dp shard's crops shelf-pack onto that shard's OWN canvases, and
        the canvas batch is emitted in the shard-segmented row layout —
        a canvas only ever carries crops of streams its chip serves, so
        the scatter-back routing table is shard-local by construction
        (the single-chip assumption the old auto-disable guarded).

        Returns ``(canvas group or None, kept placements, updated
        full_rows, used canvas rows)``. Spilled streams fall back to the
        full-frame path exactly like the single-chip branch; if no
        bucket segment can hold a shard's canvas count, the whole
        request set falls back (rare — counted as full rows)."""
        import dataclasses

        S = self._shards
        by_shard: Dict[int, List[int]] = {}
        for ri, req in enumerate(reqs):
            by_shard.setdefault(self._shard_of(req[0]) % S, []).append(ri)
        packed: Dict[int, tuple] = {}
        spill: set = set()
        for s, ris in sorted(by_shard.items()):
            canvases, placements, overflow = self._packer.pack(
                [reqs[ri] for ri in ris])
            if overflow:
                spill |= {reqs[ris[oi]][0] for oi in overflow}
            packed[s] = (canvases, placements)
        if spill:
            spill_rows = {req_row[ri] for ri in range(len(reqs))
                          if reqs[ri][0] in spill}
            full_rows = sorted(set(full_rows) | spill_rows)
        n_by_shard: Dict[int, int] = {}
        for s, (canvases, placements) in packed.items():
            kept = [p for p in placements if p.device_id not in spill]
            packed[s] = (canvases, kept)
            n_by_shard[s] = (1 + max(p.canvas for p in kept)) if kept else 0
        k_max = max(n_by_shard.values(), default=0)
        if k_max == 0:
            return None, [], full_rows, 0
        bucket = next(
            (b for b in sorted(self._buckets)
             if b % S == 0 and b // S >= k_max), None)
        if bucket is None:
            rows_all = {req_row[ri] for ri in range(len(reqs))}
            return None, [], sorted(set(full_rows) | rows_all), 0
        seg = bucket // S
        side = self._packer.side
        frames = np.zeros((bucket, side, side, 3), np.uint8)
        rows: List[int] = []
        device_ids: List[str] = []
        metas: List[FrameMeta] = []
        out_placements: list = []
        for s, (canvases, kept) in sorted(packed.items()):
            if not kept:
                continue
            n_used = n_by_shard[s]
            frames[s * seg:s * seg + n_used] = canvases[:n_used]
            for ci in range(n_used):
                r = s * seg + ci
                pts = [p.meta.timestamp_ms or 0
                       for p in kept if p.canvas == ci]
                rows.append(r)
                device_ids.append(f"_canvas{r}")
                metas.append(FrameMeta(
                    width=side, height=side, channels=3,
                    timestamp_ms=min(pts) if pts else 0,
                ))
            # Placement canvas indices become GLOBAL batch rows so the
            # scatter-back router addresses host outputs directly.
            out_placements.extend(
                dataclasses.replace(p, canvas=s * seg + p.canvas)
                for p in kept)
        cgroup = BatchGroup(
            src_hw=(side, side),
            device_ids=device_ids,
            frames=frames,
            metas=metas,
            bucket=bucket,
            model=group.model,
            crops=out_placements,
            rows=rows,
        )
        return cgroup, out_placements, full_rows, len(rows)

    def _coasted_detections(self, tracker, spec) -> List[pb.Detection]:
        """Gated-idle emission: advance the stream's tracker one frame
        with no detections (misses age, so stale tracks still expire
        while the stream is gated) and render the surviving predicted
        boxes as detections with geometrically decayed confidence.
        Caller holds ``_state_lock``."""
        if tracker is None:
            return []
        tracker.update([], [])
        decay = self._cfg.roi_coast_decay
        floor = self._cfg.roi_coast_floor
        n_classes = self._num_classes(spec)
        out: List[pb.Detection] = []
        for t in tracker.tracks():
            conf = t["confidence"] * decay ** max(t["misses"], 1)
            if conf < floor:
                continue
            x1, y1, x2, y2 = (int(round(v)) for v in t["box"])
            det = pb.Detection(
                box=pb.BoundingBox(left=x1, top=y1,
                                   width=x2 - x1, height=y2 - y1),
                confidence=float(conf),
                class_id=t["class_id"],
                class_name=class_name(t["class_id"], n_classes),
            )
            det.track_id = str(t["track_id"])
            out.append(det)
        return out

    def _track_rois(self, tracker) -> List[tuple]:
        """Candidate crop rectangles for a tracked stream: predicted
        track boxes inflated by cfg.roi_margin (context for the detector
        + slack for motion since the prediction), then overlapping
        rects merged to a common hull — one object must never appear in
        two crops of the same stream (double detection after
        scatter-back). Caller holds ``_state_lock``."""
        if tracker is None:
            return []
        margin = self._cfg.roi_margin
        rects: List[list] = []
        for t in tracker.tracks():
            x1, y1, x2, y2 = t["box"]
            mw = (x2 - x1) * margin
            mh = (y2 - y1) * margin
            rects.append([x1 - mw, y1 - mh, x2 + mw, y2 + mh])
        merged = True
        while merged:
            merged = False
            folded: List[list] = []
            for r in rects:
                for o in folded:
                    if (r[0] < o[2] and o[0] < r[2]
                            and r[1] < o[3] and o[1] < r[3]):
                        o[0] = min(o[0], r[0])
                        o[1] = min(o[1], r[1])
                        o[2] = max(o[2], r[2])
                        o[3] = max(o[3], r[3])
                        merged = True
                        break
                else:
                    folded.append(list(r))
            rects = folded
        return [tuple(r) for r in rects]

    def _watch_tick(self, tick_s: float,
                    inferred: Sequence[str] = ()) -> None:
        """Per-tick watermark checks (obs/watch.py): each warns once per
        episode, so a stalled device or recompile storm surfaces as ONE
        log line, not one per tick. Also feeds the per-tick SLO samples
        (fps, availability) and runs the throttled SLO evaluation."""
        self._m_drain_depth.set(self._drain_q.qsize())   # raw, for dashboards
        self.watchdog.check(
            # Effective depth (computed in _run): prefetch keeps the
            # queue full by design, so only a blocked handoff counts.
            "drain_backpressure", self._bp_depth, above=1,
            detail="device slower than the tick loop (double buffer full)",
        )
        # Recompile storm: a step-cache miss on N consecutive ticks means
        # shapes are churning faster than the cache warms (the exact
        # pathology bucketed batching exists to prevent).
        misses = self._m_cache_miss.value
        self._miss_streak = (
            self._miss_streak + 1 if misses > self._miss_seen else 0
        )
        self._miss_seen = misses
        self.watchdog.check(
            "recompile_storm", self._miss_streak, above=2,
            detail="step-cache miss on 3+ consecutive ticks (shape churn)",
        )
        if self.slo is not None:
            self._slo_tick(inferred)
        if self.prof is not None:
            # Burn-triggered profiling (obs/prof.py): fires at most one
            # bounded capture per new SLO episode / ladder escalation,
            # rate-limited, on its own thread. Idle cost: integer
            # compares under a lock.
            rung_idx = (self.ladder.rung_index
                        if self.ladder is not None else 0)
            self.prof.poll(
                episodes=self._slo_episodes,
                rung=rung_idx,
                context={
                    "slo_episode": self._slo_episodes or None,
                    "slo_burning": self._slo_burning,
                    "rung": RUNGS[rung_idx],
                },
            )
        if self.capacity is not None:
            # Throttled internally to capacity_eval_interval_s — per-tick
            # cost between refreshes is one clock read and a compare.
            self.capacity.evaluate()
        if self.hbm is not None:
            # Same stance for the byte ledger: the registered pool
            # callables are metadata reads, and between refreshes the
            # per-tick cost is one clock read and a compare.
            self.hbm.evaluate()
        if self.faults is not None and self.faults.stall_suspected():
            # Stall attribution (tick thread — the drain thread only
            # raised the suspicion): probe each shard's lead device with
            # a bounded round-trip; shards that fail become pending and
            # fail over at the top of the next tick. An unattributed
            # stall (every probe passes — generic contention, not a dead
            # chip) resolves the suspicion without a failover.
            try:
                probe = self.faults.probe_fn or self._probe_shards
                bad = probe()
            except Exception:
                log.exception("shard fault probe failed; unattributed")
                bad = []
            marked = self.faults.resolve_stall(bad, self.ticks)
            if marked:
                log.warning(
                    "device stall attributed to shard(s) %s; failover "
                    "pending", marked,
                )
            else:
                log.warning(
                    "dispatch deadline overruns resolved unattributed "
                    "(all shard probes healthy)")

    def _slo_tick(self, inferred: Sequence[str]) -> None:
        """Per-tick SLO sampling + throttled evaluation (obs/slo.py).

        Only sampled while streams are inferred: an idle engine (no
        cameras) has no fps/availability objective to miss, so it must
        never build ladder pressure. Recording is ring index math;
        the window-scan evaluation runs at most once per
        slo_eval_interval_s so the tick loop never pays it per tick.
        """
        now = time.monotonic()
        if inferred:
            if self._cfg.slo_target_fps > 0:
                good = self.perf.fps() >= self._cfg.slo_target_fps
                self._slo_fps.record(good=1.0 if good else 0.0,
                                     bad=0.0 if good else 1.0)
            window = self._cfg.slo_availability_window_s
            for device_id in inferred:
                st = self._stats.get(device_id)
                if st is None or not st.last_emit_mono:
                    continue   # never served yet: boot grace, not an SLI
                ok = now - st.last_emit_mono <= window
                self._slo_avail.record(good=1.0 if ok else 0.0,
                                       bad=0.0 if ok else 1.0)
        if now >= self._slo_next_eval:
            self._slo_next_eval = now + self._cfg.slo_eval_interval_s
            verdict = self.slo.evaluate()
            self._slo_burning = verdict["burning"]
            # Cumulative episode count across all SLOs: the prof trigger
            # watermark (one capture per newly-opened episode).
            self._slo_episodes = sum(
                s["episodes"] for s in verdict["slos"].values()
            )

    def _enqueue_drain(self, inflight: _Inflight) -> None:
        """Hand a dispatched batch to the drain thread. Blocks (in short
        interruptible slices) when the pipeline is 2 deep — backpressure,
        not unbounded in-flight growth. On shutdown while full, the
        batch's result is dropped but its buffer lease is returned."""
        try:
            self._drain_q.put_nowait(inflight)
            return
        except queue.Full:
            # The ladder/watchdog backpressure signal under prefetch:
            # the device did NOT absorb the pipeline this tick.
            self._drain_blocked = True
        while not self._stop.is_set():
            try:
                self._drain_q.put(inflight, timeout=0.1)
                return
            except queue.Full:
                continue
        if tracer.enabled:
            for did, m in zip(inflight.group.device_ids,
                              inflight.group.metas):
                if tracer.sampled(m.packet):
                    tracer.record(did, "dropped", m.packet,
                                  reason="shutdown_drain",
                                  trace_id=trace_id_of(m, did))
        if self.faults is not None:
            self.faults.note_dropped(
                _group_slots(inflight.group), "shutdown_drain")
        self._collector.release(inflight.group)

    def _drain_loop(self) -> None:
        """Event-driven drain (VERDICT r4 next #1): block on the oldest
        in-flight batch's device outputs and emit the moment they are
        ready, instead of parking finished results until the next tick
        boundary (which taxed every result a full tick_ms by design)."""
        while True:
            inflight = self._drain_q.get()
            if inflight is None:
                self._drain_q.task_done()
                return
            try:
                self._emit(inflight)
            except Exception:
                log.exception("drain failed; continuing")
                if self.faults is not None:
                    # Conservative: a partial emission still counts the
                    # whole group dropped — the ledger's lost figure can
                    # only understate health, never hide a loss.
                    self.faults.note_dropped(
                        _group_slots(inflight.group), "drain_error")
            finally:
                self._collector.release(inflight.group)
                # Closes the in-flight window the prefetch stage's
                # "busy" signal (hidden-transfer attribution) reads.
                self._drain_q.task_done()

    # -- result emission --

    def _emit(self, inflight: _Inflight) -> None:
        group = inflight.group
        spec = self._models[group.model or self._spec.name][0]
        if group.coast is not None:
            # MOSAIC gated-idle group: no device outputs at all; emit
            # the tracker-coasted detections computed at gate time.
            self._emit_coast(inflight, spec)
            return
        t_drain0 = time.time()
        host = {k: np.asarray(v) for k, v in inflight.outputs.items()}  # D2H
        t_drained = time.time()
        device_ms = (t_drained - inflight.t_submit) * 1000.0
        if self.faults is not None:
            # Stall watchdog signal (engine/fault.py): submit-to-drained
            # wall time against fault_dispatch_deadline_ms with
            # hysteresis — a wedged chip shows up here first, as the
            # drain future that stops resolving on time.
            self.faults.note_drain(device_ms)
        self._m_device.labels(group.model or self._spec.name).observe(
            device_ms
        )
        # r17 per-shard attribution: under the shard-segmented layout
        # each mesh slice was busy for the WHOLE dispatch (the chips run
        # the same program in lockstep), so every shard that carried
        # frames is charged the full device_ms — per-chip measured and
        # attributed time then agree by construction and conservation
        # holds per shard as well as in aggregate.
        shard_frames = shard_streams = None
        if group.rows is not None and self._shards > 1:
            seg = max(1, group.bucket // self._shards)
            shard_frames = {}
            shard_streams = {}
            for j in range(len(group.device_ids)):
                s = str(group.rows[j] // seg)
                shard_frames[s] = shard_frames.get(s, 0) + 1
                shard_streams.setdefault(s, []).append(group.device_ids[j])
        if group.crops is not None:
            # MOSAIC canvas batch: the fps window counts the STREAMS the
            # canvases served, and occupancy is the crop-pixel area
            # share (a canvas is not one fully-occupied batch slot).
            streams = len({p.device_id for p in group.crops})
            self.perf.note_batch(
                group.model or self._spec.name, group.src_hw, group.bucket,
                device_ms, len(group.device_ids), streams=streams,
                area_frac=CanvasPacker.area_fraction(
                    group.crops, len(group.device_ids), group.src_hw[0]),
                shard_frames=shard_frames,
            )
            if self.capacity is not None:
                # Ledger attribution by packed canvas share: each
                # stream's weight is its crops' blitted canvas-pixel
                # area, so a stream with two big tracks carries more of
                # the batch's cost than a one-sliver neighbor.
                areas: Dict[str, int] = {}
                for p in group.crops:
                    a = ((p.dst[2] - p.dst[0]) * (p.dst[3] - p.dst[1]))
                    areas[p.device_id] = areas.get(p.device_id, 0) + a
                crop_shards = None
                if shard_streams is not None:
                    crop_shards = {}
                    for did in areas:
                        s = str(self._shard_of(did) % self._shards)
                        crop_shards.setdefault(s, []).append(did)
                self.capacity.note_batch(
                    group.model or self._spec.name, group.src_hw,
                    group.bucket, device_ms, list(areas),
                    weights=list(areas.values()), kind="roi",
                    shard_streams=crop_shards,
                )
            self._emit_canvas(inflight, host, spec, device_ms, t_drained)
            return
        # Per-bucket device attribution (obs/perf.py): device-time
        # histogram, padded-slot waste, occupancy, live MFU/fps gauges.
        self.perf.note_batch(
            group.model or self._spec.name, group.src_hw, group.bucket,
            device_ms, len(group.device_ids),
            shard_frames=shard_frames,
        )
        if self.capacity is not None:
            # Ledger attribution by slot occupancy: the bucket's cost
            # (padding included — padded slots are real device time the
            # occupants caused) splits equally across the real frames.
            self.capacity.note_batch(
                group.model or self._spec.name, group.src_hw,
                group.bucket, device_ms, group.device_ids,
                shard_streams=shard_streams,
            )
        slo_latency = (
            self._slo_latency
            if self.slo is not None and spec.kind == "detect" else None
        )
        now_ms = int(t_drained * 1000)
        if self._roi is not None and spec.kind == "detect" \
                and group.frames.ndim == 4:
            # Classic full-frame detect emission while ROI serving is
            # on: stamp the refresh cadence (gate feedback) and count
            # the streams toward the equivalent-fps window.
            now_mono = time.monotonic()
            with self._state_lock:
                for device_id in group.device_ids:
                    self._roi.note_full(device_id, now_mono)
            self.perf.note_roi_emit(len(group.device_ids))
        for i, device_id in enumerate(group.device_ids):
            meta = group.metas[i]
            # Shard-segmented layout (r17): slot i's device outputs (and
            # its leased frame) live at batch row rows[i]; identity on
            # the single-chip path.
            row = group.rows[i] if group.rows is not None else i
            # Structured log correlation: every record logged while this
            # slot emits (tracker, annotate, publish, quality) carries
            # stream=<id> seq=<packet> (utils/logging.py injector).
            ctx = set_log_context(stream=device_id, seq=meta.packet)
            try:
                self._emit_slot(
                    inflight, host, row, device_id, meta, spec, now_ms,
                    device_ms, slo_latency, t_drain0, t_drained,
                )
            finally:
                reset_log_context(ctx)

    def _emit_slot(self, inflight, host, row, device_id, meta, spec, now_ms,
                   device_ms, slo_latency, t_drain0, t_drained) -> None:
        group = inflight.group
        detections = self._to_detections(host, row, spec)
        if self._cfg.track and spec.kind == "detect":
            # Unconditionally — empty frames MUST reach the tracker so
            # misses accumulate and stale tracks expire; skipping them
            # would freeze old tracks and hand their ids to the next
            # object that appears nearby.
            self._assign_tracks(device_id, spec.name, detections)
            if (self._cascade is not None and group.frames.ndim == 4
                    and group.crops is None):
                # CASCADE harvest: letterbox each tracked detection's
                # crop into its device clip ring (scattered next tick).
                # Classic full-frame slots only — frames[row] is the
                # leased host buffer, valid until _emit returns; canvas
                # and clip slots have no per-stream full frame here.
                try:
                    self._cascade.harvest(
                        device_id, group.frames[row], detections, meta)
                except Exception:
                    log.exception("cascade harvest failed; continuing")
        if self.quality is not None:
            self._observe_quality(host, row, device_id, meta, detections)
        latency = max(0.0, now_ms - meta.timestamp_ms) if meta.timestamp_ms else 0.0
        result = pb.InferenceResult(
            device_id=device_id,
            timestamp=meta.timestamp_ms,
            model=spec.name,
            model_version="0",
            detections=detections,
            latency_ms=latency,
            batch_size=group.bucket,
            frame_packet=meta.packet,
            # Trace-context echo: clients join their receive event to the
            # frame's cross-process lineage on this id (0 = unstamped).
            trace_id=meta.trace_id,
            parent_span=meta.parent_span,
        )
        if self.faults is not None:
            # (packet, timestamp_ms): monotone per stream even for
            # producers that never stamp packet ids (ledger dup/rebase
            # detection, engine/fault.py).
            self.faults.ledger.note_emitted(
                device_id, (meta.packet, meta.timestamp_ms))
        self._publish(result)
        if self._cfg.stage_trace:
            self.stage_records.append({
                "device_id": device_id,
                "ts_pub_ms": meta.timestamp_ms,
                "t_collect": inflight.t_collect,
                "t_submit": inflight.t_submit,
                "t_drain0": t_drain0,
                "t_drained": t_drained,
                "t_emitted": time.time(),
                "bucket": group.bucket,
            })
        self._annotate(device_id, meta, detections, spec)
        st = self._stats.setdefault(device_id, StreamStats())
        st.frames += 1
        st.note_latency(latency)
        st.last_batch = group.bucket
        st.note_device(device_ms, group.padded_slots)
        st.last_emit_mono = time.monotonic()
        if slo_latency is not None and meta.timestamp_ms:
            # p50 detect-latency SLI: one good/bad event per emitted
            # detect frame (objective 0.5 == the p50 target).
            ok = latency <= self._cfg.slo_latency_ms
            slo_latency.record(good=1.0 if ok else 0.0,
                               bad=0.0 if ok else 1.0)
        self._m_frames.labels(device_id).inc()
        self._m_latency.labels(device_id).observe(latency)
        if latency > self._cfg.obs_late_ms:
            self._m_late.labels(device_id).inc()
        if tracer.sampled(meta.packet):
            tid = trace_id_of(meta, device_id)
            tracer.record(
                device_id, "device", meta.packet, ts=t_drained,
                dur_ms=device_ms, bucket=group.bucket, trace_id=tid,
            )
            tracer.record(device_id, "emit", meta.packet, trace_id=tid)

    def _emit_coast(self, inflight: _Inflight, spec) -> None:
        """Emit a gated-idle (MOSAIC ``coast``) group: detections were
        computed at gate time on the tick thread (tracker coasting); this
        just fans them out with the same per-stream semantics as
        ``_emit_slot``. Rides the drain queue so coasted results never
        overtake an earlier in-flight device batch for the same stream."""
        group = inflight.group
        now_ms = int(time.time() * 1000)
        slo_latency = (
            self._slo_latency
            if self.slo is not None and spec.kind == "detect" else None
        )
        for device_id, meta, detections in group.coast:
            ctx = set_log_context(stream=device_id, seq=meta.packet)
            try:
                self._emit_stream_result(
                    inflight, device_id, meta, detections, spec, now_ms,
                    0.0, slo_latency, coasted=True,
                )
            finally:
                reset_log_context(ctx)
        self.perf.note_roi_emit(len(group.coast))
        if self.capacity is not None:
            # Zero-cost occupants: a coasting stream must read as
            # costing 0 ms in the ledger, not as missing from it.
            self.capacity.note_coast(
                [device_id for device_id, _, _ in group.coast])

    def _emit_canvas(self, inflight: _Inflight, host: dict, spec,
                     device_ms: float, t_drained: float) -> None:
        """MOSAIC scatter-back: route each canvas detection to its crop
        by center point (cells never overlap — the packer keeps a
        background gap), map it through the exact per-crop inverse
        affine (ops/boxes.py ``uncrop_boxes``), clip to the crop's
        source rect, and emit per source stream. A detection whose
        center lands in no cell (gap/background artifact, or a spilled
        stream's cell that left the routing table) is counted and
        dropped — it must never reach the wrong stream."""
        from ..ops.boxes import uncrop_boxes

        group = inflight.group
        now_ms = int(t_drained * 1000)
        slo_latency = (
            self._slo_latency
            if self.slo is not None and spec.kind == "detect" else None
        )
        by_canvas: Dict[int, list] = {}
        results: Dict[str, tuple] = {}   # device_id -> (meta, [Detection])
        for p in group.crops:
            by_canvas.setdefault(p.canvas, []).append(p)
            results.setdefault(p.device_id, (p.meta, []))
        thr = (
            self._conf_threshold
            if self._spec is not None and spec.name == self._spec.name
            else 0.0
        )
        n_classes = self._num_classes(spec)
        # Shard-segmented canvas batches (r17): placement .canvas already
        # names the GLOBAL batch row, so host outputs index directly;
        # identity range on the single-chip path.
        canvas_rows = (group.rows if group.rows is not None
                       else range(len(group.device_ids)))
        for ci in canvas_rows:
            cells = by_canvas.get(ci)
            if not cells:
                continue
            for j in np.nonzero(host["valid"][ci])[0]:
                score = float(host["scores"][ci, j])
                if score < thr:
                    continue
                bx = [float(v) for v in host["boxes"][ci, j]]
                cx = (bx[0] + bx[2]) / 2.0
                cy = (bx[1] + bx[3]) / 2.0
                cell = next(
                    (p for p in cells if p.contains(cx, cy)), None)
                if cell is None:
                    self.perf.note_roi_unrouted()
                    continue
                box = uncrop_boxes(
                    np.asarray(bx, np.float32), scale=cell.scale,
                    dst_origin=cell.dst[:2], src_origin=cell.src[:2],
                )
                x1 = max(cell.src[0], min(float(box[0]), cell.src[2]))
                y1 = max(cell.src[1], min(float(box[1]), cell.src[3]))
                x2 = max(cell.src[0], min(float(box[2]), cell.src[2]))
                y2 = max(cell.src[1], min(float(box[3]), cell.src[3]))
                ix1, iy1 = int(round(x1)), int(round(y1))
                ix2, iy2 = int(round(x2)), int(round(y2))
                cid = int(host["classes"][ci, j])
                results[cell.device_id][1].append(pb.Detection(
                    box=pb.BoundingBox(left=ix1, top=iy1,
                                       width=ix2 - ix1, height=iy2 - iy1),
                    confidence=score,
                    class_id=cid,
                    class_name=class_name(cid, n_classes),
                ))
        for device_id, (meta, detections) in results.items():
            ctx = set_log_context(stream=device_id, seq=meta.packet)
            try:
                self._emit_stream_result(
                    inflight, device_id, meta, detections, spec, now_ms,
                    device_ms, slo_latency,
                )
            finally:
                reset_log_context(ctx)
        self.perf.note_roi_emit(len(results))

    def _emit_stream_result(self, inflight, device_id, meta, detections,
                            spec, now_ms, device_ms, slo_latency,
                            coasted: bool = False) -> None:
        """ROI-path twin of ``_emit_slot``'s tail: tracker association,
        quality detections-only observation (canvas slots carry no
        per-stream frame statistics), publish, annotate, stats, SLO.
        Kept separate so the classic full-frame path stays byte-for-byte
        untouched with roi off. Coasted results skip tracker association
        (the gate already advanced the tracker and the detections ARE
        its tracks) and device-time attribution (no device work ran)."""
        group = inflight.group
        if self._cfg.track and spec.kind == "detect" and not coasted:
            self._assign_tracks(device_id, spec.name, detections)
        if self.quality is not None:
            self.quality.observe(
                device_id,
                classes=[d.class_id for d in detections],
                scores=[d.confidence for d in detections],
            )
        latency = max(0.0, now_ms - meta.timestamp_ms) if meta.timestamp_ms else 0.0
        result = pb.InferenceResult(
            device_id=device_id,
            timestamp=meta.timestamp_ms,
            model=spec.name,
            model_version="0",
            detections=detections,
            latency_ms=latency,
            batch_size=group.bucket,
            frame_packet=meta.packet,
            trace_id=meta.trace_id,
            parent_span=meta.parent_span,
        )
        if self.faults is not None:
            # (packet, timestamp_ms): monotone per stream even for
            # producers that never stamp packet ids (ledger dup/rebase
            # detection, engine/fault.py).
            self.faults.ledger.note_emitted(
                device_id, (meta.packet, meta.timestamp_ms))
        self._publish(result)
        self._annotate(device_id, meta, detections, spec)
        st = self._stats.setdefault(device_id, StreamStats())
        st.frames += 1
        st.note_latency(latency)
        st.last_batch = group.bucket
        if not coasted:
            st.note_device(device_ms, group.padded_slots)
        st.last_emit_mono = time.monotonic()
        if slo_latency is not None and meta.timestamp_ms:
            ok = latency <= self._cfg.slo_latency_ms
            slo_latency.record(good=1.0 if ok else 0.0,
                               bad=0.0 if ok else 1.0)
        self._m_frames.labels(device_id).inc()
        self._m_latency.labels(device_id).observe(latency)
        if latency > self._cfg.obs_late_ms:
            self._m_late.labels(device_id).inc()

    def _observe_quality(self, host: dict, i: int, device_id: str,
                         meta: FrameMeta, detections) -> None:
        """Fold one emitted slot into the quality plane: the device
        frame statistics (when the step carried them — mesh/clip paths
        don't), the detection set for flatline + drift scoring, and —
        for the canary stream — the host-side content checksum into the
        integrity checker (replay/checksum.py host_slot_checksum)."""
        kwargs = {}
        qs = host.get("quality_stats")
        if qs is not None:
            kwargs = {
                "luma_mean": float(qs[i, 0]),
                "luma_var": float(qs[i, 1]),
                "diff_energy": float(qs[i, 2]),
            }
            if self._roi is not None:
                # MOSAIC gate feedback: the next tick classifies this
                # stream against the diff energy just fetched (only
                # full-frame slots carry stats, so the refresh cadence
                # keeps the signal alive).
                with self._state_lock:
                    self._roi.note_diff(device_id, float(qs[i, 2]))
        self.quality.observe(
            device_id,
            classes=[d.class_id for d in detections],
            scores=[d.confidence for d in detections],
            **kwargs,
        )
        if (self.canary is not None and device_id == self.canary.stream
                and "boxes" in host):
            from ..replay.checksum import host_slot_checksum

            self.canary.note(meta.packet, host_slot_checksum(host, i))

    def _assign_tracks(self, device_id: str, model: str, detections) -> None:
        """Per-stream SORT-style association (engine/tracker.py): fills
        Detection.track_id, which `_annotate` forwards as the reference's
        AnnotateRequest.object_tracking_id — the field the reference leaves
        to external ML clients. The tracker resets when the stream's model
        changes: class_ids from different models are different label
        vocabularies, so tracks must never continue across a switch."""
        from .tracker import IoUTracker

        with self._state_lock:
            entry = self._trackers.get(device_id)
            if entry is None or entry[0] != model:
                # Ids stay unique within the stream across resets: the
                # fresh tracker continues numbering where the old one
                # stopped.
                first = entry[1].next_id if entry else 1
                entry = (model, IoUTracker(next_id=first))
                self._trackers[device_id] = entry
            tracker = entry[1]
            boxes = [
                (d.box.left, d.box.top, d.box.left + d.box.width,
                 d.box.top + d.box.height)
                for d in detections
            ]
            # Scores ride along so ROI coasting can decay from the last
            # matched confidence (state-only: emitted bytes unchanged).
            ids = tracker.update(
                boxes, [d.class_id for d in detections],
                scores=[d.confidence for d in detections],
            )
        for det, tid in zip(detections, ids):
            det.track_id = tid

    # -- temporal cascade (CASCADE, temporal/scheduler.py) -----------------

    def _cascade_head(self, pool, slot_idx, time_idx, n_real):
        """Temporal-head dispatch for the cascade scheduler: device-side
        time-ordered clip gather from the state pool, then one bucketed
        program (VideoMAE head + logistic anomaly scorer) cached in the
        engine step cache under its own ``cascade:`` model key. Returns
        (host outputs, device_ms). The pool array itself never crosses
        to the host — only the small outputs dict does; the two int32
        index vectors are the aux H2D traffic (``vep_h2d_*``)."""
        import jax

        name = self._cfg.cascade_model
        spec, model, variables = self._ensure_model(name)
        bucket = int(slot_idx.shape[0])
        side = pool.side
        label = f"cascade:{name}"
        key = (label, getattr(self._cfg, "stem", "classic"),
               (side, side), bucket)
        fn = self._step_cache.get(key)
        if fn is None:
            self._m_cache_miss.inc()
            fn = _TimedStep(
                jax.jit(_build_cascade_head(
                    model, self._cfg.cascade_score_w,
                    self._cfg.cascade_score_b)),
                self.perf, label, (side, side), bucket,
                on_compiled=self._hbm_compile_tap(
                    label, (side, side), bucket))
            self._step_cache[key] = fn
        else:
            self._m_cache_hit.inc()
        t0 = time.perf_counter()
        clips = pool.gather(slot_idx, time_idx)
        outputs = fn(variables, clips)
        host = {k: np.asarray(v) for k, v in outputs.items()}
        device_ms = (time.perf_counter() - t0) * 1000.0
        self.perf.note_h2d(
            f"cascade/{name}", bucket,
            int(slot_idx.nbytes + time_idx.nbytes), 0.0)
        self.perf.note_batch(
            f"cascade/{name}", (side, side), bucket, device_ms, n_real,
            streams=0,  # head passes are not emitted frames: keep the
                        # aggregate-fps window honest (quality pattern)
        )
        return host, device_ms

    def _cascade_tick(self) -> None:
        """Drive one scheduler tick and fan its outcome out: lineage
        spans for sampled due tracks (the ``temporal`` stage joining
        detect→track→temporal→emit) and per-event uplink / archive /
        metrics emission. Never raises — the detect path must not feel
        a cascade failure."""
        try:
            res = self._cascade.tick()
        except Exception:
            log.exception("cascade tick failed; continuing")
            return
        if self.capacity is not None and res.head_ms is not None:
            # Ledger attribution for the 1/N-cadence temporal head: the
            # dispatch's measured time splits equally across the due
            # tracks' streams (raw cost in the ledger; cadence-amortized
            # per-tick figure via amortize_n — a head pass every N ticks
            # is 1/N of its cost per tick at steady state).
            side = self._cascade.side
            streams = [stream for stream, _ in res.head_tracks]
            shard_streams = None
            if self._shards > 1 and self._shard_of is not None and streams:
                shard_streams = {}
                for stream in set(streams):
                    s = str(self._shard_of(stream) % self._shards)
                    shard_streams.setdefault(s, []).append(stream)
            self.capacity.note_batch(
                f"cascade/{self._cfg.cascade_model}", (side, side),
                len(res.head_tracks) or 1, res.head_ms,
                streams,
                kind="cascade",
                amortize_n=self._cfg.cascade_every_n,
                shard_streams=shard_streams,
            )
        if tracer.enabled and res.head_ms is not None:
            t_now = time.time()
            for stream, meta in res.head_tracks:
                if meta is None or not tracer.sampled(meta.packet):
                    continue
                tracer.record(
                    stream, "temporal", meta.packet, ts=t_now,
                    dur_ms=res.head_ms,
                    trace_id=trace_id_of(meta, stream),
                )
        for ev in res.events:
            self._cascade_emit_event(ev)

    def _cascade_emit_event(self, ev: dict) -> None:
        """One cascade event out three planes, each failing
        independently: ``vep_cascade_events_total`` metrics, an
        Annotate-shaped record on the existing uplink batch path
        (type="cascade", retry+breaker+spool downstream), and — on
        "enter" — the track's recent tile history into the archive sink
        as a clip segment."""
        kind = ev["kind"]
        self.perf.note_cascade_event(kind)
        meta = ev.get("meta")
        now_ms = int(time.time() * 1000)
        ts = (meta.timestamp_ms
              if meta is not None and getattr(meta, "timestamp_ms", 0)
              else now_ms)
        seq = None
        if self.journal is not None:
            # Hysteresis already edge-triggers enter/exit — each is a
            # decision event with the score that crossed the threshold.
            seq = self.journal.record(
                "engine", f"cascade_{kind}",
                subject=("stream", str(ev["stream"])),
                trigger={"track": str(ev["track_id"]),
                         "score": round(float(ev["score"]), 4),
                         "tick": int(ev["tick"])})
        log.info(
            "cascade %s stream=%s track=%s score=%.3f tick=%d",
            kind, ev["stream"], ev["track_id"], ev["score"], ev["tick"],
            extra={"vep_actor": "engine",
                   "vep_subject": f"stream:{ev['stream']}",
                   "vep_journal_seq": seq},
        )
        if self._annotations is not None:
            try:
                req = pb.AnnotateRequest(
                    device_name=ev["stream"],
                    type="cascade",
                    start_timestamp=ts,
                    object_type=f"anomaly_{kind}",
                    object_tracking_id=str(ev["track_id"]),
                    confidence=float(ev["score"]),
                    ml_model="temporal.cascade",
                    ml_model_version=self._cfg.cascade_model,
                    width=(getattr(meta, "width", 0)
                           if meta is not None else 0),
                    height=(getattr(meta, "height", 0)
                            if meta is not None else 0),
                )
                self._annotations.publish(req.SerializeToString())
            except Exception:
                log.exception("cascade uplink publish failed")
        history = ev.get("history")
        if kind == "enter" and self._archiver is not None and history:
            try:
                from ..ingest.archive import GopSegment

                fps = max(1.0, 1000.0 / max(self._cfg.tick_ms, 1))
                dur_ms = int(len(history) * 1000.0 / fps)
                self._archiver.submit(GopSegment(
                    device_id=f"cascade_{ev['stream']}",
                    start_ts_ms=ts - dur_ms,
                    end_ts_ms=ts,
                    fps=fps,
                    frames=list(history),
                ))
            except Exception:
                log.exception("cascade archive trigger failed")

    def _to_detections(self, host: dict, i: int, spec=None) -> List[pb.Detection]:
        spec = spec or self._spec
        out: List[pb.Detection] = []
        if spec.kind == "detect":
            valid = host["valid"][i]
            # The calibrated operating point rides the DEFAULT model's
            # checkpoint; per-stream extra models start from init and
            # keep the NMS floor.
            thr = (
                self._conf_threshold
                if self._spec is not None and spec.name == self._spec.name
                else 0.0
            )
            for j in np.nonzero(valid)[0]:
                if float(host["scores"][i, j]) < thr:
                    continue
                # BoundingBox carries int32 pixel coords (proto parity with
                # the reference's AnnotateRequest consumers).
                x1, y1, x2, y2 = (int(round(float(v))) for v in host["boxes"][i, j])
                cid = int(host["classes"][i, j])
                out.append(pb.Detection(
                    box=pb.BoundingBox(left=x1, top=y1, width=x2 - x1, height=y2 - y1),
                    confidence=float(host["scores"][i, j]),
                    class_id=cid,
                    class_name=class_name(cid, self._num_classes(spec)),
                ))
        elif spec.kind == "embed":
            out.append(pb.Detection(
                confidence=1.0, class_id=-1,
                embedding=[float(v) for v in host["embedding"][i]],
            ))
        else:
            for p, cid in zip(host["top_probs"][i], host["top_ids"][i]):
                out.append(pb.Detection(
                    confidence=float(p), class_id=int(cid),
                    class_name=class_name(int(cid), self._num_classes(spec)),
                ))
        return out

    def _num_classes(self, spec=None) -> int:
        spec = spec or self._spec
        model = self._models[spec.name][1] if spec.name in self._models else self._model
        cfg = getattr(model, "cfg", None)
        return getattr(cfg, "num_classes", 0) if cfg is not None else 0

    def _publish(self, result: pb.InferenceResult) -> None:
        with self._sub_lock:
            if self._fanout_closed:
                return
            subs = list(self._subscribers)
        for q, ids in subs:
            if ids is not None and result.device_id not in ids:
                continue
            try:
                q.put_nowait(result)
            except queue.Full:
                # Slow subscriber: latest-wins spirit, drop — but count it
                # (engine thread is the only writer; plain increments).
                self.subscriber_drops += 1
                self.subscriber_drops_by_stream[result.device_id] = (
                    self.subscriber_drops_by_stream.get(result.device_id, 0) + 1
                )
                self._m_sub_drops.labels(result.device_id).inc()

    def _annotate(
        self, device_id: str, meta: FrameMeta, detections: Sequence[pb.Detection],
        spec=None,
    ) -> None:
        spec = spec or self._spec
        if self._annotations is None:
            return
        eligible = [
            det for det in detections
            if det.confidence > 0.0 and (det.class_id >= 0 or det.embedding)
        ]
        if not self._should_annotate(device_id, meta, eligible):
            self.annotations_suppressed += len(eligible)
            return
        for det in eligible:
            req = pb.AnnotateRequest(
                device_name=device_id,
                type="detection" if spec.kind == "detect" else spec.kind,
                start_timestamp=meta.timestamp_ms or int(time.time() * 1000),
                object_type=det.class_name,
                object_tracking_id=det.track_id,
                confidence=det.confidence,
                object_bouding_box=det.box if det.HasField("box") else None,
                # Re-ID feature vectors ride the proto's embedding field
                # (AnnotateRequest.object_signature, video_streaming.proto:26)
                object_signature=list(det.embedding),
                ml_model=spec.name,
                ml_model_version="0",
                width=meta.width,
                height=meta.height,
                is_keyframe=meta.is_keyframe,
            )
            self._annotations.publish(req.SerializeToString())

    def _should_annotate(self, device_id, meta, eligible) -> bool:
        """Per-stream annotation emit policy (cfg.annotation_emit or the
        StreamProcess.annotation_policy override). The reference never
        rate-limits because its CLIENTS choose what to annotate
        (examples/annotation.py); the engine is a firehose and must not
        outrun the uplink drain budget (VERDICT r2 weak #3)."""
        policy = ""
        if self._ann_policy_resolver is not None:
            policy = self._ann_policy_resolver(device_id) or ""
        policy = policy or self._cfg.annotation_emit
        if policy == "all":
            return True
        if policy == "keyframe":
            return bool(meta.is_keyframe)
        if policy not in ("min_interval", "on_change"):
            if (device_id, policy) not in self._ann_policy_warned:
                self._ann_policy_warned.add((device_id, policy))
                log.warning(
                    "unknown annotation policy %r for %s; emitting all",
                    policy, device_id,
                )
            return True
        # The whole policy-state read/update runs under _state_lock: the
        # engine-thread GC deletes _ann_state entries for dropped streams
        # under the same lock, and a setdefault-then-mutate-unlocked here
        # would keep writing an orphaned dict (state silently lost, a
        # re-added stream's first frames mis-gated).
        with self._state_lock:
            st = self._ann_state.setdefault(device_id, {})
            if policy == "min_interval":
                if not eligible:
                    # Nothing to emit: must NOT consume the interval slot, or
                    # sparse scenes (mostly empty frames) would starve real
                    # detections quasi-indefinitely.
                    return True
                now = meta.timestamp_ms or int(time.time() * 1000)
                last = st.get("last_ms")
                if last is not None and now - last < \
                        self._cfg.annotation_min_interval_ms:
                    return False
                st["last_ms"] = now
                return True
            # on_change: the tracked object set changed, or some object's
            # confidence moved more than the configured delta. Track ids when
            # the tracker runs, per-class max-confidence otherwise.
            cur: Dict[str, float] = {}
            for det in eligible:
                key = det.track_id or f"class{det.class_id}"
                cur[key] = max(cur.get(key, 0.0), det.confidence)
            prev = st.get("sig")
            delta = self._cfg.annotation_confidence_delta
            changed = prev is None or set(cur) != set(prev) or any(
                abs(cur[k] - prev[k]) > delta for k in cur
            )
            if changed:
                st["sig"] = cur
            return changed and bool(eligible)
