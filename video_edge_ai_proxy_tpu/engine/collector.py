"""Batch collector: N camera streams → padded device batches per tick.

This is the fan-in point (SURVEY.md §2.3 P3): where the reference left each
ML client to read one Redis stream at a time
(`/root/reference/server/grpcapi/grpc_api.go:187-229`), the collector walks
every active ring each tick, takes the newest unseen frame per stream
(latest-wins, depth-1 semantics preserved), groups frames by source
geometry, and pads each group to a bucketed batch size so XLA sees a small
closed set of shapes (SURVEY.md §7 hard part 1 — no recompilation storms).

Video models get clip assembly: a per-stream sliding window of the last
``clip_len`` frames (the temporal axis is just a leading axis, SURVEY.md
§5.7).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..bus.interface import Frame, FrameBus, FrameMeta


@dataclass
class BatchGroup:
    """One shape-homogeneous device batch (before padding)."""

    src_hw: tuple            # (H, W) of the source frames
    device_ids: List[str]
    frames: np.ndarray       # [N, H, W, C] u8, or [N, T, H, W, C] for clips
    metas: List[FrameMeta]
    bucket: int = 0          # padded batch size chosen by pad_to_bucket
    model: str = ""          # registry model these streams run (engine key)


def pad_to_bucket(group: BatchGroup, buckets: Sequence[int]) -> BatchGroup:
    """Zero-pad the batch dim to the smallest bucket >= N. Oversized batches
    are the caller's job (Collector.collect chunks to max bucket)."""
    n = group.frames.shape[0]
    bucket = next((b for b in sorted(buckets) if b >= n), None)
    if bucket is None:
        raise ValueError(f"batch {n} exceeds max bucket {max(buckets)}")
    if bucket != n:
        pad = np.zeros((bucket - n,) + group.frames.shape[1:], group.frames.dtype)
        group.frames = np.concatenate([group.frames, pad], axis=0)
    group.bucket = bucket
    return group


class Collector:
    """Tracks per-stream cursors and assembles per-tick batches."""

    def __init__(
        self,
        bus: FrameBus,
        *,
        buckets: Sequence[int] = (1, 2, 4, 8, 16, 32),
        clip_len: int = 0,
        active_window_s: float = 10.0,
        model_of: Optional[callable] = None,   # device_id -> (model, clip_len)
        default_model: str = "",
        interest_of: Optional[callable] = None,  # device_id -> bool
    ):
        self._bus = bus
        self._buckets = tuple(sorted(buckets))
        self._clip_len = clip_len
        self._active_window_s = active_window_s
        self._model_of = model_of
        self._default_model = default_model
        # Inference gating (SURVEY §2.3 P6, device half): ``interest_of``
        # answers "does anything consume results for this stream right
        # now" (uplink configured / live subscriber). A stream whose
        # interest lapses keeps inferring for ``active_window_s`` (linger
        # prevents batch-membership thrash on reconnecting clients), then
        # drops out of the device batch AND out of keep_streams_hot — so
        # the worker's lazy-decode valve actually closes.
        self._interest_of = interest_of
        self._last_interest: Dict[str, float] = {}
        self._cursors: Dict[str, int] = {}
        self._clips: Dict[str, deque] = {}
        self._geom: Dict[str, tuple] = {}   # last-seen (h, w, c) per stream
        # shape -> {"bufs": [arr], "prev": set(idx), "cur": [idx]} (_pooled)
        self._pool: Dict[tuple, dict] = {}
        self._only: Optional[set] = None   # restrict to these ids (None = all)

    def _stream_model(self, device_id: str):
        """(model name, clip_len) for one stream — per-stream override via
        the resolver (StreamProcess.inference_model), else engine default."""
        if self._model_of is not None:
            resolved = self._model_of(device_id)
            if resolved:
                return resolved
        return self._default_model, self._clip_len

    def restrict(self, device_ids: Optional[Sequence[str]]) -> None:
        self._only = set(device_ids) if device_ids else None

    def active_streams(self) -> List[str]:
        ids = self._bus.streams()
        if self._only is not None:
            ids = [d for d in ids if d in self._only]
        return sorted(ids)

    def _gated(self, device_id: str) -> bool:
        """True when this stream must NOT be inferred this tick: the
        operator switched it off (``inference_model: "none"``) or nothing
        consumes its results and the ``active_window_s`` linger expired."""
        model, _ = self._stream_model(device_id)
        if model == "none":
            return True
        if self._interest_of is None:
            return False
        now = time.monotonic()
        if self._interest_of(device_id):
            self._last_interest[device_id] = now
            return False
        last = self._last_interest.get(device_id)
        return last is None or now - last >= self._active_window_s

    def partition(self) -> tuple:
        """ONE bus enumeration -> (present, inferred): every listed
        stream, and the subset the engine will infer this tick. The
        engine's tick calls this once and threads the lists through
        keep_streams_hot / collect / its GC — on the Redis backend each
        enumeration is a SCAN and each gating check runs the model
        resolver, so repeating them per call triples control-plane
        traffic to a shared production server."""
        present = self.active_streams()
        return present, [d for d in present if not self._gated(d)]

    def inference_streams(self) -> List[str]:
        """Streams the engine will actually infer this tick."""
        return self.partition()[1]

    def keep_streams_hot(
        self, now_ms: Optional[int] = None,
        device_ids: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """The engine is a frame consumer like any gRPC client: touching
        ``last_query`` keeps the ingest workers' lazy-decode gate open
        (reference semantics, ``python/rtsp_to_rtmp.py:144-145``) — but
        ONLY for streams it will actually infer. Touching a gated stream
        would hold every idle camera's decode valve open from inside the
        engine, defeating the lazy-decode CPU saving (round-2 verdict
        missing #4). ``device_ids``: a precomputed inferred set (from
        ``partition``); None re-enumerates."""
        ids = list(device_ids) if device_ids is not None \
            else self.inference_streams()
        for device_id in ids:
            self._bus.touch_query(device_id, now_ms)
        return ids

    def _begin_tick(self) -> None:
        """Start a new pool rotation epoch (called at collect() entry).
        Buffers backing the previous EMITTING tick's groups stay
        off-limits — the engine's double-buffered dispatch may still be
        reading them — and the new tick's handouts accumulate so no two
        same-shape groups within one tick can share a buffer. Idle ticks
        (cur drained by _unrotate) keep the existing protection window:
        consumers hold frames from the last tick that emitted, however
        long ago that was."""
        for slot in self._pool.values():
            if slot["cur"]:
                slot["prev"] = set(slot["cur"])
                slot["cur"] = []

    def _pooled(self, shape: tuple) -> np.ndarray:
        """Pooled batch buffer per shape. Reuse keeps the pages warm —
        fresh allocations at the north-star shape fault ~25k pages per
        tick, which measured as several times the raw memcpy floor
        (tools/bench_latency host leg). Every call within one tick gets a
        DISTINCT buffer (3 models on same-geometry cameras build 3+
        same-shape groups per tick), and nothing handed out the previous
        tick is reused, so a returned BatchGroup's frames stay valid for
        one full tick of double-buffered dispatch. The pool grows to the
        high-water mark of (this tick + last tick) same-shape groups —
        steady state 2 buffers for the common one-group case."""
        slot = self._pool.get(shape)
        if slot is None:
            slot = {"bufs": [], "prev": set(), "cur": []}
            self._pool[shape] = slot
        busy = slot["prev"].union(slot["cur"])
        idx = next(
            (i for i in range(len(slot["bufs"])) if i not in busy), None
        )
        if idx is None:
            slot["bufs"].append(np.zeros(shape, np.uint8))
            idx = len(slot["bufs"]) - 1
        slot["cur"].append(idx)
        return slot["bufs"][idx]

    def _unrotate(self, shape: tuple) -> None:
        """No group was emitted from the last-handed-out buffer (every
        read came back empty): hand it back so idle ticks do not grow the
        pool or burn the one-tick safety margin for consumers still
        holding the previous tick's frames."""
        slot = self._pool[shape]
        if slot["cur"]:
            slot["cur"].pop()

    def collect(
        self, device_ids: Optional[Sequence[str]] = None
    ) -> List[BatchGroup]:
        """One tick: newest unseen frame per stream -> (model, shape)-
        grouped, bucket-padded batches (clips for video models).
        ``device_ids``: precomputed inferred set (from ``partition``);
        None re-enumerates.

        Single-pass hot path: non-clip streams whose geometry is known
        from a previous tick are read by the bus DIRECTLY into pooled
        batch slots (`read_latest_into`) — ring to device batch in one
        memory pass. First-sight streams, clip assembly, and geometry
        drift take the generic frame path and join the fast path next
        tick."""
        if device_ids is None:
            device_ids = self.inference_streams()
        self._begin_tick()
        max_bucket = self._buckets[-1]

        fast_plan: Dict[tuple, list] = {}   # (model, (h,w,c)) -> [ids]
        slow_ids: List[str] = []
        for device_id in device_ids:
            model, clip_len = self._stream_model(device_id)
            geom = self._geom.get(device_id)
            if clip_len or geom is None:
                slow_ids.append(device_id)
            else:
                fast_plan.setdefault((model, geom), []).append(device_id)

        groups: List[BatchGroup] = []
        spill: List[tuple] = []             # geometry drifted mid-plan

        for (model, geom), devs in sorted(fast_plan.items()):
            for start in range(0, len(devs), max_bucket):
                chunk = devs[start:start + max_bucket]
                alloc = next(b for b in self._buckets if b >= len(chunk))
                batch = self._pooled((alloc,) + geom)
                ids: List[str] = []
                metas: List[FrameMeta] = []
                for device_id in chunk:
                    res = self._bus.read_latest_into(
                        device_id, batch[len(ids)],
                        min_seq=self._cursors.get(device_id, 0),
                    )
                    if res is None:
                        continue
                    if isinstance(res, Frame):   # geometry drifted
                        self._cursors[device_id] = res.seq
                        if res.data.ndim == 3:   # corrupt 1-D frames must
                            # not poison the geometry cache (generic-path
                            # guard below applies here too)
                            self._geom[device_id] = res.data.shape
                        spill.append((device_id, model, res))
                        continue
                    seq, meta = res
                    self._cursors[device_id] = seq
                    ids.append(device_id)
                    metas.append(meta)
                n = len(ids)
                if not n:
                    self._unrotate((alloc,) + geom)
                    continue
                bucket = next(b for b in self._buckets if b >= n)
                view = batch[:bucket]
                if bucket != n:
                    view[n:] = 0
                groups.append(BatchGroup(
                    src_hw=geom[:2], device_ids=ids, frames=view,
                    metas=metas, bucket=bucket, model=model,
                ))

        # Generic path: first sight (geometry unknown), clips, drift.
        by_key: Dict[tuple, list] = {}
        for device_id in slow_ids:
            frame = self._bus.read_latest(
                device_id, min_seq=self._cursors.get(device_id, 0)
            )
            if frame is None:
                continue
            self._cursors[device_id] = frame.seq
            model, clip_len = self._stream_model(device_id)
            if frame.data.ndim == 3:
                self._geom[device_id] = frame.data.shape
            hw = frame.data.shape[:2]
            if clip_len:
                window = self._clips.get(device_id)
                if window is None or window.maxlen != clip_len:
                    # (Re)create on clip-length change — a re-added stream
                    # with a different model must not inherit a stale window.
                    window = deque(maxlen=clip_len)
                    self._clips[device_id] = window
                window.append(frame)
                if len(window) < clip_len:
                    continue
                sample = np.stack([f.data for f in window])
            else:
                sample = frame.data
            by_key.setdefault((model, hw), []).append(
                (device_id, sample, frame.meta)
            )
        for device_id, model, frame in spill:
            by_key.setdefault((model, frame.data.shape[:2]), []).append(
                (device_id, frame.data, frame.meta)
            )

        for (model, hw), items in sorted(by_key.items()):
            for start in range(0, len(items), max_bucket):
                chunk = items[start:start + max_bucket]
                n = len(chunk)
                bucket = next(b for b in self._buckets if b >= n)
                # Fused stack+pad: one pass instead of np.stack + concat.
                batch = np.empty(
                    (bucket,) + chunk[0][1].shape, chunk[0][1].dtype
                )
                for i, (_, arr, _) in enumerate(chunk):
                    batch[i] = arr
                if bucket != n:
                    batch[n:] = 0
                groups.append(BatchGroup(
                    src_hw=hw,
                    device_ids=[d for d, _, _ in chunk],
                    frames=batch,
                    metas=[m for _, _, m in chunk],
                    bucket=bucket,
                    model=model,
                ))
        return groups

    def drop_stream(self, device_id: str) -> None:
        self._cursors.pop(device_id, None)
        self._clips.pop(device_id, None)
        self._geom.pop(device_id, None)
        self._last_interest.pop(device_id, None)
