"""Batch collector: N camera streams → padded device batches per tick.

This is the fan-in point (SURVEY.md §2.3 P3): where the reference left each
ML client to read one Redis stream at a time
(`/root/reference/server/grpcapi/grpc_api.go:187-229`), the collector walks
every active ring each tick, takes the newest unseen frame per stream
(latest-wins, depth-1 semantics preserved), groups frames by source
geometry, and pads each group to a bucketed batch size so XLA sees a small
closed set of shapes (SURVEY.md §7 hard part 1 — no recompilation storms).

Video models get clip assembly: a per-stream sliding window of the last
``clip_len`` frames (the temporal axis is just a leading axis, SURVEY.md
§5.7).
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..bus.interface import Frame, FrameBus, FrameMeta
from ..obs import registry as obs_registry, tracer
from ..obs.spans import trace_id_of


def stream_shard(device_id: str, shards: int) -> int:
    """Stable stream -> mesh-shard assignment (dp-sharded serving).

    crc32 is platform- and run-stable, so a stream always lands on the
    same chip: its ROI tracker state, thumbnail slot and cascade clips
    live in that shard's pools and never migrate mid-flight. The engine
    and the collector must agree on this mapping — it is THE routing
    function for mesh-native serving."""
    if shards <= 1:
        return 0
    return zlib.crc32(device_id.encode("utf-8")) % shards


def make_repin(base_shard_of, shards: int, dead):
    """Deterministic rendezvous re-pin for survivor-mesh failover
    (device-fault domain, r22).

    ``base_shard_of`` is the routing function that was live when the
    fault hit (``stream_shard`` bound to the old shard count, or a
    previous failover's repin — composition handles cascaded faults);
    ``shards`` its shard count; ``dead`` the faulted shard indices.
    Survivor shards keep their old index order in the rebuilt mesh
    (new shard i == i-th surviving old shard, same physical device), so:

    - a stream whose home shard survives maps to that shard's new index
      — it stays on the SAME device, state intact, which is what makes
      failover a re-pin and not a full crc32 reshuffle (surviving
      shards keep >= 90% of their pins by construction: they keep all
      of them);
    - a stream whose home shard died re-pins by highest-random-weight
      (rendezvous) hashing over the survivors — deterministic,
      uniformly spread, and stable under further shard loss (only
      streams of the newly dead shard move again)."""
    dead = frozenset(int(s) for s in dead)
    survivors = [s for s in range(int(shards)) if s not in dead]
    if not survivors:
        raise ValueError("no surviving shards to re-pin onto")
    new_index = {s: i for i, s in enumerate(survivors)}

    def repin(device_id: str) -> int:
        home = base_shard_of(device_id)
        idx = new_index.get(home)
        if idx is not None:
            return idx
        best = max(
            survivors,
            key=lambda t: zlib.crc32(f"{device_id}@{t}".encode("utf-8")),
        )
        return new_index[best]

    return repin


@dataclass
class BatchGroup:
    """One shape-homogeneous device batch (before padding)."""

    src_hw: tuple            # (H, W) of the source frames
    device_ids: List[str]
    frames: np.ndarray       # [N, H, W, C] u8, or [N, T, H, W, C] for clips
    metas: List[FrameMeta]
    bucket: int = 0          # padded batch size chosen by pad_to_bucket
    model: str = ""          # registry model these streams run (engine key)
    lease: Optional[tuple] = None  # (pool shape, buf idx) when the frames
                                   # view a pooled buffer under strict
                                   # leasing (Collector.release returns it)
    # MOSAIC lineage (cfg.roi, engine/runner.py). ``crops``: this group's
    # frames are packed shared canvases, one CropPlacement per blitted
    # crop — the provenance the scatter-back path needs to route canvas
    # detections to their source streams. ``coast``: no device work at
    # all; list of (device_id, meta, detections) for gated-idle streams
    # whose tracker-coasted results ride the drain queue so per-stream
    # emit ordering is preserved. Both None on the classic full-frame
    # path — which is exactly what keeps roi=False bit-identical.
    crops: Optional[list] = None
    coast: Optional[list] = None
    # Mesh-sharded layout (Collector(shards=S)): ``rows[j]`` is the frame
    # row of ``device_ids[j]`` in the shard-segmented batch — shard s owns
    # rows [s*bucket/S, (s+1)*bucket/S), each segment zero-padded
    # independently so one ``dp``-sharded device_put gives every chip
    # exactly its own streams' frames. None = dense identity layout (row
    # j == device_ids[j]), the single-chip path, bit-identical to pre-
    # shard behavior.
    rows: Optional[List[int]] = None

    @property
    def padded_slots(self) -> int:
        """Batch slots carrying zero-padding instead of real frames — the
        per-batch waste obs/perf.py attributes (pad_to_bucket and the
        pooled fast paths both pad up to ``bucket``)."""
        return max(0, self.bucket - len(self.device_ids))

    @property
    def nbytes(self) -> int:
        """Bytes of frame plane this batch ships host->device when
        dispatched: the whole padded uint8 plane, padding slots included
        (they cross the PCIe/ICI link like real frames). Aux tensors that
        ride along per dispatch (e.g. the int32 thumbnail slot-index
        vector for 3-arg quality steps) are accounted at the dispatch
        site in engine/runner.py, which adds them to this figure before
        feeding the vep_h2d_* accounting in obs/perf.py — the evidence
        gate for ROADMAP item 5's uint8-shipping / double-buffered H2D
        work."""
        return int(self.frames.nbytes)


def pad_to_bucket(group: BatchGroup, buckets: Sequence[int]) -> BatchGroup:
    """Zero-pad the batch dim to the smallest bucket >= N. Oversized batches
    are the caller's job (Collector.collect chunks to max bucket)."""
    n = group.frames.shape[0]
    bucket = next((b for b in sorted(buckets) if b >= n), None)
    if bucket is None:
        raise ValueError(f"batch {n} exceeds max bucket {max(buckets)}")
    if bucket != n:
        pad = np.zeros((bucket - n,) + group.frames.shape[1:], group.frames.dtype)
        group.frames = np.concatenate([group.frames, pad], axis=0)
    group.bucket = bucket
    return group


@dataclass(frozen=True)
class CropPlacement:
    """Provenance for one crop blitted onto a shared canvas (MOSAIC).

    The forward placement is a pure integer affine — source rect ``src``
    decimated by ``scale`` (source px per canvas px, power of two) and
    blitted with its top-left corner at ``dst``'s origin — so the
    scatter-back inverse (ops/boxes.py ``uncrop_boxes``) is exact:
    ``src_px = (canvas_px - dst_origin) * scale + src_origin``.
    """

    device_id: str
    meta: FrameMeta          # the source frame's meta (timestamps, packet)
    canvas: int              # slot index within the canvas batch
    src: tuple               # (x0, y0, x1, y1) source-frame px (ints)
    dst: tuple               # (x0, y0, x1, y1) canvas px (ints)
    scale: int               # source px per canvas px (>= 1, power of 2)

    def contains(self, x: float, y: float) -> bool:
        """Does a canvas-coordinate point land in this crop's cell? Used
        by the scatter-back router: one cell per detection center, cells
        never overlap (the packer keeps a gap between them)."""
        return (self.dst[0] <= x < self.dst[2]
                and self.dst[1] <= y < self.dst[3])


class CanvasPacker:
    """Deterministic shelf packer: many streams' active crops → a small
    set of static-shape shared canvases (MOSAIC, arxiv 2305.03222).

    Geometry is the bucket: every canvas is ``side``×``side`` uint8, so
    the packed batch reuses the engine's existing (geometry, bucket) step
    cache — XLA still sees a small closed shape set, no new programs
    beyond the one canvas geometry. Packing is deterministic (sort by
    scaled height/width then stream id, first-fit shelves) so replaying
    the same crops yields byte-identical canvases — the property the
    replay-checksum harness leans on.

    Crops larger than a canvas are decimated by the smallest power-of-two
    stride that fits; power-of-two strided views keep the inverse
    transform exact (no fractional resampling) and the blit a cheap numpy
    strided copy. A ``gap`` of background pixels separates cells so a
    detection can never straddle two streams' crops; background is 114
    gray, matching ``preprocess_letterbox``'s pad value so cell borders
    look like letterbox padding to the detector.
    """

    def __init__(self, side: int = 640, gap: int = 8,
                 max_canvases: int = 8, min_crop: int = 16):
        self.side = int(side)
        self.gap = int(gap)
        self.max_canvases = int(max_canvases)
        self.min_crop = int(min_crop)

    def _fit_scale(self, w: int, h: int) -> int:
        scale = 1
        while (w + scale - 1) // scale > self.side \
                or (h + scale - 1) // scale > self.side:
            scale *= 2
        return scale

    def pack(self, requests: Sequence[tuple]):
        """``requests``: (device_id, meta, frame [H,W,3] u8, roi xyxy).

        Returns (canvases [K, side, side, 3] u8, placements, overflow):
        ``placements`` one CropPlacement per packed crop, ``overflow``
        the request indices that did not fit within ``max_canvases``
        (the engine falls those streams back to the full-frame path).
        """
        side, gap = self.side, self.gap
        prepared = []   # (sh, sw, scale, rect, req_index)
        overflow: List[int] = []
        for ri, (device_id, _meta, frame, roi) in enumerate(requests):
            fh, fw = frame.shape[0], frame.shape[1]
            x0 = max(0, min(int(roi[0]), fw - 1))
            y0 = max(0, min(int(roi[1]), fh - 1))
            x1 = max(x0 + 1, min(int(round(roi[2])), fw))
            y1 = max(y0 + 1, min(int(round(roi[3])), fh))
            # Tiny ROIs inflate to min_crop: the detector needs context
            # and the NMS floor behaves badly on few-pixel cells.
            if x1 - x0 < self.min_crop:
                x1 = min(fw, x0 + self.min_crop)
                x0 = max(0, x1 - self.min_crop)
            if y1 - y0 < self.min_crop:
                y1 = min(fh, y0 + self.min_crop)
                y0 = max(0, y1 - self.min_crop)
            scale = self._fit_scale(x1 - x0, y1 - y0)
            sw = (x1 - x0 + scale - 1) // scale
            sh = (y1 - y0 + scale - 1) // scale
            prepared.append((sh, sw, scale, (x0, y0, x1, y1), ri))
        # Deterministic shelf order: tallest first, then widest, then
        # stream id — identical input always packs identically.
        prepared.sort(key=lambda p: (-p[0], -p[1],
                                     requests[p[4]][0], p[4]))
        placements: List[CropPlacement] = []
        slots = []   # per-canvas shelf cursors: [x, y, shelf_h]
        blits = []   # (canvas, dst, rect, scale, req_index)
        for sh, sw, scale, rect, ri in prepared:
            placed = False
            for ci, cur in enumerate(slots):
                x, y, shelf_h = cur
                if x + sw > side:                     # next shelf
                    x, y, shelf_h = 0, y + shelf_h + gap, 0
                if x + sw <= side and y + sh <= side:
                    blits.append((ci, (x, y, x + sw, y + sh),
                                  rect, scale, ri))
                    slots[ci] = [x + sw + gap, y, max(shelf_h, sh)]
                    placed = True
                    break
            if not placed:
                if len(slots) < self.max_canvases:
                    ci = len(slots)
                    slots.append([sw + gap, 0, sh])
                    blits.append((ci, (0, 0, sw, sh), rect, scale, ri))
                else:
                    overflow.append(ri)
        canvases = np.full((len(slots), side, side, 3), 114, np.uint8)
        for ci, dst, rect, scale, ri in blits:
            device_id, meta, frame, _roi = requests[ri]
            x0, y0, x1, y1 = rect
            view = frame[y0:y1:scale, x0:x1:scale]
            canvases[ci, dst[1]:dst[3], dst[0]:dst[2]] = view
            placements.append(CropPlacement(
                device_id=device_id, meta=meta, canvas=ci,
                src=rect, dst=dst, scale=scale,
            ))
        return canvases, placements, overflow

    @staticmethod
    def area_fraction(placements: Sequence[CropPlacement],
                      n_canvases: int, side: int) -> float:
        """Crop-pixel share of the canvas batch — the crop-level
        occupancy obs/perf.py reports for packed batches (a canvas is
        NOT one fully-occupied slot)."""
        if not n_canvases:
            return 0.0
        used = sum((p.dst[2] - p.dst[0]) * (p.dst[3] - p.dst[1])
                   for p in placements)
        return used / float(n_canvases * side * side)


class Collector:
    """Tracks per-stream cursors and assembles per-tick batches."""

    def __init__(
        self,
        bus: FrameBus,
        *,
        buckets: Sequence[int] = (1, 2, 4, 8, 16, 32),
        clip_len: int = 0,
        active_window_s: float = 10.0,
        model_of: Optional[callable] = None,   # device_id -> (model, clip_len)
        default_model: str = "",
        interest_of: Optional[callable] = None,  # device_id -> bool
        strict_lease: bool = False,
        shards: int = 1,
    ):
        self._bus = bus
        self._buckets = tuple(sorted(buckets))
        # Mesh-sharded batch layout (engine.mesh, dp axis): every batch is
        # segmented into ``shards`` equal row ranges, streams are routed to
        # their stream_shard() segment, and each segment pads
        # independently — the frames a dp-sharded device_put lands on chip
        # s are exactly shard s's streams. Buckets must split evenly;
        # non-divisible ones are dropped here (the engine pre-filters to
        # the same set). shards=1 keeps every path bit-identical.
        self._shards = max(1, int(shards))
        if self._shards > 1:
            sharded = tuple(b for b in self._buckets if b % self._shards == 0)
            if not sharded:
                import logging

                logging.getLogger("vep.engine.collector").warning(
                    "no bucket in %s divides into %d shards; serving "
                    "unsharded", self._buckets, self._shards)
                self._shards = 1
            else:
                self._buckets = sharded
        # Stream -> shard routing override (device-fault failover,
        # ``repin``): None = the stable crc32 ``stream_shard`` map.
        self._shard_fn = None
        # Degradation-ladder bucket cap (resilience/ladder.py rung 2):
        # None = full bucket list; an int hides buckets above it so new
        # batches compile/run at the next-smaller device program.
        self._bucket_cap: Optional[int] = None
        self._clip_len = clip_len
        self._active_window_s = active_window_s
        self._model_of = model_of
        self._default_model = default_model
        # Inference gating (SURVEY §2.3 P6, device half): ``interest_of``
        # answers "does anything consume results for this stream right
        # now" (uplink configured / live subscriber). A stream whose
        # interest lapses keeps inferring for ``active_window_s`` (linger
        # prevents batch-membership thrash on reconnecting clients), then
        # drops out of the device batch AND out of keep_streams_hot — so
        # the worker's lazy-decode valve actually closes.
        self._interest_of = interest_of
        self._last_interest: Dict[str, float] = {}
        self._cursors: Dict[str, int] = {}
        self._clips: Dict[str, deque] = {}
        self._geom: Dict[str, tuple] = {}   # last-seen (h, w, c) per stream
        # shape -> {"bufs": [arr], "prev": set, "cur": [idx], "leased":
        # [idx in lease order]} (_pooled / release)
        self._pool: Dict[tuple, dict] = {}
        # strict_lease (the engine's mode): a buffer backing an emitted
        # BatchGroup stays off-limits until Collector.release(group) —
        # required once dispatched batches outlive the tick that built
        # them (the engine's event-driven drain queue). Off (default):
        # the epoch heuristic alone bounds reuse to one emitting tick,
        # which is enough for callers that consume groups synchronously.
        self._strict_lease = strict_lease
        self._pool_lock = threading.Lock()  # release() runs on the drain
                                            # thread, _pooled on the engine
        # Incremental assembly window (assemble_until): frames are copied
        # into their pooled batch slots AS THEY ARRIVE between ticks, so
        # collect() at the tick boundary only finalizes. None = no window
        # active (plain collect path).
        self._window: Optional[dict] = None
        self._only: Optional[set] = None   # restrict to these ids (None = all)
        # Latest-wins supersessions are BY DESIGN, but invisible drops are
        # not: a cursor that jumps k>1 sequence numbers means k-1 frames
        # were published and never read (camera outrunning the tick rate).
        self._m_skipped = obs_registry.counter(
            "vep_frames_skipped_total",
            "Frames superseded before read (latest-wins drops)",
            ("stream",),
        )

    def set_bucket_cap(self, cap: Optional[int]) -> None:
        """Cap the effective bucket list (degradation-ladder rung 2,
        resilience/ladder.py): ``cap=8`` hides buckets above 8 so new
        batches run the smaller, already-compiled device program; ``None``
        restores the full list. In-flight groups and the assembly
        window's existing allocations are untouched — the cap applies
        from the next planning/collect pass."""
        self._bucket_cap = cap

    def _effective_buckets(self) -> tuple:
        cap = self._bucket_cap
        if cap is None:
            return self._buckets
        eff = tuple(b for b in self._buckets if b <= cap)
        return eff or self._buckets[:1]

    def _rebase_if_restarted(self, device_id: str) -> bool:
        """A producer that recreates its ring (stop/start stream re-add,
        worker crash-restart) restarts sequence numbering below our
        cursor, so ``read_latest*(min_seq=cursor)`` would treat every
        frame on the new ring as already-seen until its seq caught up —
        seconds of invisibly dropped frames at low fps. A head strictly
        below the cursor is impossible on a monotonic ring, so it is an
        unambiguous restart signal: drop the cursor (callers retry the
        read in the same pass). ``head()`` None (backend without cheap
        heads) keeps the old behavior. Returns True when rebased."""
        cursor = self._cursors.get(device_id, 0)
        if cursor:
            head = self._bus.head(device_id)
            if head is not None and head < cursor:
                self._cursors.pop(device_id, None)
                return True
        return False

    def _note_read(self, device_id: str, seq: int, meta) -> None:
        """Every cursor advance funnels here: counts latest-wins skips and
        stamps the frame's ``collect`` lineage span. ``pub_ms`` rides the
        span because the publish span usually lives in a worker
        subprocess — the ingest->collect leg must be computable from the
        engine side alone."""
        prev = self._cursors.get(device_id, 0)
        if prev and seq > prev + 1:
            self._m_skipped.labels(device_id).inc(seq - prev - 1)
        self._cursors[device_id] = seq
        if meta is not None and tracer.sampled(meta.packet):
            tracer.record(
                device_id, "collect", meta.packet, pub_ms=meta.timestamp_ms,
                trace_id=trace_id_of(meta, device_id),
            )

    def _stream_model(self, device_id: str):
        """(model name, clip_len) for one stream — per-stream override via
        the resolver (StreamProcess.inference_model), else engine default."""
        if self._model_of is not None:
            resolved = self._model_of(device_id)
            if resolved:
                return resolved
        return self._default_model, self._clip_len

    def restrict(self, device_ids: Optional[Sequence[str]]) -> None:
        self._only = set(device_ids) if device_ids else None

    def active_streams(self) -> List[str]:
        ids = self._bus.streams()
        if self._only is not None:
            ids = [d for d in ids if d in self._only]
        return sorted(ids)

    def _gated(self, device_id: str) -> bool:
        """True when this stream must NOT be inferred this tick: the
        operator switched it off (``inference_model: "none"``) or nothing
        consumes its results and the ``active_window_s`` linger expired."""
        model, _ = self._stream_model(device_id)
        if model == "none":
            return True
        if self._interest_of is None:
            return False
        now = time.monotonic()
        if self._interest_of(device_id):
            self._last_interest[device_id] = now
            return False
        last = self._last_interest.get(device_id)
        return last is None or now - last >= self._active_window_s

    def partition(self) -> tuple:
        """ONE bus enumeration -> (present, inferred): every listed
        stream, and the subset the engine will infer this tick. The
        engine's tick calls this once and threads the lists through
        keep_streams_hot / collect / its GC — on the Redis backend each
        enumeration is a SCAN and each gating check runs the model
        resolver, so repeating them per call triples control-plane
        traffic to a shared production server."""
        present = self.active_streams()
        return present, [d for d in present if not self._gated(d)]

    def inference_streams(self) -> List[str]:
        """Streams the engine will actually infer this tick."""
        return self.partition()[1]

    def keep_streams_hot(
        self, now_ms: Optional[int] = None,
        device_ids: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """The engine is a frame consumer like any gRPC client: touching
        ``last_query`` keeps the ingest workers' lazy-decode gate open
        (reference semantics, ``python/rtsp_to_rtmp.py:144-145``) — but
        ONLY for streams it will actually infer. Touching a gated stream
        would hold every idle camera's decode valve open from inside the
        engine, defeating the lazy-decode CPU saving (round-2 verdict
        missing #4). ``device_ids``: a precomputed inferred set (from
        ``partition``); None re-enumerates."""
        ids = list(device_ids) if device_ids is not None \
            else self.inference_streams()
        for device_id in ids:
            self._bus.touch_query(device_id, now_ms)
        return ids

    # Failsafe: a caller that leases (collect() under strict_lease) but
    # never releases would grow a shape's pool without bound; past this
    # many live buffers per shape new handouts become one-off non-pooled
    # allocations (idx None — never tracked, never reused). The engine's
    # drain queue is depth-2, so steady state is 3-4; hitting the cap
    # means a leak and is logged.
    MAX_POOL_BUFFERS = 8

    def _begin_tick(self) -> None:
        """Start a new pool rotation epoch (called at collect() entry).
        Buffers backing the previous EMITTING tick's groups stay
        off-limits — the engine's double-buffered dispatch may still be
        reading them — and the new tick's handouts accumulate so no two
        same-shape groups within one tick can share a buffer. Idle ticks
        (cur drained by _unrotate) keep the existing protection window:
        consumers hold frames from the last tick that emitted, however
        long ago that was."""
        with self._pool_lock:
            for slot in self._pool.values():
                if slot["cur"]:
                    slot["prev"] = set(slot["cur"])
                    slot["cur"] = []

    def _pooled(self, shape: tuple):
        """Pooled batch buffer per shape -> (array, pool index). Reuse
        keeps the pages warm — fresh allocations at the north-star shape
        fault ~25k pages per tick, which measured as several times the
        raw memcpy floor (tools/bench_latency host leg). Every call
        within one tick gets a DISTINCT buffer (3 models on same-geometry
        cameras build 3+ same-shape groups per tick), nothing handed out
        the previous tick is reused, and under strict_lease nothing
        leased to an in-flight batch is reused until release(). The pool
        grows to the observed high-water mark — steady state 2 buffers
        for the common synchronous one-group case."""
        with self._pool_lock:
            slot = self._pool.get(shape)
            if slot is None:
                slot = {"bufs": [], "prev": set(), "cur": [], "leased": [],
                        "fill": {}}
                self._pool[shape] = slot
            busy = set(slot["prev"])
            busy.update(slot["cur"])
            busy.update(slot["leased"])
            idx = next(
                (i for i in range(len(slot["bufs"])) if i not in busy), None
            )
            if idx is None:
                if len(slot["bufs"]) >= self.MAX_POOL_BUFFERS \
                        and slot["leased"]:
                    # Failsafe: leak containment. Stealing the oldest lease
                    # here would hand the SAME pages to a new batch while an
                    # in-flight dispatch may still be reading them (torn
                    # frames). A one-off non-pooled buffer costs the page
                    # faults the pool exists to avoid, but only on the
                    # already-broken leak path — correctness over speed.
                    import logging

                    logging.getLogger("vep.engine.collector").warning(
                        "batch pool for shape %s hit %d buffers; handing "
                        "out a one-off non-pooled buffer (a consumer is "
                        "not calling Collector.release)", shape,
                        self.MAX_POOL_BUFFERS,
                    )
                    return np.zeros(shape, np.uint8), None
                slot["bufs"].append(np.zeros(shape, np.uint8))
                idx = len(slot["bufs"]) - 1
            slot["cur"].append(idx)
            return slot["bufs"][idx], idx

    def pool_nbytes(self) -> int:
        """Total bytes held by the pooled batch buffers across shapes —
        the obs/hbm.py ``register_pool`` tap for the collector's host
        staging pool (the canvas/batch buffers the device step reads
        from). Sums live ``.nbytes`` under the pool lock so the figure
        is exact against the constituent arrays at any instant."""
        with self._pool_lock:
            return sum(buf.nbytes for slot in self._pool.values()
                       for buf in slot["bufs"])

    def _unrotate(self, shape: tuple) -> None:
        """No group was emitted from the last-handed-out buffer (every
        read came back empty): hand it back so idle ticks do not grow the
        pool or burn the one-tick safety margin for consumers still
        holding the previous tick's frames."""
        with self._pool_lock:
            slot = self._pool[shape]
            if slot["cur"]:
                slot["cur"].pop()

    def _lease(self, group: BatchGroup, shape: tuple, idx) -> None:
        """Under strict leasing, tie the group to its pooled buffer: the
        pool will not reuse it until release(group). ``idx`` None = the
        failsafe handed out a one-off non-pooled buffer — nothing to
        lease, release(group) stays a no-op."""
        if not self._strict_lease or idx is None:
            return
        with self._pool_lock:
            self._pool[shape]["leased"].append(idx)
            group.lease = (shape, idx)

    def release(self, group: BatchGroup) -> None:
        """Return a strict-leased group's buffer to the pool (called by
        the engine's drain thread once the batch is emitted — i.e. once
        nothing can still be reading the host frames). No-op for
        generic-path groups (fresh allocations) and non-strict mode."""
        if group.lease is None:
            return
        shape, idx = group.lease
        group.lease = None
        with self._pool_lock:
            slot = self._pool.get(shape)
            if slot is not None:
                try:
                    slot["leased"].remove(idx)
                except ValueError:
                    pass   # double release / unknown lease: stay robust

    def _zero_pad_rows(self, buf: np.ndarray, shape: tuple, idx,
                       n: int, touched: int) -> None:
        """Zero only the pooled buffer rows that may actually be dirty,
        instead of memset-ing the full pad tail every tick: the pool
        tracks a per-buffer dirty high-water mark ("fill"), so a steady
        16-stream batch re-zeroes nothing and the ~100 MB/tick frame
        plane is touched exactly once (the bus copy). ``touched`` is the
        caller's per-tick attempt high-water — one past the highest slot
        any read_latest_into call targeted, including calls that did NOT
        join the batch: a drifted/raced read may leave a partial write in
        its target slot before the geometry check fails (bus/shm_bus.py
        seqlock reader copies before validating). Invariant after this
        call: rows >= n of ``buf`` are zero and fill[idx] == n. ``idx``
        None = one-off failsafe buffer, freshly np.zeros — nothing to
        do."""
        if idx is None:
            return
        touched = min(max(touched, n), buf.shape[0])
        with self._pool_lock:
            slot = self._pool.get(shape)
            if slot is None:                 # defensive: shape evicted
                dirty = buf.shape[0]
            else:
                fill = slot["fill"]
                # Fresh pool buffers are np.zeros => default high-water 0.
                dirty = max(fill.get(idx, 0), touched)
                fill[idx] = n
        if dirty > n:
            buf[n:dirty] = 0

    def _zero_pad_rows_sharded(self, buf: np.ndarray, shape: tuple, idx,
                               real: set, bucket: int, touched: int) -> None:
        """Shard-layout twin of _zero_pad_rows: padding is interleaved
        (each shard's segment pads independently), so instead of one
        contiguous tail the dirty rows are "every row in the dirty extent
        not carrying a real frame". Restores the pool invariant rows >=
        ``bucket`` are zero (fill[idx] == bucket) plus the sharded one:
        interior pad rows inside the view are zero."""
        touched = min(max(touched, bucket), buf.shape[0])
        dirty = touched
        if idx is not None:
            with self._pool_lock:
                slot = self._pool.get(shape)
                if slot is None:             # defensive: shape evicted
                    dirty = buf.shape[0]
                else:
                    fill = slot["fill"]
                    dirty = max(fill.get(idx, 0), touched)
                    fill[idx] = bucket
        for r in range(dirty):
            if r not in real:
                buf[r] = 0

    def _finish_sharded(self, buf: np.ndarray, shape: tuple, idx,
                        per: List[list], seg_src: int, bucket: int,
                        touched: int, *, src_hw: tuple,
                        model: str) -> BatchGroup:
        """Compact per-shard rows from allocation spacing (``seg_src``
        rows per shard) down to the final bucket's spacing, zero the
        dirty pad rows, and build the shard-segmented BatchGroup.
        ``per[s]`` is shard s's (device_id, meta) list in read order.
        Compaction is overlap-safe: with seg <= seg_src the destination
        row s*seg+i never exceeds the source row s*seg_src+i, and
        ascending (s, i) order means every source is read before any
        later destination could land on it."""
        seg = bucket // self._shards
        ids: List[str] = []
        metas: List[FrameMeta] = []
        rows: List[int] = []
        real: set = set()
        for s, entries in enumerate(per):
            for i, (device_id, meta) in enumerate(entries):
                old = s * seg_src + i
                new = s * seg + i
                if new != old:
                    buf[new] = buf[old]
                ids.append(device_id)
                metas.append(meta)
                rows.append(new)
                real.add(new)
        self._zero_pad_rows_sharded(buf, shape, idx, real, bucket, touched)
        group = BatchGroup(
            src_hw=src_hw, device_ids=ids, frames=buf[:bucket],
            metas=metas, bucket=bucket, model=model, rows=rows,
        )
        self._lease(group, shape, idx)
        return group

    def _by_shard(self, devs: Sequence) -> List[list]:
        """Partition a stream list (or (device_id, ...) tuple list) into
        per-shard lists, preserving order within each shard."""
        out: List[list] = [[] for _ in range(self._shards)]
        fn = self._shard_fn
        for item in devs:
            did = item if isinstance(item, str) else item[0]
            s = stream_shard(did, self._shards) if fn is None else fn(did)
            out[s % self._shards].append(item)
        return out

    def repin(self, *, shards: int, shard_of,
              buckets: Optional[Sequence[int]] = None) -> None:
        """Survivor-mesh failover re-pin (device-fault domain, r22): swap
        the routing function and shard count in one tick-thread call.
        ``shard_of`` is a ``make_repin`` closure (or any stream -> shard
        map the engine installs — engine and collector MUST share it,
        same invariant as ``stream_shard``). The live assembly window is
        invalidated: its slot plan was laid out under the old routing and
        would land frames in segments the new mesh does not own; the
        frames are still on their rings and next tick's plan re-reads
        them (latest-wins, nothing lost). ``buckets`` replaces the bucket
        list (the survivor dp count divides a different subset); buckets
        not divisible by the new shard count are dropped, engine
        pre-filter convention."""
        self._window = None
        self._shards = max(1, int(shards))
        self._shard_fn = shard_of if self._shards > 1 else None
        if buckets is not None:
            sharded = tuple(sorted(
                b for b in buckets if b % self._shards == 0))
            if sharded:
                self._buckets = sharded

    # -- incremental batch assembly (between ticks) --

    def assemble_until(
        self, deadline: float, device_ids: Optional[Sequence[str]] = None,
        stop_event=None,
    ) -> None:
        """Overlap batch assembly with frame arrival (VERDICT r4 next
        #1b): instead of sleeping out the tick remainder and memcpy-ing
        every stream's frame at collect() time — which put the whole
        ~100 MB/tick frame plane between a camera's publish and its
        dispatch (pub_to_collect p50 3x the memcpy floor) — plan the next
        tick's batches now and copy each frame into its pooled slot the
        moment its producer publishes. The bus doorbell (futex on shm,
        condition on memory) wakes the sweep per publish with zero idle
        CPU; backends without a doorbell (Redis: every poll is a network
        round trip) sleep to the deadline and keep the collect-time path.

        Runs on the engine thread between ticks; ``deadline`` is
        time.monotonic-based; ``device_ids`` is the inferred set from
        partition() (a stream gated after planning still emits one last
        result at finalize — gating is linger-tolerant by design)."""
        remaining = deadline - time.monotonic()
        if not getattr(self._bus, "doorbell", False):
            if remaining > 0:
                if stop_event is not None:
                    stop_event.wait(remaining)
                else:
                    time.sleep(remaining)
            return
        if remaining <= 0:
            return
        self.plan_assembly(device_ids)
        token = self._bus.doorbell_token()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            if stop_event is not None and stop_event.is_set():
                return
            token = self._bus.doorbell_wait(token, min(remaining, 0.1))
            self.assemble_step()

    def plan_assembly(
        self, device_ids: Optional[Sequence[str]] = None
    ) -> None:
        """Lay out next tick's fast-path batches: (model, geometry)
        grouping and bucket chunking identical to collect()'s, with a
        pooled buffer acquired per group. Streams with unknown geometry
        or clip assembly stay unplanned (they take collect()'s generic
        path and join the window next tick)."""
        if device_ids is None:
            device_ids = self.inference_streams()
        buckets = self._effective_buckets()
        max_bucket = buckets[-1]
        fast_plan: Dict[tuple, list] = {}
        for device_id in device_ids:
            model, clip_len = self._stream_model(device_id)
            geom = self._geom.get(device_id)
            if not clip_len and geom is not None:
                fast_plan.setdefault((model, geom), []).append(device_id)
        groups: Dict[tuple, dict] = {}
        of: Dict[str, tuple] = {}
        shard_of: Dict[str, int] = {}
        for (model, geom), devs in sorted(fast_plan.items()):
            if self._shards > 1:
                # Shard-segmented window groups: chunk capacity is per
                # shard (a chunk fills when its fullest SHARD fills), and
                # a stream's slot is pinned inside its shard's segment.
                cap = max_bucket // self._shards
                by_shard = self._by_shard(devs)
                n_chunks = max(((len(l) + cap - 1) // cap
                                for l in by_shard if l), default=0)
                for ci in range(n_chunks):
                    chunk = [l[ci * cap:(ci + 1) * cap] for l in by_shard]
                    need = max(len(l) for l in chunk)
                    alloc = next(b for b in buckets
                                 if b // self._shards >= need)
                    shape = (alloc,) + geom
                    buf, bidx = self._pooled(shape)
                    key = (model, geom, ci)
                    groups[key] = {
                        "model": model, "geom": geom, "shape": shape,
                        "buf": buf, "idx": bidx,
                        "per": [[] for _ in range(self._shards)],
                        "entry": {}, "slot": {},
                        "seg": alloc // self._shards,
                        "hw": 0,   # attempt high-water
                    }
                    for s, shard_devs in enumerate(chunk):
                        for device_id in shard_devs:
                            of[device_id] = key
                            shard_of[device_id] = s
                continue
            for ci, start in enumerate(range(0, len(devs), max_bucket)):
                chunk = devs[start:start + max_bucket]
                alloc = next(b for b in buckets if b >= len(chunk))
                shape = (alloc,) + geom
                buf, bidx = self._pooled(shape)
                key = (model, geom, ci)
                groups[key] = {
                    "model": model, "geom": geom, "shape": shape,
                    "buf": buf, "idx": bidx,
                    "ids": [], "metas": [], "slot": {},
                    "hw": 0,   # attempt high-water for _zero_pad_rows
                }
                for device_id in chunk:
                    of[device_id] = key
        self._window = {"groups": groups, "of": of, "spill": [],
                        "shard": shard_of}

    def assemble_step(self) -> int:
        """One pass over the planned streams: copy any newly published
        frame straight into its group's next free slot (latest-wins: a
        second publish within the window overwrites the stream's slot).
        Returns how many frames were copied."""
        win = self._window
        if win is None:
            return 0
        got = 0
        drifted: List[str] = []
        for device_id, key in win["of"].items():
            cursor = self._cursors.get(device_id, 0)
            head = self._bus.head(device_id)
            if head is not None and head < cursor:
                # Ring recreated under us — see _rebase_if_restarted.
                self._cursors.pop(device_id, None)
                cursor = 0
            if head is not None and head <= cursor:
                continue   # idle ring: one cheap load, no read setup
            g = win["groups"][key]
            slot = g["slot"].get(device_id)
            sharded = "per" in g
            if sharded:
                s = win["shard"][device_id]
                t = slot if slot is not None \
                    else g["seg"] * s + len(g["per"][s])
            else:
                t = slot if slot is not None else len(g["ids"])
            g["hw"] = max(g["hw"], t + 1)   # slot t may get partial bytes
            res = self._bus.read_latest_into(
                device_id, g["buf"][t], min_seq=cursor,
            )
            if res is None:
                continue
            if isinstance(res, Frame):   # geometry drifted mid-window
                self._note_read(device_id, res.seq, res.meta)
                if res.data.ndim == 3:
                    self._geom[device_id] = res.data.shape
                win["spill"].append((device_id, g["model"], res))
                drifted.append(device_id)
                continue
            seq, meta = res
            self._note_read(device_id, seq, meta)
            if sharded:
                if slot is None:
                    g["slot"][device_id] = t
                    g["entry"][device_id] = (s, len(g["per"][s]))
                    g["per"][s].append((device_id, meta))
                else:
                    es, ei = g["entry"][device_id]
                    g["per"][es][ei] = (device_id, meta)
            elif slot is None:
                g["slot"][device_id] = len(g["ids"])
                g["ids"].append(device_id)
                g["metas"].append(meta)
            else:
                g["metas"][slot] = meta
            got += 1
        for device_id in drifted:
            del win["of"][device_id]
        return got

    def collect(
        self, device_ids: Optional[Sequence[str]] = None
    ) -> List[BatchGroup]:
        """One tick: newest unseen frame per stream -> (model, shape)-
        grouped, bucket-padded batches (clips for video models).
        ``device_ids``: precomputed inferred set (from ``partition``);
        None re-enumerates.

        Single-pass hot path: non-clip streams whose geometry is known
        from a previous tick are read by the bus DIRECTLY into pooled
        batch slots (`read_latest_into`) — ring to device batch in one
        memory pass. First-sight streams, clip assembly, and geometry
        drift take the generic frame path and join the fast path next
        tick."""
        if device_ids is None:
            device_ids = self.inference_streams()
        self._begin_tick()
        buckets = self._effective_buckets()
        max_bucket = buckets[-1]

        groups: List[BatchGroup] = []
        spill: List[tuple] = []             # geometry drifted mid-plan
        win_planned: set = set()
        win = self._window
        if win is not None:
            # Finalize the assembly window: one catch-up sweep for frames
            # published since the last doorbell wake, then emit the
            # incrementally filled batches as-is — their copies already
            # happened, overlapped with arrival.
            self.assemble_step()
            self._window = None
            win_planned = set(win["of"])
            spill.extend(win["spill"])
            for key, g in sorted(win["groups"].items()):
                if "per" in g:   # shard-segmented window group
                    counts = [len(p) for p in g["per"]]
                    if not any(counts):
                        continue   # idle; buffer ages out via epochs
                    bucket = next(b for b in self._buckets
                                  if b // self._shards >= max(counts))
                    groups.append(self._finish_sharded(
                        g["buf"], g["shape"], g["idx"], g["per"],
                        g["seg"], bucket, g["hw"],
                        src_hw=g["geom"][:2], model=g["model"]))
                    continue
                n = len(g["ids"])
                if n == 0:
                    continue   # idle group; its buffer ages out via epochs
                # Full bucket list, NOT the capped one: the window buffer
                # was allocated before a cap could land, and its alloc is
                # always a member of the full list >= n.
                bucket = next(b for b in self._buckets if b >= n)
                self._zero_pad_rows(g["buf"], g["shape"], g["idx"], n,
                                    g["hw"])
                view = g["buf"][:bucket]
                group = BatchGroup(
                    src_hw=g["geom"][:2], device_ids=g["ids"],
                    frames=view, metas=g["metas"], bucket=bucket,
                    model=g["model"],
                )
                self._lease(group, g["shape"], g["idx"])
                groups.append(group)

        fast_plan: Dict[tuple, list] = {}   # (model, (h,w,c)) -> [ids]
        slow_ids: List[str] = []
        for device_id in device_ids:
            if device_id in win_planned:
                continue   # already served (or known idle) via the window
            model, clip_len = self._stream_model(device_id)
            geom = self._geom.get(device_id)
            if clip_len or geom is None:
                slow_ids.append(device_id)
            else:
                fast_plan.setdefault((model, geom), []).append(device_id)

        for (model, geom), devs in sorted(fast_plan.items()):
            if self._shards > 1:
                self._collect_fast_sharded(
                    model, geom, devs, buckets, groups, spill)
                continue
            for start in range(0, len(devs), max_bucket):
                chunk = devs[start:start + max_bucket]
                alloc = next(b for b in buckets if b >= len(chunk))
                batch, bidx = self._pooled((alloc,) + geom)
                ids: List[str] = []
                metas: List[FrameMeta] = []
                hw = 0   # attempt high-water for _zero_pad_rows
                for device_id in chunk:
                    hw = max(hw, len(ids) + 1)
                    res = self._bus.read_latest_into(
                        device_id, batch[len(ids)],
                        min_seq=self._cursors.get(device_id, 0),
                    )
                    if res is None and self._rebase_if_restarted(device_id):
                        res = self._bus.read_latest_into(
                            device_id, batch[len(ids)], min_seq=0,
                        )
                    if res is None:
                        continue
                    if isinstance(res, Frame):   # geometry drifted
                        self._note_read(device_id, res.seq, res.meta)
                        if res.data.ndim == 3:   # corrupt 1-D frames must
                            # not poison the geometry cache (generic-path
                            # guard below applies here too)
                            self._geom[device_id] = res.data.shape
                        spill.append((device_id, model, res))
                        continue
                    seq, meta = res
                    self._note_read(device_id, seq, meta)
                    ids.append(device_id)
                    metas.append(meta)
                n = len(ids)
                if not n:
                    if bidx is not None:
                        # One-off failsafe buffers never entered "cur";
                        # unrotating would pop a legitimate same-tick entry.
                        self._unrotate((alloc,) + geom)
                    continue
                bucket = next(b for b in buckets if b >= n)
                self._zero_pad_rows(batch, (alloc,) + geom, bidx, n, hw)
                view = batch[:bucket]
                group = BatchGroup(
                    src_hw=geom[:2], device_ids=ids, frames=view,
                    metas=metas, bucket=bucket, model=model,
                )
                self._lease(group, (alloc,) + geom, bidx)
                groups.append(group)

        # Generic path: first sight (geometry unknown), clips, drift.
        by_key: Dict[tuple, list] = {}
        for device_id in slow_ids:
            frame = self._bus.read_latest(
                device_id, min_seq=self._cursors.get(device_id, 0)
            )
            if frame is None and self._rebase_if_restarted(device_id):
                frame = self._bus.read_latest(device_id, min_seq=0)
            if frame is None:
                continue
            self._note_read(device_id, frame.seq, frame.meta)
            model, clip_len = self._stream_model(device_id)
            if frame.data.ndim == 3:
                self._geom[device_id] = frame.data.shape
            hw = frame.data.shape[:2]
            if clip_len:
                window = self._clips.get(device_id)
                if window is None or window.maxlen != clip_len:
                    # (Re)create on clip-length change — a re-added stream
                    # with a different model must not inherit a stale window.
                    window = deque(maxlen=clip_len)
                    self._clips[device_id] = window
                window.append(frame)
                if len(window) < clip_len:
                    continue
                sample = np.stack([f.data for f in window])
            else:
                sample = frame.data
            by_key.setdefault((model, hw), []).append(
                (device_id, sample, frame.meta)
            )
        for device_id, model, frame in spill:
            by_key.setdefault((model, frame.data.shape[:2]), []).append(
                (device_id, frame.data, frame.meta)
            )

        for (model, hw), items in sorted(by_key.items()):
            if self._shards > 1:
                self._collect_generic_sharded(model, hw, items, buckets,
                                              groups)
                continue
            for start in range(0, len(items), max_bucket):
                chunk = items[start:start + max_bucket]
                n = len(chunk)
                bucket = next(b for b in buckets if b >= n)
                # Fused stack+pad: one pass instead of np.stack + concat.
                batch = np.empty(
                    (bucket,) + chunk[0][1].shape, chunk[0][1].dtype
                )
                for i, (_, arr, _) in enumerate(chunk):
                    batch[i] = arr
                if bucket != n:
                    batch[n:] = 0
                groups.append(BatchGroup(
                    src_hw=hw,
                    device_ids=[d for d, _, _ in chunk],
                    frames=batch,
                    metas=[m for _, _, m in chunk],
                    bucket=bucket,
                    model=model,
                ))
        return groups

    def _collect_fast_sharded(self, model: str, geom: tuple,
                              devs: Sequence[str], buckets: tuple,
                              groups: List[BatchGroup],
                              spill: List[tuple]) -> None:
        """Shard-segmented fast path: one (model, geometry) stream set ->
        pooled, bucket-padded, shard-segmented batches. Streams read
        directly into their shard's segment at allocation spacing; the
        final bucket is the smallest whose PER-SHARD segment covers the
        fullest shard, then _finish_sharded compacts the segments down."""
        S = self._shards
        max_bucket = buckets[-1]
        cap = max_bucket // S        # per-shard chunk capacity
        by_shard = self._by_shard(devs)
        n_chunks = max(((len(l) + cap - 1) // cap for l in by_shard if l),
                       default=0)
        for c in range(n_chunks):
            chunk = [l[c * cap:(c + 1) * cap] for l in by_shard]
            need = max(len(l) for l in chunk)
            alloc = next(b for b in buckets if b // S >= need)
            shape = (alloc,) + geom
            batch, bidx = self._pooled(shape)
            seg_a = alloc // S
            per: List[list] = [[] for _ in range(S)]
            touched = 0   # attempt high-water (one past highest row hit)
            for s, shard_devs in enumerate(chunk):
                for device_id in shard_devs:
                    t = s * seg_a + len(per[s])
                    touched = max(touched, t + 1)
                    res = self._bus.read_latest_into(
                        device_id, batch[t],
                        min_seq=self._cursors.get(device_id, 0),
                    )
                    if res is None and self._rebase_if_restarted(device_id):
                        res = self._bus.read_latest_into(
                            device_id, batch[t], min_seq=0,
                        )
                    if res is None:
                        continue
                    if isinstance(res, Frame):   # geometry drifted
                        self._note_read(device_id, res.seq, res.meta)
                        if res.data.ndim == 3:
                            self._geom[device_id] = res.data.shape
                        spill.append((device_id, model, res))
                        continue
                    seq, meta = res
                    self._note_read(device_id, seq, meta)
                    per[s].append((device_id, meta))
            counts = [len(p) for p in per]
            if not any(counts):
                if bidx is not None:
                    self._unrotate(shape)
                continue
            bucket = next(b for b in buckets if b // S >= max(counts))
            groups.append(self._finish_sharded(
                batch, shape, bidx, per, seg_a, bucket, touched,
                src_hw=geom[:2], model=model))

    def _collect_generic_sharded(self, model: str, hw: tuple,
                                 items: Sequence[tuple], buckets: tuple,
                                 groups: List[BatchGroup]) -> None:
        """Shard-segmented generic path (first sight, clips, drift):
        fresh zeroed buffer, samples written straight at final-bucket
        spacing — no compaction needed, interior pads already zero."""
        S = self._shards
        cap = buckets[-1] // S
        by_shard = self._by_shard(items)
        n_chunks = max(((len(l) + cap - 1) // cap for l in by_shard if l),
                       default=0)
        for c in range(n_chunks):
            chunk = [l[c * cap:(c + 1) * cap] for l in by_shard]
            need = max(len(l) for l in chunk)
            bucket = next(b for b in buckets if b // S >= need)
            seg = bucket // S
            first = next(l[0] for l in chunk if l)
            batch = np.zeros((bucket,) + first[1].shape, first[1].dtype)
            ids: List[str] = []
            metas: List[FrameMeta] = []
            rows: List[int] = []
            for s, shard_items in enumerate(chunk):
                for i, (device_id, arr, meta) in enumerate(shard_items):
                    batch[s * seg + i] = arr
                    ids.append(device_id)
                    metas.append(meta)
                    rows.append(s * seg + i)
            groups.append(BatchGroup(
                src_hw=hw, device_ids=ids, frames=batch, metas=metas,
                bucket=bucket, model=model, rows=rows,
            ))

    def drop_stream(self, device_id: str) -> None:
        self._cursors.pop(device_id, None)
        self._clips.pop(device_id, None)
        self._geom.pop(device_id, None)
        self._last_interest.pop(device_id, None)
