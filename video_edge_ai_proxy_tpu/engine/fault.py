"""Device-fault domain for the mesh engine (r22 tentpole).

The reference pipeline's failure handling is host-side only: a worker
process that dies is restarted by the process manager
(`/root/reference/server/services/process.go:113-160`) and its stream
resumes from the ring — the accelerator itself is assumed immortal.
Once serving is dp-sharded over a multi-chip mesh (r17/r20) that
assumption is the dominant availability gap: one wedged or failed chip
zeroes the whole member's capacity. This module is the device-side
fault domain the reference never needed:

- :class:`FaultLedger` — frame-conservation accounting across a
  failover. Every frame handed to the device pipeline is counted out
  again as emitted or as a reasoned drop, with fault windows declared
  explicitly, so "we lost nothing outside the fault window" is a
  checkable balance (MigrationLedger convention, serve/router.py), not
  a hope.
- :class:`FaultPlane` — per-dispatch deadline/error watchdog state:
  hard faults (an XLA error attributed to a shard), stall suspicion
  (drain fetch overrunning ``fault_dispatch_deadline_ms`` for
  ``fault_hysteresis`` consecutive batches, attributed by a per-shard
  probe), the pending-failover handoff to the tick thread, and the
  ``vep_fault_*`` metric families + ``/api/v1/faults`` snapshot.

The failover itself (survivor mesh rebuild, AOT-warm recompile,
rendezvous stream re-pin, counted-reset state evacuation) runs in
``InferenceEngine._execute_failover`` on the tick thread; this module
deliberately imports no jax so the control surface stays importable
without a backend (CLAUDE.md lazy-import rule).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..obs import registry as obs_registry


class FaultLedger:
    """Frame-conservation proof for the device-fault domain.

    Balance identity: ``dispatched == emitted + sum(dropped) + lost``
    where ``lost`` is the residual — zero once the pipeline quiesces.
    Drops carry a reason and whether a declared fault window was open;
    ``device_fault`` drops outside any window are loss the failover
    cannot excuse (``lost_outside_window``). Duplicates are detected by
    per-stream sequence monotonicity — the engine keys emissions on
    ``(packet, timestamp_ms)`` so producers that never stamp packet ids
    still order by capture time: re-emitting a key a stream already
    emitted is a duplicate; a key *below* the last one is a producer
    restart rebase (bus rings renumber on re-create — legitimate,
    counted separately, never a duplicate)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.dispatched = 0
        self.emitted = 0
        self.duplicated = 0
        self.rebased = 0
        self.dropped: Dict[str, int] = {}
        self.dropped_outside_window = 0
        self._last_seq: Dict[str, int] = {}
        self._windows: List[dict] = []
        self._open: Optional[dict] = None

    # -- taps (engine tick / drain threads) --

    def note_dispatched(self, n: int) -> None:
        with self._lock:
            self.dispatched += int(n)

    def note_emitted(self, stream: str, seq) -> None:
        with self._lock:
            self.emitted += 1
            last = self._last_seq.get(stream)
            if last is not None:
                if seq == last:
                    self.duplicated += 1
                elif seq < last:
                    self.rebased += 1
            self._last_seq[stream] = seq

    def note_dropped(self, n: int, reason: str) -> None:
        with self._lock:
            self.dropped[reason] = self.dropped.get(reason, 0) + int(n)
            if reason == "device_fault" and self._open is None:
                self.dropped_outside_window += int(n)

    # -- fault windows --

    def open_window(self, reason: str) -> None:
        with self._lock:
            if self._open is None:
                self._open = {"reason": reason, "opened": self._clock(),
                              "closed": None}

    def close_window(self) -> None:
        with self._lock:
            if self._open is not None:
                self._open["closed"] = self._clock()
                self._windows.append(self._open)
                self._open = None

    @property
    def window_open(self) -> bool:
        with self._lock:
            return self._open is not None

    def balance(self) -> dict:
        """The conservation verdict. ``lost`` > 0 means frames entered
        the pipeline and never came out under ANY counted reason — only
        meaningful once in-flight batches have drained (callers quiesce
        first; a live snapshot legitimately shows the drain queue's
        depth here)."""
        with self._lock:
            dropped = dict(self.dropped)
            lost = self.dispatched - self.emitted - sum(dropped.values())
            return {
                "dispatched": self.dispatched,
                "emitted": self.emitted,
                "dropped": dropped,
                "duplicated": self.duplicated,
                "rebased": self.rebased,
                "lost": lost,
                "lost_outside_window": self.dropped_outside_window
                + max(0, lost),
                "windows": [dict(w) for w in self._windows]
                + ([dict(self._open)] if self._open else []),
            }


class FaultPlane:
    """Watchdog state machine + obs surface for the device-fault domain.

    States per engine: healthy -> (hard error | stall suspicion ->
    probe) -> shards pending failover -> failover executed by the tick
    thread -> healthy over the survivor mesh. Detection runs where the
    signal is (errors on the tick thread, deadline overruns on the
    drain thread); the failover handoff is the ``pending`` map, drained
    by the tick thread only — one writer for every mesh mutation."""

    EVENTS_KEEP = 32

    def __init__(self, *, shards: int = 1,
                 deadline_ms: float = 5000.0,
                 hysteresis: int = 2,
                 failover_budget_ms: float = 30000.0,
                 probe_timeout_ms: float = 2000.0,
                 clock=time.monotonic, journal=None):
        self.deadline_ms = float(deadline_ms)
        self.hysteresis = max(1, int(hysteresis))
        self.failover_budget_ms = float(failover_budget_ms)
        self.probe_timeout_ms = float(probe_timeout_ms)
        self.shards = max(1, int(shards))
        self.ledger = FaultLedger(clock=clock)
        self._clock = clock
        # r23 decision journal: detection and failover are audit events;
        # last_detected_seq is the cause handle the engine links its
        # failover (and the supervisor its device_fault spawn) to.
        self.journal = journal
        self.last_detected_seq: Optional[int] = None
        self._lock = threading.Lock()
        self._overruns = 0              # consecutive drain overruns
        self._suspect_since: Optional[float] = None
        self._pending: Dict[int, str] = {}   # shard -> fault kind
        self._events: deque = deque(maxlen=self.EVENTS_KEEP)
        self.failovers = 0
        # Per-shard stall attribution: None = engine default probe (a
        # tiny device round-trip per shard lead, bounded by
        # probe_timeout_ms); tests and the chaos soak inject their own.
        # Returns the list of faulted shard indices (current numbering).
        self.probe_fn = None
        self._m_detected = obs_registry.counter(
            "vep_fault_detected_total",
            "Device faults detected, by kind", ("kind",))
        self._m_failovers = obs_registry.counter(
            "vep_fault_failovers_total",
            "Survivor-mesh failovers executed, by outcome", ("outcome",))
        self._m_failover_ms = obs_registry.histogram(
            "vep_fault_failover_ms",
            "Failover wall time, detection handoff to survivor mesh "
            "serving (ms)").labels()
        self._m_dropped = obs_registry.counter(
            "vep_fault_dropped_frames_total",
            "Frames dropped by the device-fault domain, by reason",
            ("reason",))
        self._m_evacuated = obs_registry.counter(
            "vep_fault_evacuated_total",
            "Sharded carry-state entries counted-reset at failover",
            ("kind",))
        self._m_shards = obs_registry.gauge(
            "vep_fault_survivor_shards",
            "Mesh shards currently serving (shrinks on failover)"
        ).labels()
        self._m_overruns = obs_registry.counter(
            "vep_fault_deadline_overruns_total",
            "Drain fetches exceeding fault_dispatch_deadline_ms").labels()
        self._m_shards.set(self.shards)

    def configure(self, *, shards: int,
                  shard_devices: Optional[Dict[int, List[str]]] = None
                  ) -> None:
        """Engine wiring at warmup (and after every mesh swap): the live
        shard count and the shard -> device-name attribution map."""
        with self._lock:
            self.shards = max(1, int(shards))
        self._m_shards.set(self.shards)
        if shard_devices is not None:
            self.set_shard_devices(shard_devices)

    # -- detection taps --

    def note_drain(self, device_ms: float) -> None:
        """Drain-thread tap, once per fetched batch: deadline overrun
        hysteresis. Consecutive overruns >= the hysteresis open a stall
        suspicion for the tick thread to probe; one on-time batch closes
        it (a transient contention spike is not a dead chip)."""
        with self._lock:
            if device_ms > self.deadline_ms:
                self._overruns += 1
                self._m_overruns.inc()
                if self._overruns >= self.hysteresis \
                        and self._suspect_since is None:
                    self._suspect_since = self._clock()
            else:
                self._overruns = 0
                self._suspect_since = None

    def stall_suspected(self) -> bool:
        with self._lock:
            return self._suspect_since is not None and not self._pending

    def resolve_stall(self, faulted: Sequence[int], tick: int) -> List[int]:
        """Tick-thread probe verdict: ``faulted`` shards (possibly
        empty — generic slowness, not a dead chip) resolve the open
        suspicion. Faulted shards become pending and open the ledger's
        fault window at detection time."""
        marked = []
        with self._lock:
            self._suspect_since = None
            self._overruns = 0
            for s in faulted:
                s = int(s)
                if s not in self._pending:
                    self._pending[s] = "stall"
                    marked.append(s)
        for s in marked:
            self._m_detected.labels("stall").inc()
            self._note_detected("stall", s, tick)
        if marked:
            self.ledger.open_window("stall")
        return marked

    def note_error(self, exc: BaseException, tick: int) -> Optional[int]:
        """Tick-thread tap from the dispatch error path: classify a step
        exception. A shard attribution (the injected wrapper's
        ``fault_shard`` attribute, or a device name from the registered
        shard->devices map appearing in the message) marks the shard
        pending and opens the fault window; unattributable errors stay
        the tick loop's log-and-continue problem."""
        shard = getattr(exc, "fault_shard", None)
        if shard is None:
            text = str(exc)
            for s, names in getattr(self, "_shard_devices", {}).items():
                if any(n and n in text for n in names):
                    shard = s
                    break
        if shard is None:
            return None
        shard = int(shard)
        with self._lock:
            fresh = shard not in self._pending
            self._pending[shard] = "xla_error"
        if fresh:
            self._m_detected.labels("xla_error").inc()
            self._note_detected("xla_error", shard, tick)
            self.ledger.open_window("xla_error")
        return shard

    def set_shard_devices(self, shard_devices: Dict[int, List[str]]) -> None:
        """Register shard -> device-name strings for error attribution
        (re-registered by the engine after every mesh swap)."""
        self._shard_devices = {
            int(s): [str(n) for n in names]
            for s, names in shard_devices.items()
        }

    def _note_detected(self, kind: str, shard: int, tick: int) -> None:
        with self._lock:
            self._events.append({
                "event": "detected", "kind": kind, "shard": shard,
                "tick": tick, "ts": time.time(),
            })
        if self.journal is not None:
            self.last_detected_seq = self.journal.record(
                "fault", "detected", subject=("shard", str(shard)),
                trigger={"kind": kind, "tick": int(tick)})

    # -- failover handoff (tick thread) --

    def pending(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._pending)

    def clear_pending(self, outcome: str = "skipped") -> None:
        """Abandon pending faults without a failover (no survivors, no
        mesh, unattributable) — the window closes so later drops are not
        excused by a failover that never ran."""
        with self._lock:
            had = bool(self._pending)
            pending = dict(self._pending)
            self._pending.clear()
        if had:
            self._m_failovers.labels(outcome).inc()
            self.ledger.close_window()
            if self.journal is not None:
                self.journal.record(
                    "fault", "failover_skipped",
                    subject=("shard", ",".join(
                        str(s) for s in sorted(pending))),
                    trigger={"outcome": outcome,
                             "pending": len(pending)},
                    cause=self.last_detected_seq)

    def note_failover(self, event: dict) -> None:
        """Record a completed failover: closes the fault window, updates
        the survivor-shard gauge, appends the event (served verbatim by
        ``/api/v1/faults`` and mined by tools/fault_smoke.py)."""
        with self._lock:
            self._pending.clear()
            self.shards = int(event.get("survivors", self.shards))
            self._events.append(dict(event, event="failover"))
            self.failovers += 1
        self._m_failovers.labels(
            "over_budget" if event.get("over_budget") else "ok").inc()
        self._m_failover_ms.observe(float(event.get("failover_ms", 0.0)))
        self._m_shards.set(self.shards)
        for kind, n in (event.get("evacuated") or {}).items():
            if n:
                self._m_evacuated.labels(str(kind)).inc(int(n))
        self.ledger.close_window()
        if self.journal is not None:
            dead = event.get("shards_dead") or []
            streams = event.get("streams") or {}
            self.journal.record(
                "fault", "failover",
                subject=("shard", ",".join(str(s) for s in dead)),
                trigger={"kinds": ",".join(
                    str(k) for k in (event.get("kinds") or [])) or "unknown",
                    "survivors": int(event.get("survivors", 0)),
                    "failover_ms": round(
                        float(event.get("failover_ms", 0.0)), 1),
                    "repinned": int(streams.get("repinned", 0))},
                cause=self.last_detected_seq)

    def note_dropped(self, n: int, reason: str) -> None:
        """Ledger + metric tap for reasoned frame drops (the lineage
        tracer records the per-frame side separately)."""
        if n <= 0:
            return
        self.ledger.note_dropped(n, reason)
        self._m_dropped.labels(reason).inc(int(n))

    # -- introspection --

    def snapshot(self) -> dict:
        """The ``/api/v1/faults`` document."""
        with self._lock:
            pending = dict(self._pending)
            events = [dict(e) for e in self._events]
            suspect = self._suspect_since is not None
            overruns = self._overruns
            shards = self.shards
            failovers = self.failovers
        return {
            "config": {
                "deadline_ms": self.deadline_ms,
                "hysteresis": self.hysteresis,
                "failover_budget_ms": self.failover_budget_ms,
                "probe_timeout_ms": self.probe_timeout_ms,
            },
            "shards": shards,
            "failovers": failovers,
            "active": bool(pending) or self.ledger.window_open,
            "stall_suspected": suspect,
            "consecutive_overruns": overruns,
            "pending": {str(s): k for s, k in pending.items()},
            "events": events,
            "ledger": self.ledger.balance(),
        }
