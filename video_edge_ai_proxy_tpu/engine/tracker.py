"""Per-stream multi-object tracking for the inference plane.

The wire contract always had a slot for this — ``AnnotateRequest
.object_tracking_id`` (`/root/reference/proto/video_streaming.proto:15`) —
but the reference expects *external* ML clients to fill it. Our engine
produces the detections, so it can produce stable track ids too:
a SORT-style tracker (greedy IoU association + constant-velocity
extrapolation, no Kalman filter — at 10-30 fps per stream the linear
motion model is the part that matters) runs host-side per stream on the
already-fetched NMS output. Device work is untouched: tracking is O(tracks
× detections) numpy on ≤100 boxes, microseconds next to a device batch.

Association: detections and live tracks are matched greedily by IoU
(same class only, predicted track box vs detection box). Unmatched
detections open new tracks immediately; unmatched tracks coast on their
velocity and are dropped after ``max_misses`` consecutive misses. Ids are
``<stream-scoped monotonic int>`` rendered as strings for the proto field.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Sequence

import numpy as np


@dataclass
class _Track:
    track_id: int
    box: np.ndarray            # xyxy, float32
    velocity: np.ndarray       # d(box)/frame, float32[4]
    class_id: int
    misses: int = 0
    confidence: float = 0.0    # last matched detection's score (ROI
                               # coasted emissions decay from this)


def _iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """[N,4] x [M,4] xyxy -> [N,M] IoU."""
    if len(a) == 0 or len(b) == 0:
        return np.zeros((len(a), len(b)), np.float32)
    tl = np.maximum(a[:, None, :2], b[None, :, :2])
    br = np.minimum(a[:, None, 2:], b[None, :, 2:])
    inter = np.prod(np.maximum(br - tl, 0.0), axis=-1)
    area_a = np.prod(np.maximum(a[:, 2:] - a[:, :2], 0.0), axis=-1)
    area_b = np.prod(np.maximum(b[:, 2:] - b[:, :2], 0.0), axis=-1)
    union = area_a[:, None] + area_b[None, :] - inter
    return (inter / np.maximum(union, 1e-9)).astype(np.float32)


@dataclass
class IoUTracker:
    """One tracker per stream (the engine keeps a dict keyed by device_id)."""

    iou_thresh: float = 0.3
    max_misses: int = 30       # frames a lost track coasts before dropping
    # Wall-clock guard: miss counting only advances when update() runs, so
    # a stream outage (no frames at all) would otherwise freeze tracks at
    # misses=0 and hand an hour-old id to whatever appears near the stale
    # box on reconnect. A gap longer than this clears all tracks (ids keep
    # counting up — see next_id).
    max_gap_s: float = 10.0
    # First id this tracker issues. Stream-scoped uniqueness must survive a
    # tracker reset (model switch), so a replacement tracker is constructed
    # with next_id = predecessor.next_id rather than restarting at 1.
    next_id: int = 1
    _tracks: List[_Track] = field(default_factory=list)
    _last_update: float = 0.0

    def update(
        self,
        boxes: Sequence[Sequence[float]],
        classes: Sequence[int],
        now: float | None = None,
        scores: Sequence[float] | None = None,
    ) -> List[str]:
        """One frame of detections -> one track id per detection, in order.

        ``scores`` (optional, parallel to ``boxes``) stores each matched
        detection's confidence on its track so gated-idle streams can
        emit tracker-coasted results with a decayed confidence
        (``tracks()`` below); omitted → confidences keep their last
        value (new tracks start at 0)."""
        now = time.monotonic() if now is None else now
        if self._last_update and now - self._last_update > self.max_gap_s:
            self._tracks = []
        self._last_update = now
        dets = np.asarray(boxes, np.float32).reshape(-1, 4)
        cls = np.asarray(classes, np.int64).reshape(-1)

        # Predict: coast every live track along its velocity.
        for t in self._tracks:
            t.box = t.box + t.velocity
        pred = (
            np.stack([t.box for t in self._tracks])
            if self._tracks else np.zeros((0, 4), np.float32)
        )
        iou = _iou_matrix(pred, dets)
        # Same-class gating: cross-class pairs can never match.
        for ti, t in enumerate(self._tracks):
            iou[ti, cls != t.class_id] = 0.0

        assigned = [-1] * len(dets)
        used_tracks = set()
        # Greedy: repeatedly take the globally best remaining pair. With
        # <=100 boxes this is exact enough that Hungarian buys nothing.
        while iou.size:
            ti, di = np.unravel_index(np.argmax(iou), iou.shape)
            if iou[ti, di] < self.iou_thresh:
                break
            t = self._tracks[ti]
            # t.box is the *predicted* position, so (det - t.box) is the
            # prediction residual; adding half of it is an EMA (alpha=0.5)
            # over measured per-frame deltas: v += 0.5*(md - v_old).
            t.velocity = t.velocity + 0.5 * (dets[di] - t.box)
            t.box = dets[di].copy()
            t.misses = 0
            if scores is not None:
                t.confidence = float(scores[di])
            assigned[di] = t.track_id
            used_tracks.add(ti)
            iou[ti, :] = -1.0
            iou[:, di] = -1.0

        # Unmatched detections: new tracks, id issued immediately.
        for di in range(len(dets)):
            if assigned[di] == -1:
                t = _Track(
                    track_id=self.next_id,
                    box=dets[di].copy(),
                    velocity=np.zeros(4, np.float32),
                    class_id=int(cls[di]),
                    confidence=(float(scores[di])
                                if scores is not None else 0.0),
                )
                self.next_id += 1
                self._tracks.append(t)
                assigned[di] = t.track_id

        # Unmatched tracks: count the miss, drop the stale.
        survivors = []
        for ti, t in enumerate(self._tracks):
            if ti in used_tracks or t.track_id in assigned:
                survivors.append(t)
            else:
                t.misses += 1
                if t.misses <= self.max_misses:
                    survivors.append(t)
        self._tracks = survivors

        return [str(a) for a in assigned]

    @property
    def live_tracks(self) -> int:
        return len(self._tracks)

    def tracks(self) -> List[dict]:
        """Snapshot of live tracks at their current (predicted) boxes.

        The ROI gate (engine/runner.py) reads this for two things:
        candidate crop rectangles for tracked streams, and
        tracker-coasted result emission for gated-idle streams — call
        ``update([], [])`` first to advance predictions and count the
        miss so stale tracks still expire while a stream is gated.
        Boxes are plain float tuples (xyxy); mutating the snapshot never
        touches tracker state."""
        return [
            {
                "track_id": t.track_id,
                "box": tuple(float(v) for v in t.box),
                "class_id": t.class_id,
                "misses": t.misses,
                "confidence": t.confidence,
            }
            for t in self._tracks
        ]
