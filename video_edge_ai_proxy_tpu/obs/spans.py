"""Per-frame lineage tracing: sampled span events in per-stream rings.

A frame's identity is its packet number stamped at ingest
(``FrameMeta.packet``) keyed by device id — already on the wire, so
lineage needs NO new meta fields. Stages record span events as the frame
flows worker -> bus -> collector -> engine submit -> device -> result
emit. Sampling is 1-in-N on the frame id (deterministic: the SAME frames
are sampled at every stage, so spans join into complete lineages) and the
sampled() check is one modulo + attribute read — the off-hot-path cost
when tracing is disabled is a single boolean test.

Stage vocabulary (the segments a soak report breaks latency into):

- ``publish`` — ingest worker wrote the frame to the bus. Usually in a
  subprocess, so in-process consumers may never see this span; collect
  spans therefore carry ``pub_ms`` (the frame's wall-clock publish stamp)
  so the ingest->collect leg is computable from the engine side alone.
- ``collect`` — engine collector read the frame off the bus.
- ``submit``  — frame's batch was handed to the device drain thread.
- ``device``  — jitted step drained; ``dur_ms`` = device wall time.
- ``emit``    — postprocessed result published to the result plane.
- ``temporal`` — cascade temporal-head pass consumed this frame's track
  crop (temporal/scheduler.py); ``dur_ms`` = head device wall time for
  the pass. Off the per-frame path (cadence 1/N), so lineages show the
  detect→track→temporal→emit join only on head ticks. Not a LEG: the
  stage rides ``stage_breakdown``'s per-stage table and Chrome export,
  but the leg latency table stays per-frame.
- ``dropped`` — terminal: the frame left the pipeline without a result
  (staleness shed, shutdown drain, unrouted ROI crop). Closing the
  lineage here keeps trace export and ``stage_breakdown`` honest about
  drops instead of leaving the span open forever.

Cross-process stitching (r14): the worker stamps ``FrameMeta.trace_id``
(``trace_id_for`` — deterministic, content-derived) at publish; every
span a stage records carries ``trace_id=`` in its extras and the id is
echoed in gRPC/REST responses, so fragments from N processes join into
one trace in the fleet merge (tools/obs_export.py).

Events export as Chrome trace-event JSON (``to_chrome_trace``, loadable
in chrome://tracing / Perfetto) via ``tools/obs_export.py`` and are
queryable live at ``/api/v1/trace``. ``stage_breakdown`` folds a batch of
events into the per-leg latency table the soak artifact embeds.

Pure Python, jax-free. Timestamps are ``time.time()`` seconds (wall
clock) so they align with ``FrameMeta.timestamp_ms`` across processes.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

STAGES = ("publish", "collect", "submit", "device", "emit", "temporal",
          "dropped")

# Latency legs derivable from a complete lineage, in pipeline order.
LEGS = ("ingest_bus", "batch", "device", "emit", "total")

# FNV-1a 64-bit, masked to 63 bits so the id fits every carrier on the
# wire (C int64 in the shm FrameMeta, protobuf int64, JSON) without sign
# surprises.
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_TRACE_MASK = 0x7FFF_FFFF_FFFF_FFFF


def trace_id_for(stream: str, frame_id: int) -> int:
    """Deterministic per-frame trace id: FNV-1a over ``stream:frame``.

    Content-derived (not random) so a replayed trace produces the SAME
    ids run-over-run — replay checksums stay bit-identical with fleet
    telemetry enabled — while ids from different streams/processes land
    in disjoint ranges with high probability. Never returns 0 (0 on the
    wire means "unstamped", and consumers re-derive)."""
    h = _FNV_OFFSET
    for b in f"{stream}:{int(frame_id)}".encode():
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return (h & _TRACE_MASK) or 1


def trace_id_of(meta, stream: str) -> int:
    """The frame's wire trace id, deriving it for unstamped (pre-r14 or
    non-worker) producers so every consumer agrees on the same id."""
    tid = int(getattr(meta, "trace_id", 0) or 0)
    return tid if tid else trace_id_for(stream, getattr(meta, "packet", 0))


class SpanRecorder:
    """Thread-safe sampled span sink with per-stream ring buffers.

    Disabled by default: serving imports this at module load, but tracing
    only turns on when the server/harness calls ``configure``. ``sampled``
    is the hot-path gate — call sites do ``if tracer.sampled(fid): ...``
    so the span-dict build is skipped entirely for unsampled frames.
    """

    def __init__(self, sample_every: int = 16, ring: int = 1024,
                 enabled: bool = False):
        self._lock = threading.Lock()
        self._rings: Dict[str, deque] = {}
        self.sample_every = max(1, int(sample_every))
        self.ring = int(ring)
        self.enabled = bool(enabled)

    def configure(self, *, enabled: Optional[bool] = None,
                  sample_every: Optional[int] = None,
                  ring: Optional[int] = None) -> None:
        if sample_every is not None:
            self.sample_every = max(1, int(sample_every))
        if ring is not None and int(ring) != self.ring:
            self.ring = int(ring)
            with self._lock:
                self._rings = {
                    k: deque(v, maxlen=self.ring)
                    for k, v in self._rings.items()
                }
        if enabled is not None:
            self.enabled = bool(enabled)

    def sampled(self, frame_id: int) -> bool:
        """Deterministic 1-in-N gate; same verdict at every stage."""
        return self.enabled and (int(frame_id) % self.sample_every == 0)

    def record(self, stream: str, stage: str, frame_id: int,
               ts: Optional[float] = None, dur_ms: Optional[float] = None,
               **extra) -> None:
        """Append one span event. ``ts`` = wall-clock seconds at span END
        (defaults to now); ``dur_ms`` = span duration when known."""
        ev = {
            "stream": stream,
            "stage": stage,
            "frame": int(frame_id),
            "ts": time.time() if ts is None else float(ts),
        }
        if dur_ms is not None:
            ev["dur_ms"] = round(float(dur_ms), 4)
        if extra:
            ev.update(extra)
        with self._lock:
            ring = self._rings.get(stream)
            if ring is None:
                ring = deque(maxlen=self.ring)
                self._rings[stream] = ring
            ring.append(ev)

    def events(self, stream: Optional[str] = None,
               limit: Optional[int] = None) -> List[dict]:
        """Snapshot of buffered events (oldest first), optionally one
        stream, optionally the most recent ``limit`` per stream."""
        with self._lock:
            if stream is not None:
                evs = list(self._rings.get(stream, ()))
                if limit:
                    evs = evs[-limit:]
                return evs
            out: List[dict] = []
            for ring in self._rings.values():
                evs = list(ring)
                if limit:
                    evs = evs[-limit:]
                out.extend(evs)
        out.sort(key=lambda e: e["ts"])
        return out

    def streams(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()


# THE process-wide tracer (mirrors ``metrics.registry``). The server and
# the replay harness call ``tracer.configure(enabled=True, ...)``.
tracer = SpanRecorder()


def _lineages(events: Iterable[dict]) -> Dict[tuple, Dict[str, dict]]:
    """Group events by (stream, frame) -> {stage: latest event}."""
    by_frame: Dict[tuple, Dict[str, dict]] = {}
    for ev in events:
        key = (ev.get("stream"), ev.get("frame"))
        by_frame.setdefault(key, {})[ev.get("stage")] = ev
    return by_frame


def _leg_stats(samples: List[float]) -> dict:
    n = len(samples)
    if n == 0:
        return {"count": 0, "avg": None, "p50": None, "p90": None,
                "p99": None}
    s = sorted(samples)

    def q(p: float) -> float:
        idx = min(n - 1, max(0, int(round(p / 100.0 * (n - 1)))))
        return round(s[idx], 3)

    return {"count": n, "avg": round(sum(s) / n, 3), "p50": q(50),
            "p90": q(90), "p99": q(99)}


def stage_breakdown(events: Iterable[dict]) -> dict:
    """Fold span events into per-leg latency stats (ms).

    Legs::

        ingest_bus  publish stamp (pub_ms on the collect span, or the
                    publish span's ts) -> collected off the bus
        batch       collected -> batch submitted to the device thread
        device      device span dur_ms (drained jitted step)
        emit        device drain end -> result emitted
        total       publish stamp -> result emitted

    Partial lineages contribute whichever legs they can; a frame sampled
    mid-flight (ring rolled over) just has fewer legs. Lineages closed by
    a terminal ``dropped`` span (shed, shutdown, unrouted — the r14 fix
    for drop-orphaned spans) are counted under ``drops`` by reason
    instead of silently reading as still-in-flight.
    """
    legs: Dict[str, List[float]] = {leg: [] for leg in LEGS}
    drops: Dict[str, int] = {}
    dropped_total = 0
    for (_, _), stages in _lineages(events).items():
        dropped = stages.get("dropped")
        if dropped is not None:
            dropped_total += 1
            reason = str(dropped.get("reason", "unknown"))
            drops[reason] = drops.get(reason, 0) + 1
        collect = stages.get("collect")
        submit = stages.get("submit")
        device = stages.get("device")
        emit = stages.get("emit")
        publish = stages.get("publish")
        pub_ms = None
        if collect is not None and collect.get("pub_ms") is not None:
            pub_ms = float(collect["pub_ms"])
        elif publish is not None:
            pub_ms = publish["ts"] * 1000.0
        if pub_ms is not None and collect is not None:
            legs["ingest_bus"].append(collect["ts"] * 1000.0 - pub_ms)
        if collect is not None and submit is not None:
            legs["batch"].append((submit["ts"] - collect["ts"]) * 1000.0)
        if device is not None and device.get("dur_ms") is not None:
            legs["device"].append(float(device["dur_ms"]))
        if device is not None and emit is not None:
            legs["emit"].append((emit["ts"] - device["ts"]) * 1000.0)
        if pub_ms is not None and emit is not None:
            legs["total"].append(emit["ts"] * 1000.0 - pub_ms)
    out = {leg: _leg_stats(vals) for leg, vals in legs.items()}
    out["drops"] = {"count": dropped_total,
                    "by_reason": dict(sorted(drops.items()))}
    return out


def to_chrome_trace(events: Iterable[dict], pid: int = 1,
                    process_name: str = "video-edge-ai-proxy-tpu") -> dict:
    """Convert span events to Chrome trace-event JSON (the object; dump
    with ``json.dump``). One trace thread per stream; spans with dur_ms
    become complete events (ph "X", ts = span start), the rest instants
    (ph "i"). Loadable in chrome://tracing and Perfetto.

    ``pid``/``process_name`` namespace the host track — the multi-engine
    fleet merge (tools/obs_export.py) gives each member its own pid so N
    engines share one timeline without track collisions.
    """
    events = list(events)
    tids: Dict[str, int] = {}
    trace: List[dict] = []
    for ev in events:
        stream = str(ev.get("stream", "?"))
        if stream not in tids:
            tids[stream] = len(tids) + 1
            trace.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tids[stream], "args": {"name": f"stream {stream}"},
            })
    trace.insert(0, {
        "ph": "M", "name": "process_name", "pid": pid,
        "args": {"name": process_name},
    })
    for ev in events:
        stream = str(ev.get("stream", "?"))
        args = {k: v for k, v in ev.items()
                if k not in ("stream", "stage", "ts", "dur_ms")}
        dur_ms = ev.get("dur_ms")
        end_us = ev["ts"] * 1e6
        base = {
            "name": ev.get("stage", "?"),
            "cat": "frame",
            "pid": pid,
            "tid": tids[stream],
            "args": args,
        }
        if dur_ms is not None:
            dur_us = float(dur_ms) * 1000.0
            base.update(ph="X", ts=round(end_us - dur_us, 3),
                        dur=round(dur_us, 3))
        else:
            base.update(ph="i", ts=round(end_us, 3), s="t")
        trace.append(base)
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def validate_chrome_trace(obj) -> List[str]:
    """Schema-check a Chrome trace-event JSON object. Returns problems
    (empty = loadable). Used by ``tools/obs_export.py --check`` and
    ``make obs-smoke``."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"top level must be an object, got {type(obj).__name__}"]
    evs = obj.get("traceEvents")
    if not isinstance(evs, list):
        return ["missing traceEvents array"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            problems.append(f"event {i}: missing ph")
            continue
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                problems.append(f"event {i}: ph={ph} missing numeric ts")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append(f"event {i}: complete event missing dur")
        if "name" not in ev:
            problems.append(f"event {i}: missing name")
        if ph != "M" and not isinstance(ev.get("pid"), int):
            problems.append(f"event {i}: missing integer pid")
    return problems
