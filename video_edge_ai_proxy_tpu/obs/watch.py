"""Stall/watermark detection: threshold crossings logged once per episode.

The reference proxy's failure modes are silent-by-default: a camera wedges
and the bus just serves the last frame forever; the drain queue backs up
and latency climbs with no log line; a shape churn recompiles every tick.
The watchdog turns each into ONE warning when the threshold is crossed and
ONE info when it recovers — hysteresis by episode, so a value oscillating
around the threshold can't log-spam (the classic alert-flapping problem).

Usage: call ``check`` from an existing periodic path (the engine tick) —
the watchdog owns no thread. Each named condition is an episode state
machine; ``snapshot()`` exports active episodes + totals for
``/api/v1/stats`` and the soak artifact.

Pure Python, jax-free.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

log = logging.getLogger("vep.obs.watch")


class Watchdog:
    """Once-per-episode threshold monitor.

    ``check(name, value, above=x)`` opens an episode (and logs WARNING)
    the first time ``value > x``; subsequent breaching checks are silent;
    the first non-breaching check closes the episode (and logs INFO with
    the episode duration and peak). ``below=`` watches the other
    direction (e.g. batch occupancy collapsing).

    ``journal`` (r23, optional ``obs.journal.DecisionJournal``): every
    episode open/close is recorded as a decision event whose trigger
    carries the excursion magnitude (value/threshold on open, peak and
    duration on close) so downstream cause links explain HOW BAD the
    crossing was, not just that it happened.
    """

    def __init__(self, *, journal=None):
        self._lock = threading.Lock()
        self.journal = journal
        # name -> {since, peak, threshold, direction, detail, seq}
        self._active: Dict[str, dict] = {}
        self._episodes: Dict[str, int] = {}
        # name -> most recent COMPLETED episode (open ts + peak survive
        # the close — r23 satellite: totals alone cannot tell a journal
        # event the excursion magnitude).
        self._last: Dict[str, dict] = {}

    def check(self, name: str, value: float, *,
              above: Optional[float] = None,
              below: Optional[float] = None,
              detail: str = "") -> bool:
        """Evaluate one condition; returns True while breaching."""
        if (above is None) == (below is None):
            raise ValueError("exactly one of above=/below= required")
        breach = value > above if above is not None else value < below
        threshold = above if above is not None else below
        direction = "above" if above is not None else "below"
        now = time.time()
        opened = closed = None
        with self._lock:
            ep = self._active.get(name)
            if breach:
                if ep is None:
                    ep = {
                        "since": now,
                        "peak": value,
                        "threshold": threshold,
                        "direction": direction,
                        "detail": detail,
                        "seq": None,
                    }
                    self._active[name] = ep
                    self._episodes[name] = self._episodes.get(name, 0) + 1
                    opened = ep
                else:
                    if above is not None:
                        ep["peak"] = max(ep["peak"], value)
                    else:
                        ep["peak"] = min(ep["peak"], value)
            elif ep is not None:
                del self._active[name]
                self._last[name] = {
                    "opened": ep["since"],
                    "closed": now,
                    "duration_s": round(now - ep["since"], 3),
                    "peak": ep["peak"],
                    "threshold": ep["threshold"],
                    "direction": ep["direction"],
                }
                closed = ep
        # Journal + log OUTSIDE the lock (the journal has its own).
        if opened is not None:
            seq = None
            if self.journal is not None:
                seq = self.journal.record(
                    "watch", "episode_open", subject=("watch", name),
                    trigger={"value": float(value),
                             "threshold": float(threshold),
                             "direction": direction})
                opened["seq"] = seq
            log.warning(
                "watch: %s %s threshold %g (value %g)%s",
                name, direction, threshold, value,
                f" — {detail}" if detail else "",
                extra={"vep_actor": "watch",
                       "vep_subject": f"watch:{name}",
                       "vep_journal_seq": seq},
            )
        elif closed is not None:
            seq = None
            if self.journal is not None:
                seq = self.journal.record(
                    "watch", "episode_close", subject=("watch", name),
                    trigger={"peak": float(closed["peak"]),
                             "threshold": float(closed["threshold"]),
                             "duration_s": round(now - closed["since"], 3)},
                    cause=closed.get("seq"))
            log.info(
                "watch: %s recovered after %.1fs (peak %g, "
                "threshold %g)",
                name, now - closed["since"], closed["peak"],
                closed["threshold"],
                extra={"vep_actor": "watch",
                       "vep_subject": f"watch:{name}",
                       "vep_journal_seq": seq},
            )
        return breach

    def active(self) -> Dict[str, dict]:
        with self._lock:
            return {
                k: {**v, "age_s": round(time.time() - v["since"], 1)}
                for k, v in self._active.items()
            }

    def snapshot(self) -> dict:
        """JSON-able state for ``/api/v1/stats`` and soak artifacts."""
        with self._lock:
            active = {
                k: {
                    "since": v["since"],
                    "age_s": round(time.time() - v["since"], 1),
                    "peak": v["peak"],
                    "threshold": v["threshold"],
                    "direction": v["direction"],
                    "detail": v["detail"],
                    "seq": v.get("seq"),
                }
                for k, v in self._active.items()
            }
            return {"active": active, "episodes": dict(self._episodes),
                    "last": {k: dict(v) for k, v in self._last.items()}}

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._episodes.clear()
            self._last.clear()
