"""Stall/watermark detection: threshold crossings logged once per episode.

The reference proxy's failure modes are silent-by-default: a camera wedges
and the bus just serves the last frame forever; the drain queue backs up
and latency climbs with no log line; a shape churn recompiles every tick.
The watchdog turns each into ONE warning when the threshold is crossed and
ONE info when it recovers — hysteresis by episode, so a value oscillating
around the threshold can't log-spam (the classic alert-flapping problem).

Usage: call ``check`` from an existing periodic path (the engine tick) —
the watchdog owns no thread. Each named condition is an episode state
machine; ``snapshot()`` exports active episodes + totals for
``/api/v1/stats`` and the soak artifact.

Pure Python, jax-free.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

log = logging.getLogger("vep.obs.watch")


class Watchdog:
    """Once-per-episode threshold monitor.

    ``check(name, value, above=x)`` opens an episode (and logs WARNING)
    the first time ``value > x``; subsequent breaching checks are silent;
    the first non-breaching check closes the episode (and logs INFO with
    the episode duration and peak). ``below=`` watches the other
    direction (e.g. batch occupancy collapsing).
    """

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {since, peak, threshold, direction, detail}
        self._active: Dict[str, dict] = {}
        self._episodes: Dict[str, int] = {}

    def check(self, name: str, value: float, *,
              above: Optional[float] = None,
              below: Optional[float] = None,
              detail: str = "") -> bool:
        """Evaluate one condition; returns True while breaching."""
        if (above is None) == (below is None):
            raise ValueError("exactly one of above=/below= required")
        breach = value > above if above is not None else value < below
        threshold = above if above is not None else below
        now = time.time()
        with self._lock:
            ep = self._active.get(name)
            if breach:
                if ep is None:
                    self._active[name] = {
                        "since": now,
                        "peak": value,
                        "threshold": threshold,
                        "direction": "above" if above is not None
                        else "below",
                        "detail": detail,
                    }
                    self._episodes[name] = self._episodes.get(name, 0) + 1
                    log.warning(
                        "watch: %s %s threshold %g (value %g)%s",
                        name,
                        "above" if above is not None else "below",
                        threshold, value,
                        f" — {detail}" if detail else "",
                    )
                else:
                    if above is not None:
                        ep["peak"] = max(ep["peak"], value)
                    else:
                        ep["peak"] = min(ep["peak"], value)
            elif ep is not None:
                del self._active[name]
                log.info(
                    "watch: %s recovered after %.1fs (peak %g, "
                    "threshold %g)",
                    name, now - ep["since"], ep["peak"], ep["threshold"],
                )
        return breach

    def active(self) -> Dict[str, dict]:
        with self._lock:
            return {
                k: {**v, "age_s": round(time.time() - v["since"], 1)}
                for k, v in self._active.items()
            }

    def snapshot(self) -> dict:
        """JSON-able state for ``/api/v1/stats`` and soak artifacts."""
        with self._lock:
            active = {
                k: {
                    "since": v["since"],
                    "age_s": round(time.time() - v["since"], 1),
                    "peak": v["peak"],
                    "threshold": v["threshold"],
                    "direction": v["direction"],
                    "detail": v["detail"],
                }
                for k, v in self._active.items()
            }
            return {"active": active, "episodes": dict(self._episodes)}

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._episodes.clear()
