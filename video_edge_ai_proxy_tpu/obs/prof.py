"""Triggered device profiling: bounded jax.profiler captures as bundles.

The r7 lineage spans and r9 MFU/SLO attribution say *that* a step is slow;
only a device trace says *where*. The reference proxy has no profiler at
all (SURVEY.md §5.1), and until r10 ours was two raw hooks
(``EngineRunner.start_profile/stop_profile``) that an operator had to
drive by hand and that produced a bare log dir with no context. This
module is the single capture path behind three surfaces:

- **On-demand**: ``POST /api/v1/profile?ms=N`` (serve/rest_api.py) and the
  gRPC admin mirror (serve/server.py) call :meth:`Profiler.capture` — a
  duration-bounded ``jax.profiler`` trace written into a self-contained
  artifact *bundle*: device trace + the lineage-span window that
  overlapped the capture + a perf/SLO/health snapshot + manifest.json
  linking them.
- **Trigger-driven**: the engine polls :meth:`Profiler.poll` off its tick
  (engine/runner.py ``_watch_tick``) with the SLO episode total and the
  degradation-ladder rung; when an episode opens or the ladder escalates,
  ONE rate-limited capture fires per episode (the obs/watch.py
  once-per-episode discipline) so excursions are profiled in the act
  during chaos soaks — "profile the excursion, not the average".
- **Retention ring**: bundles live under one directory bounded by
  ``retention_bytes``; oldest bundles are evicted first (the
  resilience/spool.py bounding idiom) so weeks of triggers can never fill
  a disk.

Design notes:

- **jax inside functions.** The module is importable from the control
  plane without initializing a backend (CLAUDE.md); only the default
  ``device_tracer`` touches ``jax.profiler``.
- **Injectable everything.** ``clock``/``wall_clock``/``sleep`` and the
  ``device_tracer`` callable are constructor parameters so the trigger
  discipline, rate limit and retention ring are tested under fake clocks
  with a stub tracer (tests/test_prof.py), never by sleeping through a
  real capture.
- **One capture at a time.** Bounded captures, triggered captures and the
  legacy unbounded ``start``/``stop`` pair share one busy flag — a second
  caller gets ``RuntimeError`` (REST maps it to 409), because
  ``jax.profiler`` keeps process-global state and a second ``start_trace``
  wedges it.
- **Idle cost is a poll.** With no capture active the engine-side work is
  one ``poll()`` per watch tick: a few compares under a lock. The bench
  perf-gate covers the claim (BASELINE.md "Profiling" section).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from typing import Callable, List, Optional

from . import metrics

log = logging.getLogger("vep.obs.prof")

__all__ = ["Profiler", "find_device_trace"]

# File names inside every bundle directory.
MANIFEST = "manifest.json"
SPANS = "spans.json"
SNAPSHOT = "snapshot.json"
JOURNAL = "journal.json"
DEVICE_DIR = "device"

# Span-window slack: spans stamped up to this long after stop_trace still
# belong to the capture (the drain thread emits a batch's spans slightly
# after the device work the trace saw).
_SPAN_SLACK_S = 0.25


def _jax_device_tracer(log_dir: str, ms: int, sleep: Callable) -> None:
    """The real bounded capture: start a jax.profiler trace (with the
    Perfetto-compatible JSON artifact), hold it open for ``ms``, stop.
    jax is imported here, not at module scope (CLAUDE.md)."""
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_trace=True)
    try:
        sleep(ms / 1000.0)
    finally:
        # stop_trace flushes to disk and can raise; the caller clears its
        # busy flag regardless (same hazard the old runner hooks noted:
        # a wedged flag blocks every future capture until restart).
        jax.profiler.stop_trace()


def find_device_trace(bundle_dir: str) -> Optional[str]:
    """Locate the Perfetto/Chrome JSON the profiler wrote under a bundle
    (``device/plugins/profile/<run>/perfetto_trace.json.gz`` in current
    jax; fall back to any ``*.trace.json[.gz]``). Returns a path relative
    to ``bundle_dir``, or None."""
    root = os.path.join(bundle_dir, DEVICE_DIR)
    best: Optional[str] = None
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if name.endswith("perfetto_trace.json.gz"):
                return os.path.relpath(os.path.join(dirpath, name),
                                       bundle_dir)
            if name.endswith((".trace.json.gz", ".trace.json")):
                best = best or os.path.relpath(
                    os.path.join(dirpath, name), bundle_dir)
    return best


class Profiler:
    """Bounded jax.profiler captures into a byte-bounded bundle ring."""

    def __init__(
        self,
        directory: str,
        *,
        retention_bytes: int = 256 << 20,
        trigger: bool = True,
        trigger_ms: int = 500,
        trigger_min_interval_s: float = 60.0,
        max_ms: int = 10_000,
        keep_manifests: int = 64,
        clock=time.monotonic,
        wall_clock=time.time,
        sleep=time.sleep,
        device_tracer: Optional[Callable[[str, int], None]] = None,
        tracer=None,
        journal=None,
        snapshot_fn: Optional[Callable[[], dict]] = None,
        registry: Optional[metrics.Registry] = None,
        async_triggers: bool = True,
    ):
        reg = registry if registry is not None else metrics.registry
        self.directory = directory
        self.retention_bytes = int(retention_bytes)
        self.trigger_enabled = bool(trigger)
        self.trigger_ms = int(trigger_ms)
        self.trigger_min_interval_s = float(trigger_min_interval_s)
        self.max_ms = int(max_ms)
        self._keep_manifests = int(keep_manifests)
        self._clock = clock
        self._wall = wall_clock
        self._sleep = sleep
        self._device_tracer = device_tracer
        self._tracer = tracer
        # Decision journal (obs/journal.py, r23): events whose wall time
        # overlapped the capture land in the bundle as journal.json —
        # the WHY half next to the lineage spans' WHERE.
        self._journal = journal
        self._snapshot_fn = snapshot_fn
        self._async_triggers = bool(async_triggers)

        self._lock = threading.Lock()
        self._busy: Optional[str] = None     # None | "capture" | "manual"
        self._seq = 0
        self._captures: List[dict] = []      # recent manifests, bounded
        self._last_trigger_t: Optional[float] = None
        self._seen_episodes = 0
        self._seen_rung = 0
        self._trigger_thread: Optional[threading.Thread] = None
        self.errors = 0

        self._m_captures = reg.counter(
            "vep_prof_captures_total",
            "Completed profile captures by trigger source", ("trigger",))
        self._m_capture_ms = reg.histogram(
            "vep_prof_capture_wall_ms",
            "Capture wall time including trace flush")
        self._m_retained = reg.gauge(
            "vep_prof_retained_bytes",
            "Bytes currently held by the bundle retention ring")
        self._m_evicted = reg.counter(
            "vep_prof_evicted_total",
            "Bundles evicted by the retention byte bound")
        self._m_suppressed = reg.counter(
            "vep_prof_suppressed_total",
            "Trigger captures suppressed (rate limit / capture in flight)",
            ("reason",))
        self._m_errors = reg.counter(
            "vep_prof_errors_total", "Failed capture attempts")
        # Expose the unlabeled counters at 0 from boot: "no evictions
        # yet" must be scrapeable, not indistinguishable from "no
        # profiler" (families without children do not render).
        self._m_evicted.inc(0)
        self._m_errors.inc(0)

        os.makedirs(directory, exist_ok=True)
        existing = self._bundles()
        if existing:
            tail = os.path.basename(existing[-1]).split("_", 1)[0]
            if tail.isdigit():
                self._seq = int(tail) + 1
        self._m_retained.set(self._retained_bytes())

    # -- bundle ring ------------------------------------------------------

    def _bundles(self) -> List[str]:
        """Bundle dirs oldest-first (seq-prefixed names sort by age)."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if os.path.isdir(os.path.join(self.directory, n)))
        except FileNotFoundError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    @staticmethod
    def _dir_bytes(path: str) -> int:
        total = 0
        for dirpath, _dirnames, filenames in os.walk(path):
            for name in filenames:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return total

    def _retained_bytes(self) -> int:
        return sum(self._dir_bytes(p) for p in self._bundles())

    def _enforce_retention(self) -> None:
        """Evict oldest bundles until the ring fits ``retention_bytes``.
        The newest bundle is evicted too if it alone exceeds the bound —
        the bound is a promise to the disk, not to the bundle."""
        bundles = self._bundles()
        sizes = {p: self._dir_bytes(p) for p in bundles}
        total = sum(sizes.values())
        while bundles and total > self.retention_bytes:
            victim = bundles.pop(0)
            shutil.rmtree(victim, ignore_errors=True)
            total -= sizes.get(victim, 0)
            self._m_evicted.inc()
            log.warning("prof retention ring over %d bytes; evicted %s",
                        self.retention_bytes, os.path.basename(victim))
        self._m_retained.set(max(total, 0))

    # -- capture ----------------------------------------------------------

    def _acquire(self, mode: str) -> None:
        with self._lock:
            if self._busy is not None:
                raise RuntimeError(
                    f"profiler already running ({self._busy})")
            self._busy = mode

    def _release(self) -> None:
        with self._lock:
            self._busy = None

    def capture(self, ms: int, *, trigger: str = "manual",
                context: Optional[dict] = None) -> dict:
        """One duration-bounded capture; returns the bundle manifest.

        Raises ``ValueError`` on a bad duration (REST maps it to 400) and
        ``RuntimeError`` when a capture or a legacy manual trace is
        already in flight (REST maps it to 409).
        """
        ms = int(ms)
        if ms <= 0 or ms > self.max_ms:
            raise ValueError(
                f"capture duration must be in (0, {self.max_ms}] ms, "
                f"got {ms}")
        self._acquire("capture")
        try:
            return self._capture_locked(ms, trigger, context or {})
        finally:
            self._release()

    def _capture_locked(self, ms: int, trigger: str, context: dict) -> dict:
        with self._lock:
            seq = self._seq
            self._seq += 1
        name = f"{seq:08d}_{trigger}"
        bundle = os.path.join(self.directory, name)
        device_dir = os.path.join(bundle, DEVICE_DIR)
        os.makedirs(device_dir, exist_ok=True)
        t0_wall = self._wall()
        t0 = self._clock()
        error: Optional[str] = None
        try:
            tracer_fn = self._device_tracer
            if tracer_fn is not None:
                tracer_fn(device_dir, ms)
            else:
                _jax_device_tracer(device_dir, ms, self._sleep)
        except Exception as exc:  # capture must never kill the caller
            error = f"{type(exc).__name__}: {exc}"
            self.errors += 1
            self._m_errors.inc()
            log.error("device capture failed: %s", error)
        wall_ms = (self._clock() - t0) * 1000.0
        t1_wall = self._wall()

        # Concurrent lineage-span window: every sampled span whose end
        # timestamp falls inside the capture (plus drain slack) — the
        # host-side half of the merged timeline (tools/obs_export.py
        # --merge).
        span_events: List[dict] = []
        if self._tracer is not None:
            span_events = [
                ev for ev in self._tracer.events()
                if t0_wall <= ev.get("ts", 0.0) <= t1_wall + _SPAN_SLACK_S
            ]
        with open(os.path.join(bundle, SPANS), "w") as f:
            json.dump({"events": span_events}, f)

        # Overlapping decision-journal window (same slack as the spans:
        # a decision journaled just after stop_trace still explains the
        # capture's tail).
        journal_events: List[dict] = []
        if self._journal is not None:
            try:
                journal_events = self._journal.window(
                    t0_wall, t1_wall + _SPAN_SLACK_S)
            except Exception as exc:  # noqa: BLE001 — bundle best-effort
                log.error("prof journal window failed: %s", exc)
        with open(os.path.join(bundle, JOURNAL), "w") as f:
            json.dump({"events": journal_events}, f)

        snap: dict = {}
        if self._snapshot_fn is not None:
            try:
                snap = self._snapshot_fn() or {}
            except Exception as exc:
                log.error("prof snapshot_fn failed: %s", exc)
        with open(os.path.join(bundle, SNAPSHOT), "w") as f:
            json.dump(snap, f, default=str)

        manifest = {
            "bundle": name,
            "path": bundle,
            "trigger": trigger,
            "ms": ms,
            "wall_ms": round(wall_ms, 1),
            "t_start": t0_wall,
            "t_end": t1_wall,
            "device_trace": find_device_trace(bundle),
            "spans": SPANS,
            "span_events": len(span_events),
            "journal": JOURNAL,
            "journal_events": len(journal_events),
            "snapshot": SNAPSHOT,
            "slo_episode": context.get("slo_episode"),
            "context": context,
            "error": error,
        }
        with open(os.path.join(bundle, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
            f.write("\n")
        with self._lock:
            self._captures.append(manifest)
            del self._captures[:-self._keep_manifests]
        self._m_captures.labels(trigger).inc()
        self._m_capture_ms.labels().observe(wall_ms)
        self._enforce_retention()
        log.info("profile capture %s (%s, %d ms) -> %s",
                 name, trigger, ms, bundle)
        return manifest

    # -- trigger discipline ------------------------------------------------

    def poll(self, *, episodes: int = 0, rung: int = 0,
             context: Optional[dict] = None) -> Optional[str]:
        """Engine-tick trigger check. ``episodes`` is the cumulative SLO
        episode total; ``rung`` the current ladder rung index. Fires at
        most one capture per new episode / per escalation, rate-limited
        to one per ``trigger_min_interval_s``. Returns the reason fired,
        else None. Cheap when idle: compares under a lock."""
        with self._lock:
            reason = None
            if episodes > self._seen_episodes:
                reason = "slo_episode"
            if rung > self._seen_rung:
                reason = reason or "ladder_escalation"
            # Watermarks advance even when suppressed: once-per-episode
            # means an episode gets at most one SHOT at a capture, not a
            # retry queue that fires stale captures after the excursion.
            self._seen_episodes = max(self._seen_episodes, int(episodes))
            self._seen_rung = int(rung)
            if reason is None:
                return None
            if not self.trigger_enabled:
                return None
            now = self._clock()
            if (self._last_trigger_t is not None
                    and now - self._last_trigger_t
                    < self.trigger_min_interval_s):
                self._m_suppressed.labels("rate_limit").inc()
                return None
            if self._busy is not None:
                self._m_suppressed.labels("busy").inc()
                return None
            self._last_trigger_t = now
        ctx = dict(context or {})
        ctx.setdefault("reason", reason)
        if self._async_triggers:
            # The capture sleeps trigger_ms: never on the engine tick
            # thread. One thread at most (the busy flag rejects overlap).
            t = threading.Thread(
                target=self._trigger_capture, args=(reason, ctx),
                name="prof-trigger", daemon=True)
            self._trigger_thread = t
            t.start()
        else:
            self._trigger_capture(reason, ctx)
        return reason

    def _trigger_capture(self, reason: str, context: dict) -> None:
        try:
            self.capture(self.trigger_ms, trigger=reason, context=context)
        except (RuntimeError, ValueError) as exc:
            self._m_suppressed.labels("busy").inc()
            log.info("trigger capture skipped: %s", exc)

    def join_trigger(self, timeout: float = 30.0) -> None:
        """Wait for an in-flight trigger capture (soak/e2e teardown)."""
        t = self._trigger_thread
        if t is not None:
            t.join(timeout)

    # -- legacy unbounded path --------------------------------------------

    def start(self, log_dir: str) -> None:
        """Unbounded manual trace (legacy ``EngineRunner.start_profile``
        surface). Shares the busy flag with bounded captures — exactly
        one capture path process-wide."""
        import jax

        self._acquire("manual")
        try:
            jax.profiler.start_trace(log_dir, create_perfetto_trace=True)
        except Exception:
            self._release()
            raise
        log.info("profiler tracing to %s", log_dir)

    def stop(self) -> None:
        """Stop the manual trace started by :meth:`start`."""
        import jax

        with self._lock:
            if self._busy != "manual":
                raise RuntimeError("profiler not running")
            # Clear the flag before stop_trace: it flushes to disk and
            # can raise, and a stuck flag wedges every future capture.
            self._busy = None
        jax.profiler.stop_trace()
        log.info("profiler trace stopped")

    # -- snapshots --------------------------------------------------------

    def captures(self) -> List[dict]:
        with self._lock:
            return list(self._captures)

    def snapshot(self) -> dict:
        """JSON-able state for /api/v1/stats and soak artifacts."""
        with self._lock:
            captures = list(self._captures)
            busy = self._busy
        return {
            "dir": self.directory,
            "busy": busy,
            "trigger_enabled": self.trigger_enabled,
            "trigger_ms": self.trigger_ms,
            "trigger_min_interval_s": self.trigger_min_interval_s,
            "retention_bytes": self.retention_bytes,
            "retained_bytes": self._retained_bytes(),
            "bundles": len(self._bundles()),
            "errors": self.errors,
            "captures": captures,
        }
