"""Process-wide metrics registry: counters, gauges, log2 histograms.

Replaces the ad-hoc per-subsystem stat math (the engine's ``StreamStats``
EMA, ``rest_api.py``'s hand-rolled exposition): every subsystem registers
families here and ``/metrics`` + ``/api/v1/stats`` render ONE registry.
Design constraints (MOSAIC / arxiv 2305.03222: per-stream contention must
be visible; Jetson e2e benchmark / arxiv 2307.16834: only stage-segmented
latency explains edge video time):

- **Low overhead.** One lock acquire + int add per observation; histogram
  bucketing is ``math.frexp`` (no log, no sample storage). Hot-path call
  sites hold a child handle — no per-observation name lookup.
- **Fixed-bucket log2 histograms.** Boundaries at powers of two from
  2^-4 ms to 2^14 ms: p50/p90/p99 derivable from 20 ints without storing
  samples, so a per-stream latency histogram costs ~200 B forever.
- **Prometheus text 0.0.4** rendering with contiguous families, HELP/TYPE
  lines and label escaping (``lint_exposition`` checks all of it; the
  exposition test and ``make obs-smoke`` both run the linter).

jax-free by design: ingest workers and the control plane import this
without initializing a backend.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# Histogram bucket boundaries: le = 2**k for k in [LOG2_LO, LOG2_HI],
# plus +Inf. With ms units that spans 62.5 us .. 16.4 s — the whole
# plausible range of per-stage edge video latencies.
LOG2_LO = -4
LOG2_HI = 14
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    float(2.0 ** k) for k in range(LOG2_LO, LOG2_HI + 1)
)
N_BUCKETS = len(BUCKET_BOUNDS) + 1  # + overflow (+Inf)


def bucket_index(value: float) -> int:
    """Index of the smallest bucket with ``value <= le`` (log2 buckets).
    <= 0 maps to bucket 0 (counted, not dropped: a 0.0 ms latency is a
    legitimate observation — see the EMA-sentinel bug this replaces)."""
    if value <= BUCKET_BOUNDS[0]:
        return 0
    if value > BUCKET_BOUNDS[-1]:
        return N_BUCKETS - 1
    # frexp: value = m * 2**e with 0.5 <= m < 1, so 2**(e-1) <= value < 2**e
    m, e = math.frexp(value)
    k = e if m > 0.5 else e - 1   # smallest k with value <= 2**k
    return k - LOG2_LO


class Counter:
    """Monotonic float counter."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def set(self, v: float) -> None:
        """Scrape-time mirror of an externally-owned monotonic total (e.g.
        the annotation queue's ack count): the owner counts, the registry
        renders. Not for hot-path use — call inc() there."""
        self._v = float(v)

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    """Last-write-wins float gauge."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Fixed log2-bucket histogram; percentiles derived, samples never
    stored."""

    __slots__ = ("_lock", "_counts", "_sum", "_count")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * N_BUCKETS
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        idx = bucket_index(value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> Optional[float]:
        """Approximate quantile (0 < p <= 100): linear interpolation inside
        the bucket holding the rank, like ``histogram_quantile``. None when
        empty; overflow-bucket ranks clamp to the largest finite bound."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return None
        rank = p / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lo_cum = cum
            cum += c
            if cum >= rank:
                if i >= len(BUCKET_BOUNDS):
                    return BUCKET_BOUNDS[-1]
                hi = BUCKET_BOUNDS[i]
                lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                frac = (rank - lo_cum) / c
                return lo + (hi - lo) * frac
        return BUCKET_BOUNDS[-1]

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        out = {
            "count": total,
            "sum": round(s, 3),
            "avg": round(s / total, 3) if total else None,
        }
        for p in (50, 90, 99):
            q = self.percentile(p)
            out[f"p{p}"] = round(q, 3) if q is not None else None
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric family: kind + help + labelnames + children."""

    def __init__(self, name: str, kind: str, help_text: str,
                 labelnames: Tuple[str, ...]):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.labelnames = labelnames
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values: str, **kw: str):
        """Child for one label-value combination (created on first use).
        No labelnames -> the singleton child."""
        if kw:
            values = tuple(str(kw[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values!r}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = _KINDS[self.kind]()
                self._children[values] = child
            return child

    # Unlabeled conveniences so `registry.counter("x", "...").inc()` works.
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def dec(self, n: float = 1.0) -> None:
        self.labels().dec(n)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    @property
    def value(self) -> float:
        return self.labels().value

    def clear(self) -> None:
        """Drop every child — for families repopulated per scrape (e.g.
        per-worker gauges, where a removed camera must stop exporting)."""
        with self._lock:
            self._children.clear()

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class Registry:
    """Ordered collection of families; one per process by default."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}
        # Constant labels (e.g. instance="engine-0") applied to every
        # sample at RENDER time — not stored per-child, so hot-path
        # observation cost is unchanged and the label set can be
        # (re)configured after families exist. Fleet aggregation keys
        # member identity on these.
        self._const: Tuple[Tuple[str, str], ...] = ()

    def _family(self, name: str, kind: str, help_text: str,
                labelnames: Iterable[str]) -> Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, kind, help_text, labelnames)
                self._families[name] = fam
            elif fam.kind != kind or fam.labelnames != labelnames:
                raise ValueError(
                    f"metric {name!r} re-registered as {kind}{labelnames} "
                    f"(was {fam.kind}{fam.labelnames})"
                )
            return fam

    def counter(self, name: str, help_text: str = "",
                labelnames: Iterable[str] = ()) -> Family:
        return self._family(name, "counter", help_text, labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: Iterable[str] = ()) -> Family:
        return self._family(name, "gauge", help_text, labelnames)

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Iterable[str] = ()) -> Family:
        return self._family(name, "histogram", help_text, labelnames)

    def families(self) -> List[Family]:
        with self._lock:
            return list(self._families.values())

    # -- rendering --

    def set_const_labels(self, **labels: str) -> None:
        """Set the render-time constant label set (replacing any previous
        one). ``instance`` is the conventional member-identity key; a
        family that already carries one of these names keeps its own
        (the per-sample label wins, the const one is skipped)."""
        self._const = tuple(sorted(
            (str(k), str(v)) for k, v in labels.items()))

    @property
    def const_labels(self) -> Dict[str, str]:
        return dict(self._const)

    @staticmethod
    def _esc(v: str) -> str:
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def _labelstr(self, names: Tuple[str, ...], values: Tuple[str, ...],
                  extra: str = "") -> str:
        pairs = [f'{n}="{self._esc(v)}"' for n, v in self._const
                 if n not in names]
        pairs += [f'{n}="{self._esc(v)}"' for n, v in zip(names, values)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def render(self) -> str:
        """Prometheus text exposition 0.0.4: contiguous families, HELP and
        TYPE per family, histograms as cumulative _bucket/_sum/_count."""
        lines: List[str] = []
        for fam in self.families():
            children = fam.children()
            if not children:
                continue
            help_text = fam.help.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {fam.name} {help_text}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in children:
                if fam.kind == "histogram":
                    cum = 0
                    with child._lock:
                        counts = list(child._counts)
                        total = child._count
                        s = child._sum
                    for i, bound in enumerate(BUCKET_BOUNDS):
                        cum += counts[i]
                        ls = self._labelstr(
                            fam.labelnames, values, f'le="{bound:g}"')
                        lines.append(f"{fam.name}_bucket{ls} {cum}")
                    ls = self._labelstr(fam.labelnames, values, 'le="+Inf"')
                    lines.append(f"{fam.name}_bucket{ls} {total}")
                    ls = self._labelstr(fam.labelnames, values)
                    lines.append(f"{fam.name}_sum{ls} {s:g}")
                    lines.append(f"{fam.name}_count{ls} {total}")
                else:
                    ls = self._labelstr(fam.labelnames, values)
                    lines.append(f"{fam.name}{ls} {child.value:g}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able view of every family (``/api/v1/stats`` and the
        soak/bench artifact "obs" sections)."""
        out: dict = {}
        for fam in self.families():
            children = fam.children()
            if not children:
                continue
            samples = []
            for values, child in children:
                labels = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    samples.append({"labels": labels, **child.snapshot()})
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[fam.name] = {"kind": fam.kind, "samples": samples}
        return out

    def reset(self) -> None:
        """Drop every family (tests)."""
        with self._lock:
            self._families.clear()


# THE process-wide registry. Subsystems register families at import and
# hold child handles at hot-path call sites.
registry = Registry()


def lint_exposition(text: str) -> List[str]:
    """Validate Prometheus text-format 0.0.4 structure. Returns a list of
    problems (empty = clean). Checks: every sample belongs to an announced
    family, HELP/TYPE precede samples, families are contiguous (no family
    re-opened later), no duplicate (name, labels) samples, label values
    quoted with only valid escapes."""
    problems: List[str] = []
    seen_families: List[str] = []
    closed: set = set()
    current: Optional[str] = None
    current_kind = ""
    seen_samples: set = set()
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                problems.append(f"line {ln}: malformed comment {line!r}")
                continue
            name = parts[2]
            if name != current:
                if name in closed:
                    problems.append(
                        f"line {ln}: family {name} re-opened (samples must "
                        "be contiguous per family)")
                if current is not None:
                    closed.add(current)
                current = name
                seen_families.append(name)
            if line.startswith("# TYPE "):
                if len(parts) < 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped"):
                    problems.append(f"line {ln}: bad TYPE {line!r}")
                else:
                    current_kind = parts[3]
            continue
        if line.startswith("#"):
            continue
        # sample line: name[{labels}] value
        brace = line.find("{")
        if brace >= 0:
            name = line[:brace]
            close = line.rfind("}")
            if close < 0:
                problems.append(f"line {ln}: unterminated label set")
                continue
            labels = line[brace + 1:close]
            rest = line[close + 1:].strip()
            # validate label tokens: name="value" with escaped quotes
            import re

            token = re.compile(
                r'\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*(,|$)')
            pos = 0
            while pos < len(labels):
                m = token.match(labels, pos)
                if m is None:
                    problems.append(
                        f"line {ln}: bad label syntax near {labels[pos:]!r}")
                    break
                pos = m.end()
        else:
            name, _, rest = line.partition(" ")
            labels = ""
            rest = rest.strip()
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if current_kind in ("histogram", "summary") and \
                    name.endswith(suffix) and \
                    name[: -len(suffix)] == current:
                base = name[: -len(suffix)]
                break
        if base != current:
            problems.append(
                f"line {ln}: sample {name} outside its family block "
                f"(current family: {current})")
        try:
            float(rest.split()[0])
        except (ValueError, IndexError):
            problems.append(f"line {ln}: non-numeric value {rest!r}")
        key = (name, labels)
        if key in seen_samples:
            problems.append(f"line {ln}: duplicate sample {name}{{{labels}}}")
        seen_samples.add(key)
    return problems
