"""Observability plane: unified metrics registry, frame-lineage tracing,
stall/watermark detection (ISSUE r7 tentpole), live device-performance
attribution and SLO burn-rate evaluation (ISSUE r9 tentpole).

Pure-Python, jax-free at import, importable from control-plane and worker
code alike. Modules:

- :mod:`metrics` — process-wide counters/gauges/log2-histograms, rendered
  once by ``/metrics`` (Prometheus 0.0.4) and ``/api/v1/stats`` (JSON).
- :mod:`spans` — sampled per-frame lineage span events (ingest -> bus ->
  batch -> device -> emit), per-stream ring buffers, Chrome trace-event
  export (``tools/obs_export.py``) and ``/api/v1/trace``.
- :mod:`watch` — threshold-crossing detection (drain backpressure, batch
  occupancy, recompilation storms, frame drops) logged once per episode.
- :mod:`perf` — XLA compile cost + wall-time per (model, geometry,
  bucket), per-batch device time, padded-slot waste, live MFU /
  aggregate-fps gauges (``vep_perf_*`` / ``vep_compile_*``).
- :mod:`slo` — declarative SLOs (p50 detect latency, aggregate fps,
  stream availability) with multi-window burn-rate episodes, served at
  ``/api/v1/slo`` and feeding the resilience degradation ladder.
- :mod:`prof` — duration-bounded jax.profiler captures (on-demand via
  ``/api/v1/profile`` + gRPC admin mirror, or fired automatically when an
  SLO episode opens / the degradation ladder escalates) written as
  self-contained bundles into a byte-bounded retention ring.
- :mod:`quality` — output-quality observability: per-stream black /
  frozen / flatline verdict state machines fed by device-computed frame
  statistics, detection drift scores vs committed baselines, and the
  canary golden-replay integrity check (``vep_quality_*`` /
  ``/api/v1/quality``), feeding the degradation ladder's first-shed set
  and the ``canary_integrity`` SLO.
- :mod:`fleet` — the cross-process tier (ISSUE r14 tentpole): scrapes N
  member engines' ``/metrics`` + ``/api/v1/stats`` + ``/api/v1/slo``,
  merges counters (sum) / gauges (last-write + staleness flag) /
  histograms (bucket merge) under an ``instance`` label, and ranks
  member health (``vep_fleet_*``, ``/api/v1/fleet/stats``).
- :mod:`capacity` — the forward-looking tier (ISSUE r18 tentpole): the
  per-stream device-time ledger (conservation-gated attribution of every
  measured batch back to its occupant streams), per-(model, geometry,
  bucket) utilization rings with an EWMA-slope ``time_to_saturation_s``
  forecast, and SRE-style fast/slow capacity burn rates
  (``vep_capacity_*``, ``/api/v1/capacity``) — the signal
  ``StreamRouter.admit`` consumes for headroom-aware placement.
- :mod:`journal` — the decision audit trail (ISSUE r23 tentpole): a
  process-wide bounded ring of causally-linked control-plane decision
  events (actor/action/subject/quantitative trigger/cause link) with
  ``why()`` backward chain walks, fleet merge via monotone per-member
  seqs, and ``vep_journal_*`` counters (``/api/v1/journal`` +
  ``/api/v1/why``).
- :mod:`hbm` — the memory mirror of :mod:`capacity` (ISSUE r21
  tentpole): static per-program footprints from ``memory_analysis()``
  at AOT-compile time, dynamic per-pool byte accounting via registered
  ``nbytes`` callables, a window-peak utilization model over the
  device's HBM budget, and an EWMA-slope ``time_to_oom_s`` forecast
  (``vep_hbm_*``, ``/api/v1/hbm``) feeding the degradation ladder,
  memory-aware admission, and the supervisor's scale-out decision.
"""

from .capacity import CapacityTracker
from .hbm import HbmTracker
from .metrics import Registry, registry
from .perf import PerfTracker, cost_summary, mfu_pct
from .prof import Profiler
from .quality import CanaryChecker, QualityTracker
from .slo import BurnRateSLO, SLOEngine, SLOSpec, default_slos, integrity_slo
from .fleet import FleetAggregator
from .journal import DecisionJournal, format_event, merge_journals
from .spans import (
    SpanRecorder, stage_breakdown, to_chrome_trace, trace_id_for, tracer,
)
from .watch import Watchdog

__all__ = [
    "CapacityTracker",
    "HbmTracker",
    "Registry",
    "registry",
    "PerfTracker",
    "Profiler",
    "CanaryChecker",
    "QualityTracker",
    "cost_summary",
    "mfu_pct",
    "BurnRateSLO",
    "SLOEngine",
    "SLOSpec",
    "default_slos",
    "integrity_slo",
    "FleetAggregator",
    "DecisionJournal",
    "format_event",
    "merge_journals",
    "SpanRecorder",
    "stage_breakdown",
    "to_chrome_trace",
    "trace_id_for",
    "tracer",
    "Watchdog",
]
