"""Observability plane: unified metrics registry, frame-lineage tracing,
stall/watermark detection (ISSUE r7 tentpole).

Pure-Python, jax-free, importable from control-plane and worker code alike.
Three modules:

- :mod:`metrics` — process-wide counters/gauges/log2-histograms, rendered
  once by ``/metrics`` (Prometheus 0.0.4) and ``/api/v1/stats`` (JSON).
- :mod:`spans` — sampled per-frame lineage span events (ingest -> bus ->
  batch -> device -> emit), per-stream ring buffers, Chrome trace-event
  export (``tools/obs_export.py``) and ``/api/v1/trace``.
- :mod:`watch` — threshold-crossing detection (drain backpressure, batch
  occupancy, recompilation storms, frame drops) logged once per episode.
"""

from .metrics import Registry, registry
from .spans import SpanRecorder, stage_breakdown, to_chrome_trace, tracer
from .watch import Watchdog

__all__ = [
    "Registry",
    "registry",
    "SpanRecorder",
    "stage_breakdown",
    "to_chrome_trace",
    "tracer",
    "Watchdog",
]
