"""Fleet telemetry plane: multi-engine aggregation + member health.

ROADMAP item 4's observability substrate (MultiStream, arxiv 2207.06078:
a many-camera monitor is only operable when per-member telemetry rolls up
into one pane). Every other obs module is process-local; this one makes N
engine processes read as one system:

- ``FleetAggregator`` scrapes each member's ``/metrics`` +
  ``/api/v1/stats`` + ``/api/v1/slo`` + ``/api/v1/capacity`` +
  ``/api/v1/journal`` over plain HTTP (stdlib urllib — jax-free,
  dependency-free, importable from control-plane code). A member
  without a given plane (400/older version) degrades to an empty
  dict — mixed-version fleets merge.
- **Merge rules** (ISSUE r14): counters are SUMMED across members,
  log2 histograms are bucket-merged (identical ``le`` grids by
  construction — metrics.py owns the bounds), gauges are last-write per
  member with a staleness flag instead of a meaningless cross-member sum.
- ``merged_exposition()`` renders ONE lint-clean Prometheus text page:
  every member sample labeled ``instance="<member>"`` (preserved when the
  member already self-labels via ``Registry.set_const_labels``), plus the
  ``vep_fleet_*`` health families below.
- **Member health scoring**: liveness/staleness, SLO burn, degradation
  ladder rung and admitted-stream count folded into one ranked view —
  exactly the input the item-4 router will consume for shed/re-place
  decisions.

Serving: any member exposes ``/api/v1/fleet/stats`` +
``/api/v1/fleet/metrics`` when ``obs.fleet_members`` is configured
(serve/rest_api.py), and ``python -m video_edge_ai_proxy_tpu.obs.fleet``
runs the same aggregator standalone on stdlib http.server.

Fleet metric families (all gauges unless noted):

- ``vep_fleet_members`` — configured member count
- ``vep_fleet_member_up{instance}`` — 1 after a successful last scrape
- ``vep_fleet_member_staleness_seconds{instance}`` — age of last good
  scrape
- ``vep_fleet_member_stale{instance}`` — staleness flag (dead OR older
  than the staleness bound)
- ``vep_fleet_member_health_score{instance}`` — ranked health in [0, 1]
- ``vep_fleet_member_health_score_ema{instance}`` — EMA-smoothed score
  (r16: the flap-free signal the router's placement decisions read)
- ``vep_fleet_member_healthy{instance}`` — hysteresis-banded verdict:
  flips healthy at ``score_ema >= healthy_above`` and unhealthy at
  ``score_ema <= unhealthy_below``; holds in between, so one noisy
  scrape cannot bounce a member in and out of the placement ring
- ``vep_fleet_member_health_state_age_seconds{instance}`` — seconds
  since the last healthy/unhealthy flip (``healthy_since`` /
  ``unhealthy_since``: the router requires a minimum healthy age before
  a member takes migrated streams)
- ``vep_fleet_member_slo_burning{instance}``
- ``vep_fleet_member_ladder_rung{instance}``
- ``vep_fleet_member_streams{instance}``
- ``vep_fleet_member_warming{instance}`` — 1 while a spawned member is
  scraped-alive but its prewarm program set is incomplete (r19: held
  out of the placement ring, never retired by the supervisor)
- ``vep_fleet_member_headroom{instance}`` — forecast capacity headroom
  in [0, 1] from the member's r18 capacity plane (-1 when the member
  does not report capacity — mixed-version fleet)
- ``vep_fleet_member_capacity_utilization{instance}`` — fast-window
  device-time utilization (-1 when unreported)
- ``vep_fleet_member_time_to_saturation_seconds{instance}`` —
  EWMA-slope saturation forecast (-1 when unreported or not burning
  toward saturation)
- ``vep_fleet_member_hbm_headroom_bytes{instance}`` — device-memory
  headroom from the member's r21 HBM plane (-1 when the member does not
  report it — mixed-version fleet)
- ``vep_fleet_member_hbm_utilization{instance}`` — fast-window HBM
  utilization (-1 when unreported)
- ``vep_fleet_member_time_to_oom_seconds{instance}`` — EWMA byte-slope
  OOM forecast (-1 when unreported or not trending toward OOM)
- ``vep_fleet_scrapes_total{instance}`` /
  ``vep_fleet_scrape_failures_total{instance}`` (counters)
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

_LABEL_TOKEN = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*(?:,|$)')


def parse_exposition(text: str) -> List[dict]:
    """Parse Prometheus text 0.0.4 into ordered families:
    ``[{name, kind, help, samples: [(sample_name, labels_str, value)]}]``.
    ``labels_str`` is the raw inside-braces text ("" when unlabeled);
    values stay floats. Tolerant of unannounced samples (untyped
    family synthesized) so a foreign member's page still merges."""
    fams: List[dict] = []
    by_name: Dict[str, dict] = {}

    def family(name: str) -> dict:
        fam = by_name.get(name)
        if fam is None:
            fam = {"name": name, "kind": "untyped", "help": "",
                   "samples": []}
            by_name[name] = fam
            fams.append(fam)
        return fam

    def base_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in by_name:
                return name[: -len(suffix)]
        return name

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                continue
            fam = family(parts[2])
            if line.startswith("# HELP "):
                fam["help"] = parts[3] if len(parts) > 3 else ""
            elif len(parts) > 3:
                fam["kind"] = parts[3]
            continue
        if line.startswith("#"):
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < 0:
                continue
            name = line[:brace]
            labels = line[brace + 1:close]
            rest = line[close + 1:].strip()
        else:
            name, _, rest = line.partition(" ")
            labels = ""
        try:
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        family(base_of(name))["samples"].append((name, labels, value))
    return fams


def _labels_dict(labels_str: str) -> Dict[str, str]:
    return {m.group(1): m.group(2)
            for m in _LABEL_TOKEN.finditer(labels_str)}


def _strip_label(labels_str: str, name: str) -> str:
    """Remove one ``name="..."`` pair from a raw label string."""
    pairs = [(m.group(1), m.group(2))
             for m in _LABEL_TOKEN.finditer(labels_str)]
    return ",".join(f'{n}="{v}"' for n, v in pairs if n != name)


def _with_instance(labels_str: str, instance: str) -> str:
    """Ensure the sample carries ``instance="..."`` (members that
    self-label via set_const_labels keep their own value)."""
    if re.search(r'(^|,)\s*instance="', labels_str):
        return labels_str
    pair = f'instance="{instance}"'
    return f"{pair},{labels_str}" if labels_str else pair


class MemberState:
    """Last-scrape snapshot of one fleet member (mutated only by the
    aggregator thread; read under the aggregator lock)."""

    def __init__(self, name: str, base_url: str):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.alive = False
        self.last_ok: Optional[float] = None     # time.monotonic()
        self.last_err = ""
        self.scrapes = 0
        self.failures = 0
        self.families: List[dict] = []
        self.stats: dict = {}
        self.slo: dict = {}
        self.capacity: dict = {}
        self.hbm: dict = {}
        self.journal: list = []
        # r16 flap-free health (updated once per scrape pass, never at
        # read time): EMA of the instantaneous score + a hysteresis-banded
        # healthy verdict with entry timestamps.
        self.score_ema: Optional[float] = None
        self.healthy: Optional[bool] = None
        self.healthy_since: Optional[float] = None    # time.monotonic()
        self.unhealthy_since: Optional[float] = None

    # -- derived health signals --

    def staleness_s(self, now: float) -> Optional[float]:
        return None if self.last_ok is None else max(0.0, now - self.last_ok)

    def streams(self) -> int:
        eng = (self.stats or {}).get("engine") or {}
        return len(eng.get("streams") or {})

    def warming(self) -> bool:
        """r19 spawn state: scraped-alive but the engine's prewarm
        program set is incomplete (a spawning member binds REST before
        it compiles — see serve/server.py boot order). Distinct from
        dead/stale: the member answers scrapes and scores normally, but
        the router holds it out of the placement ring and the
        supervisor never retires it. Members that do not report prewarm
        (engine-less, pre-r19) are never warming."""
        pw = ((self.stats or {}).get("engine") or {}).get("prewarm")
        if not isinstance(pw, dict):
            return False
        return self.alive and not bool(pw.get("complete", True))

    def burning(self) -> bool:
        return bool((self.slo or {}).get("burning"))

    def ladder_rung(self) -> float:
        for fam in self.families:
            if fam["name"] == "vep_ladder_rung":
                for _, _, value in fam["samples"]:
                    return float(value)
        return 0.0

    # r18 capacity signals; all None when the member does not report the
    # capacity plane (disabled or pre-r18 — mixed-version fleet).

    def headroom(self) -> Optional[float]:
        v = (self.capacity or {}).get("headroom")
        return float(v) if v is not None else None

    def capacity_util(self) -> Optional[float]:
        util = (self.capacity or {}).get("utilization") or {}
        v = util.get("fast")
        return float(v) if v is not None else None

    def time_to_saturation_s(self) -> Optional[float]:
        v = (self.capacity or {}).get("time_to_saturation_s")
        return float(v) if v is not None else None

    # r21 HBM signals; all None when the member does not report the HBM
    # plane (disabled or pre-r21 — mixed-version fleet).

    def hbm_headroom_bytes(self) -> Optional[float]:
        v = (self.hbm or {}).get("headroom_bytes")
        return float(v) if v is not None else None

    def hbm_util(self) -> Optional[float]:
        util = (self.hbm or {}).get("utilization") or {}
        v = util.get("fast")
        return float(v) if v is not None else None

    def time_to_oom_s(self) -> Optional[float]:
        v = (self.hbm or {}).get("time_to_oom_s")
        return float(v) if v is not None else None

    # r22 device-fault signals (rides /api/v1/stats -> obs.faults; no
    # extra fetch). None when the member does not report the fault
    # domain (disabled or pre-r22 — mixed-version fleet).

    def _faults(self) -> Optional[dict]:
        f = ((self.stats or {}).get("obs") or {}).get("faults")
        return f if isinstance(f, dict) else None

    def device_fault_failovers(self) -> Optional[int]:
        """Cumulative survivor-mesh failovers the member has executed —
        the supervisor's device_fault spawn trigger (an INCREASE means a
        chip just died; the member serves degraded on fewer shards)."""
        f = self._faults()
        if f is None or f.get("failovers") is None:
            return None
        return int(f["failovers"])

    def device_fault_active(self) -> Optional[bool]:
        """A fault window is open or shards are pending failover."""
        f = self._faults()
        return bool(f.get("active")) if f is not None else None


class FleetAggregator:
    """Scrape-and-merge tier over N member engines.

    ``members``: list of ``"name=http://host:port"`` (or bare URLs, which
    take ``m<i>`` names). ``scrape_interval_s`` paces the background
    thread (``start``/``stop``); ``scrape_once`` works without it.
    ``stale_after_s`` defaults to one scrape interval so a killed member
    is staleness-flagged by the very next pass (ISSUE acceptance)."""

    def __init__(self, members, *, scrape_interval_s: float = 2.0,
                 stale_after_s: Optional[float] = None,
                 timeout_s: float = 2.0, ema_alpha: float = 0.4,
                 healthy_above: float = 0.7, unhealthy_below: float = 0.4):
        self._members: List[MemberState] = []
        for i, spec in enumerate(members):
            name, sep, url = str(spec).partition("=")
            if not sep:
                name, url = f"m{i}", str(spec)
            self._members.append(MemberState(name, url))
        # Auto-name sequence for bare-URL add_member specs. Monotonic —
        # a removal never frees its name for reuse, so add(m0,m1),
        # remove(m0), add(bare) yields m2, not a duplicate-m1 ValueError.
        self._auto_seq = len(self._members)
        self.scrape_interval_s = float(scrape_interval_s)
        self.stale_after_s = (float(stale_after_s) if stale_after_s
                              else self.scrape_interval_s)
        self.timeout_s = float(timeout_s)
        # r16 flap suppression: the EMA smooths the instantaneous score
        # and the two thresholds form a hysteresis band — a member flips
        # healthy only at >= healthy_above and unhealthy only at
        # <= unhealthy_below, holding its previous verdict in between.
        self.ema_alpha = float(ema_alpha)
        self.healthy_above = float(healthy_above)
        self.unhealthy_below = float(unhealthy_below)
        if not (0.0 <= self.unhealthy_below <= self.healthy_above <= 1.0):
            raise ValueError(
                f"hysteresis band must satisfy 0 <= unhealthy_below <= "
                f"healthy_above <= 1, got [{unhealthy_below}, "
                f"{healthy_above}]")
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_scrape_wall_ms = 0.0

    # -- lifecycle --

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-scrape", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 1.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.scrape_interval_s)

    # -- membership (r19 supervisor hooks) --

    def add_member(self, spec: str) -> str:
        """Register one member at runtime (``"name=url"`` or a bare URL,
        auto-named from a monotonic ``m<N>`` sequence — never reusing a
        removed member's name); the next scrape pass picks it up.
        Returns the member name; duplicates raise."""
        name, sep, url = str(spec).partition("=")
        with self._lock:
            if not sep:
                url = str(spec)
                # Skip operator-claimed m<N> names too, not just our own.
                while any(m.name == f"m{self._auto_seq}"
                          for m in self._members):
                    self._auto_seq += 1
                name = f"m{self._auto_seq}"
                self._auto_seq += 1
            if any(m.name == name for m in self._members):
                raise ValueError(f"member {name!r} already registered")
            self._members.append(MemberState(name, url))
        return name

    def remove_member(self, name: str) -> None:
        """Deregister a member; its health rows and merged samples stop
        at the next read. Unknown names are a no-op (retire after a
        crash-remove race must not raise). The list is replaced, not
        mutated, so a concurrently running scrape pass finishes over the
        snapshot it started with."""
        with self._lock:
            self._members = [m for m in self._members if m.name != name]

    # -- scraping --

    def _fetch(self, url: str) -> bytes:
        import urllib.request

        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read()

    def scrape_once(self) -> dict:
        """One pass over every member; returns the health view. Errors
        mark the member down (failures counted) — never raise."""
        t0 = time.monotonic()
        for m in self._members:
            try:
                text = self._fetch(m.base_url + "/metrics").decode()
                stats = json.loads(self._fetch(m.base_url + "/api/v1/stats"))
                try:
                    slo = json.loads(self._fetch(m.base_url + "/api/v1/slo"))
                except Exception:
                    slo = {}   # SLO plane disabled on the member (400)
                try:
                    capacity = json.loads(
                        self._fetch(m.base_url + "/api/v1/capacity"))
                except Exception:
                    # Capacity plane disabled (400) or a pre-r18 member
                    # (404) — merge the rest; health rows carry None.
                    capacity = {}
                try:
                    hbm = json.loads(
                        self._fetch(m.base_url + "/api/v1/hbm"))
                except Exception:
                    # HBM plane disabled (400) or a pre-r21 member (404)
                    # — merge the rest; health rows carry None and the
                    # fleet gauges render -1 sentinels.
                    hbm = {}
                try:
                    journal = json.loads(
                        self._fetch(m.base_url + "/api/v1/journal")
                    ).get("events") or []
                except Exception:
                    # Journal disabled (400) or a pre-r23 member (404)
                    # — the merged journal just misses this member.
                    journal = []
                with self._lock:
                    m.families = parse_exposition(text)
                    m.stats = stats
                    m.slo = slo
                    m.capacity = capacity
                    m.hbm = hbm
                    m.journal = journal
                    m.alive = True
                    m.last_ok = time.monotonic()
                    m.last_err = ""
                    m.scrapes += 1
            except Exception as e:  # noqa: BLE001 — any member fault
                with self._lock:
                    m.alive = False
                    m.last_err = f"{type(e).__name__}: {e}"
                    m.failures += 1
        # One EMA/hysteresis update per PASS (not per read): health()
        # stays a pure view, so concurrent readers cannot double-fold a
        # sample into the EMA or race the band state.
        now = time.monotonic()
        with self._lock:
            for m in self._members:
                score = self._raw_score(m, now)
                m.score_ema = score if m.score_ema is None else (
                    self.ema_alpha * score
                    + (1.0 - self.ema_alpha) * m.score_ema)
                if m.score_ema >= self.healthy_above:
                    verdict = True
                elif m.score_ema <= self.unhealthy_below:
                    verdict = False
                else:
                    # Mid-band: hold the previous verdict; a brand-new
                    # member starting mid-band is optimistically healthy
                    # (the placement ring would otherwise be empty at
                    # boot).
                    verdict = m.healthy if m.healthy is not None else True
                if verdict != m.healthy:
                    m.healthy = verdict
                    if verdict:
                        m.healthy_since = now
                        m.unhealthy_since = None
                    else:
                        m.unhealthy_since = now
                        m.healthy_since = None
        self._last_scrape_wall_ms = (time.monotonic() - t0) * 1000.0
        return self.health()

    # -- health --

    def _raw_score(self, m: MemberState, now: float) -> float:
        """Instantaneous health score in [0, 1] (the r14 formula); the
        EMA/hysteresis layer on top is what the router consumes."""
        staleness = m.staleness_s(now)
        stale = (not m.alive) or staleness is None \
            or staleness > self.stale_after_s
        if (not m.alive and m.last_ok is None) or stale:
            return 0.0
        return max(0.0, min(1.0, (
            1.0 - (0.5 if m.burning() else 0.0)
            - 0.15 * m.ladder_rung() - 0.02 * m.streams())))

    def _member_health(self, m: MemberState, now: float) -> dict:
        staleness = m.staleness_s(now)
        stale = (not m.alive) or staleness is None \
            or staleness > self.stale_after_s
        rung = m.ladder_rung()
        streams = m.streams()
        burning = m.burning()
        score = self._raw_score(m, now)
        return {
            "instance": m.name,
            "url": m.base_url,
            "up": m.alive,
            "stale": stale,
            "staleness_s": round(staleness, 3)
            if staleness is not None else None,
            "slo_burning": burning,
            "ladder_rung": rung,
            "streams": streams,
            "warming": m.warming(),
            # r18 capacity plane (None-keyed when the member does not
            # report it — the router treats those as capacity-less).
            "capacity": bool(m.capacity),
            "headroom": m.headroom(),
            "capacity_utilization": m.capacity_util(),
            "time_to_saturation_s": m.time_to_saturation_s(),
            # r21 HBM plane (None-keyed when unreported — the router
            # treats those as memory-blind, admitting on time alone).
            "hbm": bool(m.hbm),
            "hbm_headroom_bytes": m.hbm_headroom_bytes(),
            "hbm_utilization": m.hbm_util(),
            "time_to_oom_s": m.time_to_oom_s(),
            # r22 device-fault domain (None-keyed when unreported — the
            # supervisor skips fault-blind members).
            "device_fault_failovers": m.device_fault_failovers(),
            "device_fault_active": m.device_fault_active(),
            "score": round(score, 4),
            "score_ema": round(m.score_ema, 4)
            if m.score_ema is not None else None,
            "healthy": m.healthy,
            "healthy_since_s": round(now - m.healthy_since, 3)
            if m.healthy_since is not None else None,
            "unhealthy_since_s": round(now - m.unhealthy_since, 3)
            if m.unhealthy_since is not None else None,
            "scrapes": m.scrapes,
            "failures": m.failures,
            "last_err": m.last_err,
        }

    def health(self) -> List[dict]:
        """Per-member health, ranked best-first (the router's shed /
        re-place input: shed FROM the tail, place ONTO the head)."""
        now = time.monotonic()
        with self._lock:
            rows = [self._member_health(m, now) for m in self._members]
        rows.sort(key=lambda r: (-r["score"], r["instance"]))
        return rows

    # -- merging --

    def _merge(self) -> Tuple[dict, dict, dict]:
        """(counters, gauges, histograms) merged across live members.

        counters:   {family: {labels: {"value": sum,
                     "instances": {name: v}}}}   — sum semantics
        gauges:     {family: {labels: {"value": last-write,
                     "instance": name, "stale": bool,
                     "instances": {name: {"value": v, "stale": bool}}}}}
        histograms: {family: {labels: {"buckets": {le: cum}, "sum": s,
                     "count": n}}}               — bucket-wise sum
        ``labels`` excludes instance/le. Last-write for a gauge = the
        most recently scraped member carrying it (scrape order breaks
        ties); its staleness rides along as the flag."""
        counters: dict = {}
        gauges: dict = {}
        hists: dict = {}
        now = time.monotonic()
        with self._lock:
            members = [(m, m.staleness_s(now), m.families)
                       for m in self._members]
            stale_bound = self.stale_after_s
        order = sorted(
            (m for m in members if m[1] is not None),
            key=lambda t: t[1], reverse=True)  # stalest first, freshest last
        for m, staleness, fams in order:
            stale = (not m.alive) or staleness > stale_bound
            for fam in fams:
                kind = fam["kind"]
                if kind == "counter":
                    slot = counters.setdefault(fam["name"], {})
                    for _, labels, value in fam["samples"]:
                        key = _strip_label(labels, "instance")
                        row = slot.setdefault(
                            key, {"value": 0.0, "instances": {}})
                        row["value"] += value
                        row["instances"][m.name] = value
                elif kind == "gauge":
                    slot = gauges.setdefault(fam["name"], {})
                    for _, labels, value in fam["samples"]:
                        key = _strip_label(labels, "instance")
                        row = slot.setdefault(key, {"instances": {}})
                        row["instances"][m.name] = {
                            "value": value, "stale": stale}
                        row["value"] = value        # last write wins
                        row["instance"] = m.name
                        row["stale"] = stale
                elif kind == "histogram":
                    slot = hists.setdefault(fam["name"], {})
                    for name, labels, value in fam["samples"]:
                        key = _strip_label(
                            _strip_label(labels, "instance"), "le")
                        row = slot.setdefault(
                            key, {"buckets": {}, "sum": 0.0, "count": 0})
                        if name.endswith("_bucket"):
                            le = _labels_dict(labels).get("le", "+Inf")
                            row["buckets"][le] = \
                                row["buckets"].get(le, 0.0) + value
                        elif name.endswith("_sum"):
                            row["sum"] += value
                        elif name.endswith("_count"):
                            row["count"] += int(value)
        return counters, gauges, hists

    def merged_journal(self) -> dict:
        """The ``/api/v1/fleet/journal`` body (r23): every member's
        decision-journal events tagged ``member=<name>``, merged in
        ``(ts, member, seq)`` order — the per-member seqs are monotone,
        so the merge is deterministic across scrape arrival orders (the
        r14 stitching idiom, journal edition)."""
        from .journal import merge_journals

        with self._lock:
            per_member = {m.name: list(m.journal) for m in self._members}
        events = merge_journals(per_member)
        return {
            "members": sorted(per_member),
            "events": events,
        }

    def fleet_stats(self) -> dict:
        """The ``/api/v1/fleet/stats`` body: ranked health + merged
        counters/gauges/histograms + scrape-plane accounting."""
        counters, gauges, hists = self._merge()
        return {
            "members": len(self._members),
            "scrape_interval_s": self.scrape_interval_s,
            "stale_after_s": self.stale_after_s,
            "last_scrape_wall_ms": round(self._last_scrape_wall_ms, 3),
            "health": self.health(),
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "journal": {m.name: len(m.journal) for m in self._members},
        }

    def _fleet_families(self) -> List[str]:
        health = self.health()
        lines = [
            "# HELP vep_fleet_members Configured fleet member count",
            "# TYPE vep_fleet_members gauge",
            f"vep_fleet_members {len(self._members)}",
        ]

        def fam(name, kind, help_text, key, cast=lambda v: f"{v:g}"):
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for row in health:
                val = key(row)
                lines.append(
                    f'{name}{{instance="{row["instance"]}"}} {cast(val)}')

        fam("vep_fleet_member_up", "gauge",
            "1 when the member's last scrape succeeded",
            lambda r: 1.0 if r["up"] else 0.0)
        fam("vep_fleet_member_stale", "gauge",
            "1 when the member is dead or past the staleness bound",
            lambda r: 1.0 if r["stale"] else 0.0)
        fam("vep_fleet_member_staleness_seconds", "gauge",
            "Age of the member's last successful scrape",
            lambda r: r["staleness_s"]
            if r["staleness_s"] is not None else -1.0)
        fam("vep_fleet_member_health_score", "gauge",
            "Ranked member health in [0,1] (router placement input)",
            lambda r: r["score"])
        fam("vep_fleet_member_health_score_ema", "gauge",
            "EMA-smoothed member health score (flap-free router signal)",
            lambda r: r["score_ema"] if r["score_ema"] is not None
            else -1.0)
        fam("vep_fleet_member_healthy", "gauge",
            "Hysteresis-banded member health verdict (1=healthy)",
            lambda r: -1.0 if r["healthy"] is None
            else (1.0 if r["healthy"] else 0.0))
        fam("vep_fleet_member_health_state_age_seconds", "gauge",
            "Seconds since the member's last healthy/unhealthy flip",
            lambda r: r["healthy_since_s"]
            if r["healthy_since_s"] is not None
            else (r["unhealthy_since_s"]
                  if r["unhealthy_since_s"] is not None else -1.0))
        fam("vep_fleet_member_slo_burning", "gauge",
            "1 when the member's SLO engine reports burning",
            lambda r: 1.0 if r["slo_burning"] else 0.0)
        fam("vep_fleet_member_ladder_rung", "gauge",
            "Member degradation-ladder rung index",
            lambda r: r["ladder_rung"])
        fam("vep_fleet_member_streams", "gauge",
            "Member admitted-stream count",
            lambda r: r["streams"])
        fam("vep_fleet_member_warming", "gauge",
            "1 while a spawned member is scraped-alive but its prewarm "
            "program set is incomplete (held out of placement, never "
            "retired)",
            lambda r: 1.0 if r.get("warming") else 0.0)
        fam("vep_fleet_member_headroom", "gauge",
            "Forecast capacity headroom in [0,1] (-1 when unreported)",
            lambda r: r["headroom"] if r["headroom"] is not None else -1.0)
        fam("vep_fleet_member_capacity_utilization", "gauge",
            "Fast-window device-time utilization (-1 when unreported)",
            lambda r: r["capacity_utilization"]
            if r["capacity_utilization"] is not None else -1.0)
        fam("vep_fleet_member_time_to_saturation_seconds", "gauge",
            "EWMA-slope saturation forecast (-1 when unreported or not "
            "trending toward saturation)",
            lambda r: r["time_to_saturation_s"]
            if r["time_to_saturation_s"] is not None else -1.0)
        fam("vep_fleet_member_hbm_headroom_bytes", "gauge",
            "Device-memory headroom in bytes (-1 when unreported)",
            lambda r: r["hbm_headroom_bytes"]
            if r["hbm_headroom_bytes"] is not None else -1.0)
        fam("vep_fleet_member_hbm_utilization", "gauge",
            "Fast-window HBM utilization (-1 when unreported)",
            lambda r: r["hbm_utilization"]
            if r["hbm_utilization"] is not None else -1.0)
        fam("vep_fleet_member_time_to_oom_seconds", "gauge",
            "EWMA byte-slope OOM forecast (-1 when unreported or not "
            "trending toward OOM)",
            lambda r: r["time_to_oom_s"]
            if r["time_to_oom_s"] is not None else -1.0)
        fam("vep_fleet_scrapes_total", "counter",
            "Successful member scrapes", lambda r: r["scrapes"])
        fam("vep_fleet_scrape_failures_total", "counter",
            "Failed member scrapes", lambda r: r["failures"])
        return lines

    def merged_exposition(self) -> str:
        """One Prometheus text page for the whole fleet: every member
        sample with an ``instance`` label (contiguous per family — the
        member pages are re-grouped, not concatenated) plus the
        ``vep_fleet_*`` families. Lint-clean under
        ``metrics.lint_exposition`` (tested on member AND merged
        output)."""
        with self._lock:
            per_member = [(m.name, m.families) for m in self._members
                          if m.families]
        merged: Dict[str, dict] = {}
        order: List[str] = []
        for name, fams in per_member:
            for fam in fams:
                slot = merged.get(fam["name"])
                if slot is None:
                    slot = {"kind": fam["kind"], "help": fam["help"],
                            "samples": []}
                    merged[fam["name"]] = slot
                    order.append(fam["name"])
                for sample_name, labels, value in fam["samples"]:
                    slot["samples"].append(
                        (sample_name, _with_instance(labels, name), value))
        lines: List[str] = []
        seen: set = set()
        for fname in order:
            fam = merged[fname]
            if fam["help"]:
                lines.append(f"# HELP {fname} {fam['help']}")
            lines.append(f"# TYPE {fname} {fam['kind']}")
            for sample_name, labels, value in fam["samples"]:
                key = (sample_name, labels)
                if key in seen:   # two members claiming one identity
                    continue
                seen.add(key)
                ls = "{" + labels + "}" if labels else ""
                lines.append(f"{sample_name}{ls} {value:g}")
        lines.extend(self._fleet_families())
        return "\n".join(lines) + "\n"


def main(argv=None) -> None:
    """Standalone aggregator: scrape members, serve the merged plane on
    stdlib http.server (no aiohttp/jax — deployable next to any member).

    Usage::

      python -m video_edge_ai_proxy_tpu.obs.fleet \\
          --members m0=http://h0:8080 m1=http://h1:8080 --port 9090
    """
    import argparse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--members", nargs="+", required=True,
                    help="member specs: name=http://host:port (or bare "
                         "URLs, auto-named m0..mN)")
    ap.add_argument("--port", type=int, default=9090)
    ap.add_argument("--scrape-interval", type=float, default=2.0)
    ap.add_argument("--stale-after", type=float, default=0.0,
                    help="staleness bound seconds (0 = one scrape "
                         "interval)")
    args = ap.parse_args(argv)

    agg = FleetAggregator(
        args.members, scrape_interval_s=args.scrape_interval,
        stale_after_s=args.stale_after or None)
    agg.start()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path.split("?")[0] in ("/metrics",
                                           "/api/v1/fleet/metrics"):
                body = agg.merged_exposition().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path.split("?")[0] == "/api/v1/fleet/stats":
                body = json.dumps(agg.fleet_stats()).encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/api/v1/fleet/journal":
                body = json.dumps(agg.merged_journal()).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer(("0.0.0.0", args.port), Handler)
    print(json.dumps({"fleet_aggregator": True, "port": srv.server_port,
                      "members": len(agg._members)}), flush=True)
    try:
        srv.serve_forever()
    finally:
        agg.stop()


if __name__ == "__main__":
    main()
