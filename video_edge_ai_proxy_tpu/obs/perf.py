"""Live device-performance attribution: compile cost, padding waste, MFU.

The reference proxy has no notion of device efficiency at all — its per-
stream in/out frame counters (reference grpcapi.go:141 stats loop) say
*whether* frames flow, never *how well the accelerator is used*. On a TPU
the three quantities that decide "as fast as the hardware allows" are
(a) what each compiled program costs (XLA cost analysis: FLOPs/bytes),
(b) how long the device actually spends per batch, and (c) how many batch
slots carry zero-padding instead of real frames (``pad_to_bucket``,
engine/collector.py:45). Until r9 those existed only offline
(tools/profile_mfu.py artifacts like ``MFU_vit_r05.json``); this module
is the *live* counterpart feeding the r7 registry (obs/metrics.py) so
``/metrics`` and ``/api/v1/stats`` show, per model+bucket: device ms,
achieved TFLOPs vs ``peak_tflops``, and % slots wasted to padding
(MOSAIC / arxiv 2305.03222: spatial multiplexing lives or dies on
continuous accelerator-utilization accounting).

Design notes:

- **jax-free at import.** ``cost_summary`` takes an already-compiled XLA
  executable object duck-typed (``.cost_analysis()``), so the control
  plane imports this without initializing a backend (CLAUDE.md rule).
- **Fixed-allocation hot path.** ``note_batch`` runs per device batch on
  the drain thread: child metric handles and EMA cells are cached per
  (model, bucket) key — after the first batch of a key, the call makes no
  new long-lived objects (guarded by the tier-1 allocation-bound test in
  tests/test_obs.py).
- **Live MFU is a proxy, not a profile.** ``device_ms`` as measured by
  the engine (runner.py `_emit`) includes drain-queue wait, and on the
  dev tunnel RPC overhead; the gauge trends with true MFU (BASELINE.md
  cross-checks it against offline ``profile_mfu`` within ~10% on the
  lockstep bench) but is not a tracing profile.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, Optional, Tuple

from . import metrics

# v5e bf16 dense peak, single chip — same constant tools/profile_mfu.py
# uses for the offline artifacts, so live and offline MFU are comparable.
DEFAULT_PEAK_TFLOPS = 197.0


def cost_summary(compiled) -> dict:
    """FLOPs/bytes from an XLA compiled executable's ``cost_analysis()``.

    Same shape-tolerance as tools/profile_mfu.py: jax versions return a
    dict, a list of dicts, or raise on backends without cost analysis —
    normalize all of that to a plain {"flops": .., "bytes_accessed": ..}
    dict, empty when unavailable (callers treat missing FLOPs as
    "MFU unknown", never as an error).
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out: dict = {}
    flops = float(cost.get("flops", 0.0) or 0.0)
    nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    if flops > 0.0:
        out["flops"] = flops
    if nbytes > 0.0:
        out["bytes_accessed"] = nbytes
    return out


def memory_summary(compiled) -> dict:
    """Device-memory footprint from an XLA compiled executable's
    ``memory_analysis()`` — the byte-side sibling of :func:`cost_summary`
    feeding the r21 HBM plane (obs/hbm.py).

    Duck-typed with the same tolerance: backends without memory analysis
    (or older jax returning None) normalize to ``{}`` — callers treat a
    missing footprint as "memory unknown", never as an error. Keys when
    available: ``argument_bytes``, ``output_bytes``, ``temp_bytes``,
    ``code_bytes`` (generated executable), ``alias_bytes`` (donated-
    argument aliasing — bytes the output shares with donated inputs).
    """
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out: dict = {}
    for key, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("code_bytes", "generated_code_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
    ):
        try:
            val = getattr(mem, attr)
        except Exception:
            continue
        if val is None:
            continue
        try:
            out[key] = int(val)
        except (TypeError, ValueError):
            continue
    return out


def mfu_pct(flops: float, device_ms: float,
            peak_tflops: float) -> Optional[float]:
    """Model FLOPs utilization: achieved FLOP/s over peak, percent.
    None when any input is unknown/degenerate rather than a fake 0."""
    if flops <= 0.0 or device_ms <= 0.0 or peak_tflops <= 0.0:
        return None
    achieved = flops / (device_ms * 1e-3)
    return 100.0 * achieved / (peak_tflops * 1e12)


class _RateWindow:
    """Sliding-window event rate over a bounded deque of (t, n) samples.

    Memory is bounded by ``maxlen``; expired entries are popped on every
    add, so steady state neither grows nor shrinks — the allocation-bound
    test measures across this. One sample per device batch (not per
    frame), so 4096 slots cover >40 s even at 100 batches/s.
    """

    __slots__ = ("_window_s", "_samples", "_total")

    def __init__(self, window_s: float = 10.0, maxlen: int = 4096):
        self._window_s = float(window_s)
        self._samples: Deque[Tuple[float, float]] = collections.deque(
            maxlen=maxlen)
        self._total = 0.0

    def add(self, n: float, now: float) -> None:
        if len(self._samples) == self._samples.maxlen:
            self._total -= self._samples[0][1]   # about to be evicted
        self._samples.append((now, float(n)))
        self._total += n
        self._expire(now)

    def _expire(self, now: float) -> None:
        cutoff = now - self._window_s
        s = self._samples
        while s and s[0][0] < cutoff:
            self._total -= s.popleft()[1]

    def rate(self, now: float) -> float:
        """Events/second over the window (0.0 when empty)."""
        self._expire(now)
        if not self._samples:
            return 0.0
        span = max(now - self._samples[0][0], 1e-6)
        # Use the real elapsed span, capped at the window, so the rate is
        # meaningful immediately after start instead of diluted by the
        # not-yet-elapsed window remainder.
        return self._total / min(max(span, 0.5), self._window_s)


class _H2DCell:
    """Per-(model, bucket) host->device transfer accounting: pre-resolved
    counter children + running totals, same fixed-allocation discipline
    as :class:`_BatchCell` (``note_h2d`` runs once per dispatched batch
    — on the engine tick thread, which with the prefetch stage enabled
    just relays the numbers the transfer thread measured)."""

    __slots__ = ("bytes_child", "seconds_child", "hidden_child", "bytes",
                 "seconds", "hidden_s", "batches", "slots")

    def __init__(self, bytes_child, seconds_child, hidden_child):
        self.bytes_child = bytes_child
        self.seconds_child = seconds_child
        self.hidden_child = hidden_child
        self.bytes = 0
        self.seconds = 0.0
        self.hidden_s = 0.0
        self.batches = 0
        self.slots = 0


class _ShardCell:
    """Per-(model, bucket, shard) mesh-serving attribution: pre-resolved
    counter children + running totals (same fixed-allocation discipline
    as :class:`_BatchCell`; these are NEW label families so the existing
    aggregate series keep their label tuples)."""

    __slots__ = ("frames_child", "busy_child", "frames", "busy_ms")

    def __init__(self, frames_child, busy_child):
        self.frames_child = frames_child
        self.busy_child = busy_child
        self.frames = 0
        self.busy_ms = 0.0


class _BatchCell:
    """Per-(model, geometry, bucket) hot-path state: pre-resolved metric
    children + EMA accumulator, so ``note_batch`` is lookups and float
    math after the first batch of a key."""

    __slots__ = ("device", "padded", "slots", "occupancy", "mfu", "tflops",
                 "ema_ms", "ema_init", "frames", "padded_total")

    def __init__(self, device, padded, slots, occupancy, mfu, tflops):
        self.device = device
        self.padded = padded
        self.slots = slots
        self.occupancy = occupancy
        self.mfu = mfu
        self.tflops = tflops
        self.ema_ms = 0.0
        self.ema_init = False
        self.frames = 0
        self.padded_total = 0


class PerfTracker:
    """Per-engine device-performance attribution feeding the registry.

    ``note_compile`` runs at every step-cache miss (engine/runner.py
    ``_step``): compile wall time + XLA cost analysis keyed by
    (model, geometry, bucket). ``note_batch`` runs per drained device
    batch: device-time histogram, padded-slot waste, occupancy, and the
    derived live MFU / achieved-TFLOPs / aggregate-fps gauges
    (``vep_perf_*`` + ``vep_compile_*`` families).
    """

    def __init__(self, *, peak_tflops: float = DEFAULT_PEAK_TFLOPS,
                 registry: Optional[metrics.Registry] = None,
                 clock=time.monotonic, fps_window_s: float = 10.0):
        reg = registry if registry is not None else metrics.registry
        self.peak_tflops = float(peak_tflops)
        self._clock = clock
        self._lock = threading.Lock()
        # (model, geometry, bucket) -> compile record
        self._compiles: Dict[Tuple[str, str, int], dict] = {}
        # (model, geometry, bucket) -> hot-path cell
        self._cells: Dict[Tuple[str, str, int], _BatchCell] = {}
        # (model, bucket) -> H2D transfer cell
        self._h2d: Dict[Tuple[str, int], _H2DCell] = {}
        # (model, bucket, shard) -> mesh-serving shard cell
        self._shard_cells: Dict[Tuple[str, int, str], _ShardCell] = {}
        self._fps = _RateWindow(window_s=fps_window_s)

        self._m_compile_s = reg.histogram(
            "vep_compile_seconds",
            "XLA compile wall time per step-cache miss",
            ("model", "geometry", "bucket"))
        self._m_compile_programs = reg.counter(
            "vep_compile_programs_total",
            "Compiled serving programs per (model, geometry, bucket)",
            ("model", "geometry", "bucket"))
        self._m_program_gflop = reg.gauge(
            "vep_compile_program_gflop",
            "FLOPs per program execution from XLA cost analysis (GFLOP)",
            ("model", "geometry", "bucket"))
        self._m_device = reg.histogram(
            "vep_perf_device_ms",
            "Device batch time per bucket (submit->drained; includes "
            "drain-queue wait)", ("model", "bucket"))
        self._m_padded = reg.counter(
            "vep_perf_padded_slots_total",
            "Batch slots filled with padding, not frames (pad_to_bucket "
            "waste)", ("model", "bucket"))
        self._m_slots = reg.counter(
            "vep_perf_batch_slots_total",
            "Total batch slots dispatched (real frames + padding)",
            ("model", "bucket"))
        self._m_occupancy = reg.gauge(
            "vep_perf_bucket_occupancy_pct",
            "Real frames over bucket size, last batch",
            ("model", "bucket"))
        self._m_mfu = reg.gauge(
            "vep_perf_mfu_pct",
            "Live model-FLOPs utilization vs peak_tflops (EMA device "
            "time; proxy, see obs/perf.py)", ("model", "bucket"))
        self._m_tflops = reg.gauge(
            "vep_perf_achieved_tflops",
            "Achieved TFLOP/s per batch (EMA device time)",
            ("model", "bucket"))
        self._m_peak = reg.gauge(
            "vep_perf_peak_tflops",
            "Configured device peak TFLOP/s used for MFU")
        self._m_peak.set(self.peak_tflops)
        self._m_fps = reg.gauge(
            "vep_perf_fps",
            "Aggregate emitted frames/second (sliding window)")
        # Mesh-native serving (ISSUE 17): per-shard attribution rides NEW
        # counter families keyed by shard, so every pre-existing series
        # above keeps its exact label tuple (exposition-lint stability).
        self._m_shard_frames = reg.counter(
            "vep_perf_shard_frames_total",
            "Real frames served per dp mesh shard",
            ("model", "bucket", "shard"))
        self._m_shard_busy = reg.counter(
            "vep_perf_shard_busy_ms_total",
            "Device batch milliseconds attributed per dp mesh shard "
            "(data-parallel replication: every chip runs the full "
            "program wall time)", ("model", "bucket", "shard"))
        self._m_h2d_bytes = reg.counter(
            "vep_h2d_bytes",
            "Host->device bytes shipped per dispatched batch (uint8 "
            "frames incl. bucket padding, plus aux tensors such as the "
            "int32 thumbnail slot-index vector)", ("model", "bucket"))
        self._m_h2d_seconds = reg.counter(
            "vep_h2d_seconds",
            "Wall seconds of async device_put transfer per batch, timed "
            "on the prefetch transfer thread (copy start to "
            "block_until_ready)", ("model", "bucket"))
        self._m_h2d_hidden = reg.counter(
            "vep_h2d_hidden_seconds",
            "Share of H2D transfer wall seconds that overlapped in-flight "
            "device compute or dispatch work (prefetch stage)",
            ("model", "bucket"))
        # ROI serving attribution (MOSAIC, engine/runner.py cfg.roi):
        # per-tick gate split, packer output, scatter-back routing
        # failures, and the projected full-frame-equivalent fps — the
        # rate of per-stream results served through the ROI plane
        # (coasted + packed + full), i.e. what the fleet would have cost
        # in full frames.
        self._m_roi_states = reg.counter(
            "vep_roi_stream_states_total",
            "Motion-gate verdicts per detect stream per tick",
            ("state",))
        self._m_roi_crops = reg.counter(
            "vep_roi_crops_total",
            "Crops packed onto shared canvases").labels()
        self._m_roi_canvases = reg.counter(
            "vep_roi_canvases_total",
            "Shared canvases dispatched").labels()
        self._m_roi_occupancy = reg.gauge(
            "vep_roi_canvas_occupancy_pct",
            "Crop-pixel share of the packed canvas plane, last "
            "batch").labels()
        self._m_roi_unrouted = reg.counter(
            "vep_roi_unrouted_total",
            "Canvas detections that landed outside every crop cell "
            "(dropped in scatter-back)").labels()
        self._m_roi_fps = reg.gauge(
            "vep_roi_equivalent_fps",
            "Per-stream results served through the ROI plane per second "
            "(full-frame-equivalent fps, sliding window)").labels()
        self._roi_fps = _RateWindow(window_s=fps_window_s)
        self._roi = {"idle": 0, "roi": 0, "full": 0, "crops": 0,
                     "canvases": 0, "unrouted": 0, "area_frac": None}
        # Temporal cascade attribution (temporal/scheduler.py, engine
        # cfg.cascade): detect runs every tick, the temporal head at
        # cadence 1/N — the cadence gauge (head batches over cascade
        # ticks) is the live form of the smoke artifact's
        # cascade_head_cadence gate.
        self._m_cascade_ticks = reg.counter(
            "vep_cascade_ticks_total",
            "Engine ticks observed by the cascade scheduler").labels()
        self._m_cascade_head = reg.counter(
            "vep_cascade_head_batches_total",
            "Temporal-head batches dispatched (cadence ticks with due "
            "tracks)").labels()
        self._m_cascade_events = reg.counter(
            "vep_cascade_events_total",
            "Track event transitions fired by the hysteresis machine",
            ("kind",))
        self._m_cascade_tracks = reg.gauge(
            "vep_cascade_tracks",
            "Track slots live in the device-resident state pool").labels()
        self._m_cascade_cadence = reg.gauge(
            "vep_cascade_head_cadence",
            "Cascade ticks per temporal-head batch (target: "
            "cascade_every_n)").labels()
        self._cascade = {"ticks": 0, "head_batches": 0, "head_slots": 0,
                         "events": {}, "tracks": 0, "high_water": 0}

    # -- compile-time attribution ----------------------------------------

    @staticmethod
    def _geometry(src_hw: Tuple[int, int]) -> str:
        return f"{src_hw[0]}x{src_hw[1]}"

    def note_compile(self, model: str, src_hw: Tuple[int, int], bucket: int,
                     seconds: float, *, compiled=None,
                     cost: Optional[dict] = None) -> None:
        """Record one step-cache-miss compile. ``compiled`` (an XLA
        executable) or a pre-extracted ``cost`` dict supplies FLOPs."""
        if cost is None:
            cost = cost_summary(compiled) if compiled is not None else {}
        geometry = self._geometry(src_hw)
        key = (model, geometry, bucket)
        with self._lock:
            rec = self._compiles.get(key)
            if rec is None:
                rec = {"model": model, "geometry": geometry,
                       "bucket": bucket, "programs": 0,
                       "compile_s": 0.0, "flops": 0.0,
                       "bytes_accessed": 0.0}
                self._compiles[key] = rec
            rec["programs"] += 1
            rec["compile_s"] += float(seconds)
            if cost.get("flops"):
                rec["flops"] = cost["flops"]
            if cost.get("bytes_accessed"):
                rec["bytes_accessed"] = cost["bytes_accessed"]
        b = str(bucket)
        self._m_compile_s.labels(model, geometry, b).observe(float(seconds))
        self._m_compile_programs.labels(model, geometry, b).inc()
        if cost.get("flops"):
            self._m_program_gflop.labels(model, geometry, b).set(
                cost["flops"] / 1e9)

    # -- tick-time attribution -------------------------------------------

    def note_batch(self, model: str, src_hw: Tuple[int, int], bucket: int,
                   device_ms: float, frames: int, *,
                   streams: Optional[int] = None,
                   area_frac: Optional[float] = None,
                   shard_frames: Optional[Dict[str, int]] = None) -> None:
        """Record one drained device batch: ``frames`` real frames in a
        ``bucket``-slot program that ran for ``device_ms``.

        Canvas-aware accounting (MOSAIC packed batches): ``frames`` is
        then the canvas count, ``streams`` the number of source streams
        whose crops rode the batch (feeds the fps window — results
        emitted, not canvases), and ``area_frac`` the crop-pixel share
        of the canvas plane. With ``area_frac`` the occupancy gauge
        reports crop-level occupancy — a half-empty canvas must NOT read
        as one fully-occupied slot.

        Mesh-native serving: ``shard_frames`` maps dp shard label ->
        real frames that shard contributed to this batch; each listed
        shard is charged the FULL ``device_ms`` (replicated program —
        every chip is busy for the whole batch wall time)."""
        geometry = self._geometry(src_hw)
        key = (model, geometry, bucket)
        cell = self._cells.get(key)
        if cell is None:
            cell = self._make_cell(key)
        padded = bucket - frames
        cell.device.observe(device_ms)
        if padded > 0:
            cell.padded.inc(padded)
        cell.slots.inc(bucket)
        if area_frac is not None:
            cell.occupancy.set(100.0 * area_frac)
        else:
            cell.occupancy.set(100.0 * frames / bucket if bucket else 0.0)
        if cell.ema_init:
            cell.ema_ms = 0.9 * cell.ema_ms + 0.1 * device_ms
        else:
            cell.ema_ms = device_ms
            cell.ema_init = True
        cell.frames += frames
        cell.padded_total += max(padded, 0)
        rec = self._compiles.get(key)
        flops = rec["flops"] if rec is not None else 0.0
        util = mfu_pct(flops, cell.ema_ms, self.peak_tflops)
        if util is not None:
            cell.mfu.set(util)
            cell.tflops.set(flops / (cell.ema_ms * 1e-3) / 1e12)
        if shard_frames:
            for shard, n in shard_frames.items():
                skey = (model, bucket, str(shard))
                scell = self._shard_cells.get(skey)
                if scell is None:
                    scell = self._make_shard_cell(skey)
                scell.frames_child.inc(int(n))
                scell.busy_child.inc(device_ms)
                scell.frames += int(n)
                scell.busy_ms += float(device_ms)
        now = self._clock()
        self._fps.add(streams if streams is not None else frames, now)
        self._m_fps.set(self._fps.rate(now))

    def note_h2d(self, model: str, bucket: int, nbytes: int,
                 seconds: float, *, hidden_s: float = 0.0) -> None:
        """Record one host->device batch placement: ``nbytes`` on the wire
        (the full padded uint8 batch plus aux tensors such as the int32
        thumbnail slot-index vector) taking ``seconds`` of transfer wall
        time. With the prefetch stage enabled this is a real async
        ``device_put`` timed on the dedicated transfer thread (copy start
        to ``block_until_ready``); ``hidden_s`` is the portion of that
        window which overlapped in-flight device compute or dispatch work
        on the tick thread — the evidence behind ``h2d_hidden_pct``.
        Without prefetch it degrades to the legacy synchronous placement
        timing with ``hidden_s`` = 0. Called once per dispatched batch,
        same fixed-allocation cell discipline as ``note_batch`` — the
        direct measurement behind ROADMAP item 5's bytes-per-frame gate."""
        key = (model, bucket)
        cell = self._h2d.get(key)
        if cell is None:
            cell = self._make_h2d_cell(key)
        cell.bytes_child.inc(nbytes)
        cell.seconds_child.inc(seconds)
        if hidden_s > 0.0:
            cell.hidden_child.inc(hidden_s)
            cell.hidden_s += float(hidden_s)
        cell.bytes += int(nbytes)
        cell.seconds += float(seconds)
        cell.batches += 1
        cell.slots += int(bucket)

    # -- ROI serving attribution (cfg.roi, engine/runner.py) --------------

    def note_roi_gate(self, idle: int, roi: int, full: int) -> None:
        """One tick's motion-gate split over detect streams."""
        if idle:
            self._m_roi_states.labels("idle").inc(idle)
        if roi:
            self._m_roi_states.labels("roi").inc(roi)
        if full:
            self._m_roi_states.labels("full").inc(full)
        with self._lock:
            self._roi["idle"] += idle
            self._roi["roi"] += roi
            self._roi["full"] += full

    def note_roi_pack(self, crops: int, canvases: int,
                      area_frac: float) -> None:
        """One packed canvas batch leaving the packer."""
        self._m_roi_crops.inc(crops)
        self._m_roi_canvases.inc(canvases)
        self._m_roi_occupancy.set(100.0 * area_frac)
        with self._lock:
            self._roi["crops"] += crops
            self._roi["canvases"] += canvases
            self._roi["area_frac"] = area_frac

    def note_roi_emit(self, streams: int) -> None:
        """Per-stream results served through the ROI plane (coasted,
        packed, or full-frame-while-gating) — the full-frame-equivalent
        fps evidence (ISSUE 9 acceptance)."""
        now = self._clock()
        self._roi_fps.add(streams, now)
        self._m_roi_fps.set(self._roi_fps.rate(now))

    def note_roi_unrouted(self, n: int = 1) -> None:
        self._m_roi_unrouted.inc(n)
        with self._lock:
            self._roi["unrouted"] += n

    def roi_equivalent_fps(self) -> float:
        return self._roi_fps.rate(self._clock())

    # -- temporal cascade attribution (cfg.cascade, temporal/) -------------

    def note_cascade_tick(self) -> None:
        """One engine tick seen by the cascade scheduler (fires whether
        or not this tick is a head-cadence tick)."""
        self._m_cascade_ticks.inc()
        with self._lock:
            self._cascade["ticks"] += 1
            self._set_cascade_cadence_locked()

    def note_cascade_head(self, slots: int) -> None:
        """One temporal-head batch dispatched with ``slots`` live track
        slots (device time/H2D ride note_batch/note_h2d under the
        ``cascade/<model>`` key, same as every other program)."""
        self._m_cascade_head.inc()
        with self._lock:
            self._cascade["head_batches"] += 1
            self._cascade["head_slots"] += int(slots)
            self._set_cascade_cadence_locked()

    def note_cascade_event(self, kind: str) -> None:
        """One hysteresis transition ("enter"/"exit") fired for a track."""
        self._m_cascade_events.labels(kind).inc()
        with self._lock:
            ev = self._cascade["events"]
            ev[kind] = ev.get(kind, 0) + 1

    def note_cascade_slots(self, in_use: int, high_water: int) -> None:
        """State-pool occupancy after a cascade tick (slot-conservation
        evidence: in_use tracks live tracks, high_water stays bounded
        across churn)."""
        self._m_cascade_tracks.set(float(in_use))
        with self._lock:
            self._cascade["tracks"] = int(in_use)
            self._cascade["high_water"] = max(
                self._cascade["high_water"], int(high_water))

    def _set_cascade_cadence_locked(self) -> None:
        c = self._cascade
        if c["head_batches"]:
            self._m_cascade_cadence.set(c["ticks"] / c["head_batches"])

    def _make_h2d_cell(self, key: Tuple[str, int]) -> _H2DCell:
        model, bucket = key
        b = str(bucket)
        cell = _H2DCell(
            bytes_child=self._m_h2d_bytes.labels(model, b),
            seconds_child=self._m_h2d_seconds.labels(model, b),
            hidden_child=self._m_h2d_hidden.labels(model, b),
        )
        with self._lock:
            return self._h2d.setdefault(key, cell)

    def _make_shard_cell(self, key: Tuple[str, int, str]) -> _ShardCell:
        model, bucket, shard = key
        cell = _ShardCell(
            frames_child=self._m_shard_frames.labels(
                model, str(bucket), shard),
            busy_child=self._m_shard_busy.labels(model, str(bucket), shard),
        )
        with self._lock:
            return self._shard_cells.setdefault(key, cell)

    def _make_cell(self, key: Tuple[str, str, int]) -> _BatchCell:
        model, _geometry, bucket = key
        b = str(bucket)
        cell = _BatchCell(
            device=self._m_device.labels(model, b),
            padded=self._m_padded.labels(model, b),
            slots=self._m_slots.labels(model, b),
            occupancy=self._m_occupancy.labels(model, b),
            mfu=self._m_mfu.labels(model, b),
            tflops=self._m_tflops.labels(model, b),
        )
        with self._lock:
            return self._cells.setdefault(key, cell)

    def fps(self) -> float:
        """Aggregate emitted frames/second over the sliding window."""
        return self._fps.rate(self._clock())

    # -- snapshots --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able attribution summary for /api/v1/stats and the soak
        artifact's "perf" section."""
        with self._lock:
            compiles = [dict(rec) for rec in self._compiles.values()]
            buckets = []
            for (model, geometry, bucket), cell in sorted(
                    self._cells.items()):
                rec = self._compiles.get((model, geometry, bucket))
                flops = rec["flops"] if rec is not None else 0.0
                util = mfu_pct(flops, cell.ema_ms, self.peak_tflops)
                slots = cell.frames + cell.padded_total
                buckets.append({
                    "model": model, "geometry": geometry, "bucket": bucket,
                    "device_ms_ema": round(cell.ema_ms, 3),
                    "frames": cell.frames,
                    "padded_slots": cell.padded_total,
                    "padded_pct": round(100.0 * cell.padded_total / slots,
                                        2) if slots else 0.0,
                    "mfu_pct": round(util, 3) if util is not None else None,
                })
            shards = [
                {"model": model, "bucket": bucket, "shard": shard,
                 "frames": scell.frames,
                 "busy_ms": round(scell.busy_ms, 3)}
                for (model, bucket, shard), scell in sorted(
                    self._shard_cells.items())
            ]
            h2d = []
            h2d_seconds = 0.0
            h2d_hidden = 0.0
            for (model, bucket), cell in sorted(self._h2d.items()):
                h2d_seconds += cell.seconds
                h2d_hidden += cell.hidden_s
                h2d.append({
                    "model": model, "bucket": bucket,
                    "bytes": cell.bytes,
                    "seconds": round(cell.seconds, 6),
                    "hidden_seconds": round(cell.hidden_s, 6),
                    "hidden_pct": (round(100.0 * cell.hidden_s
                                         / cell.seconds, 1)
                                   if cell.seconds > 0 else None),
                    "batches": cell.batches,
                    "bytes_per_frame": (cell.bytes // cell.slots
                                        if cell.slots else None),
                    "mbps": (round(cell.bytes / 1e6 / cell.seconds, 1)
                             if cell.seconds > 0 else None),
                })
        out = {
            "peak_tflops": self.peak_tflops,
            "fps": round(self.fps(), 1),
            "compiles": sorted(
                compiles, key=lambda r: (r["model"], r["geometry"],
                                         r["bucket"])),
            "buckets": buckets,
            "h2d": h2d,
            "h2d_hidden_pct": (round(100.0 * h2d_hidden / h2d_seconds, 1)
                               if h2d_seconds > 0 else None),
        }
        if shards:
            out["shards"] = shards
        with self._lock:
            roi = dict(self._roi)
        gated = roi["idle"] + roi["roi"] + roi["full"]
        if gated or roi["canvases"]:
            out["roi"] = {
                "stream_ticks": {"idle": roi["idle"], "roi": roi["roi"],
                                 "full": roi["full"]},
                "gated_stream_pct": round(
                    100.0 * (roi["idle"] + roi["roi"]) / gated, 1)
                if gated else 0.0,
                "crops": roi["crops"],
                "canvases": roi["canvases"],
                "crops_per_canvas": round(
                    roi["crops"] / roi["canvases"], 2)
                if roi["canvases"] else None,
                "canvas_occupancy_pct": round(
                    100.0 * roi["area_frac"], 1)
                if roi["area_frac"] is not None else None,
                "unrouted": roi["unrouted"],
                "equivalent_fps": round(self.roi_equivalent_fps(), 1),
            }
        with self._lock:
            casc = dict(self._cascade)
            casc["events"] = dict(casc["events"])
        if casc["ticks"] or casc["head_batches"]:
            out["cascade"] = {
                "ticks": casc["ticks"],
                "head_batches": casc["head_batches"],
                "head_cadence": round(
                    casc["ticks"] / casc["head_batches"], 2)
                if casc["head_batches"] else None,
                "slots_per_head": round(
                    casc["head_slots"] / casc["head_batches"], 2)
                if casc["head_batches"] else None,
                "events": casc["events"],
                "tracks": casc["tracks"],
                "slot_high_water": casc["high_water"],
            }
        return out
