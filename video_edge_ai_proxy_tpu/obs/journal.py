"""Control-plane decision journal: causally-linked audit events.

ISSUE r23. Six autonomous control loops act on the serving path
(degradation ladder, ROI/cascade gating, headroom admission r18,
supervisor spawn/retire r19, memory-aware placement r21, fault
failover r22) and each only exposes its own snapshot — answering "why
is stream X degraded" means correlating six ``/api/v1/*`` surfaces by
hand. The reference proxy has no audit trail at all (its process
supervisor restarts containers silently — processes.go:318 just logs
and respawns); per-decision accounting as a first-class output follows
the many-camera monitor economics of MultiStream (arxiv 2207.06078)
and the end-to-end benchmarking practice of arxiv 2307.16834.

Design (the ``obs/slo.py`` ring idiom, generalized):

- ``DecisionJournal`` is a process-wide bounded ring of structured
  **decision events**. The record path is zero-allocation in the ring
  itself: parallel slot lists preallocated at construction, one index
  write per field, no per-event object. Sequence numbers are monotone
  from 1 and never reused — they are the causal-link currency and the
  fleet-merge tiebreak.
- Every event carries ``actor`` (which loop), ``action`` (what it
  did), ``subject`` (``(kind, id)`` — stream/member/tenant/shard/slo),
  the quantitative ``trigger`` (the numbers that forced the action,
  e.g. ``{"time_to_saturation_s": 42}``), and ``cause`` — the seq of
  the event that provoked this one, forming causal chains:
  SLO burn → ladder rung → cascade cadence stretch.
- ``why(kind, id)`` finds the subject's newest event and walks cause
  links backward into a human-readable chain. Eviction re-roots
  chains instead of dangling them: a cause seq older than the oldest
  retained slot renders as an ``(evicted)`` root marker, never a
  KeyError.
- ``latest_seq(...)`` is the cause-resolution helper for decision
  sites: a bounded backward scan at decision frequency (ladder
  transitions, spawns, migrations) — never on the per-frame path.
- Events are edge-triggered by convention: actors journal state
  CHANGES (rung transition, episode open/close, spawn, migrate), never
  per-tick observations, so a 4096-slot ring holds hours of history.

Pure Python, stdlib + ``obs.metrics`` only — importable from
control-plane code without initializing a backend, exactly like
``watch.py``. Journal off (``EngineConfig.journal=False``) ⇒ every
hook holds ``journal=None`` ⇒ bit-identical replay (pinned by
tests/test_journal.py against the r22 fault-off checksum).

Metric families:

- ``vep_journal_events_total{actor,action}`` — recorded events
- ``vep_journal_evictions_total`` — ring-overflow overwrites
- ``vep_journal_retained`` — events currently held (≤ capacity)
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

Subject = Tuple[str, str]


def format_event(ev: dict) -> str:
    """One human-readable line for a journal event dict — the ``why()``
    chain rendering: ``[seq] actor.action subject (k=v, ...)``."""
    parts = [f"[{ev['seq']}]", f"{ev['actor']}.{ev['action']}"]
    if ev.get("subject"):
        kind, ident = ev["subject"]
        parts.append(f"{kind}={ident}")
    trig = ev.get("trigger")
    if trig:
        kv = []
        for k in sorted(trig):
            v = trig[k]
            kv.append(f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}")
        parts.append("(" + ", ".join(kv) + ")")
    return " ".join(parts)


class DecisionJournal:
    """Bounded, causally-linked journal of control-plane decisions.

    ``capacity`` slots; ``clock`` injectable (defaults to wall time —
    fleet merge orders events across processes, so monotonic clocks
    from different members would not compare)."""

    def __init__(self, capacity: int = 4096, *, clock=time.time,
                 registry=None):
        if registry is None:
            from .metrics import registry as _registry
            registry = _registry
        self._cap = max(16, int(capacity))
        self._clock = clock
        self._lock = threading.Lock()
        # Parallel slot lists — the ring. record() writes by index;
        # nothing is appended or popped after construction.
        n = self._cap
        self._s_ts: List[float] = [0.0] * n
        self._s_actor: List[str] = [""] * n
        self._s_action: List[str] = [""] * n
        self._s_subj: List[Optional[Subject]] = [None] * n
        self._s_trigger: List[Optional[dict]] = [None] * n
        self._s_cause: List[Optional[int]] = [None] * n
        self._next_seq = 1           # seqs are 1-based, monotone, unique
        self._c_events = registry.counter(
            "vep_journal_events_total",
            "Control-plane decision events recorded",
            ("actor", "action"))
        self._c_evicted = registry.counter(
            "vep_journal_evictions_total",
            "Journal ring overwrites (oldest event evicted)")
        self._g_retained = registry.gauge(
            "vep_journal_retained",
            "Decision events currently retained in the ring")

    # -- record path ---------------------------------------------------------

    def record(self, actor: str, action: str, *,
               subject: Optional[Subject] = None,
               trigger: Optional[dict] = None,
               cause: Optional[int] = None) -> int:
        """Append one decision event; returns its seq (the handle
        callers thread into later ``cause=`` links). Called at decision
        frequency — rung transitions, spawns, migrations — never
        per-frame."""
        with self._lock:
            seq = self._next_seq
            idx = (seq - 1) % self._cap
            self._s_ts[idx] = self._clock()
            self._s_actor[idx] = actor
            self._s_action[idx] = action
            self._s_subj[idx] = subject
            self._s_trigger[idx] = trigger
            self._s_cause[idx] = cause
            self._next_seq = seq + 1
            evicted = seq > self._cap
        self._c_events.labels(actor, action).inc()
        if evicted:
            self._c_evicted.inc()
        else:
            self._g_retained.set(float(seq))
        return seq

    # -- reads ---------------------------------------------------------------

    @property
    def next_seq(self) -> int:
        with self._lock:
            return self._next_seq

    def _oldest_locked(self) -> int:
        """Oldest retained seq (1 until the ring first wraps)."""
        return max(1, self._next_seq - self._cap)

    def _event_locked(self, seq: int) -> Optional[dict]:
        if not (self._oldest_locked() <= seq < self._next_seq):
            return None
        idx = (seq - 1) % self._cap
        return {
            "seq": seq,
            "ts": self._s_ts[idx],
            "actor": self._s_actor[idx],
            "action": self._s_action[idx],
            "subject": self._s_subj[idx],
            "trigger": self._s_trigger[idx],
            "cause": self._s_cause[idx],
        }

    def event(self, seq: int) -> Optional[dict]:
        """The event for ``seq``, or None when unknown or evicted."""
        with self._lock:
            return self._event_locked(seq)

    def events(self, *, subject: Optional[Subject] = None,
               subject_kind: Optional[str] = None,
               actor: Optional[str] = None,
               action: Optional[str] = None,
               since: Optional[int] = None,
               limit: Optional[int] = None) -> List[dict]:
        """Retained events oldest→newest, filtered. ``since`` is a seq
        (exclusive — the REST cursor idiom: pass the last seq you saw);
        ``limit`` keeps the newest N after filtering."""
        with self._lock:
            lo = self._oldest_locked()
            if since is not None:
                lo = max(lo, int(since) + 1)
            out = []
            for seq in range(lo, self._next_seq):
                ev = self._event_locked(seq)
                if ev is None:
                    continue
                if actor is not None and ev["actor"] != actor:
                    continue
                if action is not None and ev["action"] != action:
                    continue
                if subject is not None and ev["subject"] != tuple(subject):
                    continue
                if subject_kind is not None and (
                        ev["subject"] is None
                        or ev["subject"][0] != subject_kind):
                    continue
                out.append(ev)
        if limit is not None and limit > 0:
            out = out[-int(limit):]
        return out

    def window(self, t0: float, t1: float) -> List[dict]:
        """Events with ``t0 <= ts <= t1`` — the prof-bundle overlap
        embed (obs/prof.py writes the journal window next to spans)."""
        with self._lock:
            return [ev for seq in range(self._oldest_locked(),
                                        self._next_seq)
                    for ev in (self._event_locked(seq),)
                    if ev is not None and t0 <= ev["ts"] <= t1]

    def latest_seq(self, *, actor: Optional[str] = None,
                   action: Optional[str] = None,
                   subject: Optional[Subject] = None) -> Optional[int]:
        """Newest retained seq matching the filters (backward scan) —
        the cause-resolution helper decision sites call to link their
        action to the observation that provoked it."""
        with self._lock:
            for seq in range(self._next_seq - 1,
                             self._oldest_locked() - 1, -1):
                ev = self._event_locked(seq)
                if ev is None:
                    continue
                if actor is not None and ev["actor"] != actor:
                    continue
                if action is not None and ev["action"] != action:
                    continue
                if subject is not None and ev["subject"] != tuple(subject):
                    continue
                return seq
        return None

    # -- why() ---------------------------------------------------------------

    def why(self, kind: str, ident: str, *, max_links: int = 8) -> dict:
        """The causal chain behind a subject's current state: find the
        subject's newest event, walk ``cause`` links backward, return
        root-first with human-readable lines. An evicted cause becomes
        a re-rooted ``(evicted)`` marker — chains never dangle."""
        subject = (str(kind), str(ident))
        chain: List[dict] = []
        evicted_root = False
        with self._lock:
            cur: Optional[int] = None
            for seq in range(self._next_seq - 1,
                             self._oldest_locked() - 1, -1):
                ev = self._event_locked(seq)
                if ev is not None and ev["subject"] == subject:
                    cur = seq
                    break
            while cur is not None and len(chain) < max_links:
                ev = self._event_locked(cur)
                if ev is None:          # cause fell off the ring
                    evicted_root = True
                    break
                chain.append(ev)
                cur = ev["cause"]
        chain.reverse()
        text = [format_event(ev) for ev in chain]
        if evicted_root:
            text.insert(0, "(root evicted from journal ring)")
        return {
            "subject": {"kind": subject[0], "id": subject[1]},
            "found": bool(chain),
            "links": len(chain),
            "evicted_root": evicted_root,
            "chain": chain,
            "text": text,
        }

    # -- snapshot ------------------------------------------------------------

    def snapshot(self, *, tail: int = 0) -> dict:
        """JSON-able accounting for ``stats()["obs"]["journal"]`` and
        artifacts: counts per actor/action plus (opt-in) the newest
        ``tail`` events."""
        with self._lock:
            oldest = self._oldest_locked()
            by_actor: Dict[str, int] = {}
            by_action: Dict[str, int] = {}
            for seq in range(oldest, self._next_seq):
                idx = (seq - 1) % self._cap
                actor = self._s_actor[idx]
                by_actor[actor] = by_actor.get(actor, 0) + 1
                key = f"{actor}.{self._s_action[idx]}"
                by_action[key] = by_action.get(key, 0) + 1
            out = {
                "capacity": self._cap,
                "next_seq": self._next_seq,
                "oldest_seq": oldest,
                "recorded": self._next_seq - 1,
                "retained": self._next_seq - oldest,
                "evicted": max(0, self._next_seq - 1 - self._cap),
                "by_actor": by_actor,
                "by_action": by_action,
            }
            if tail > 0:
                out["tail"] = [
                    self._event_locked(seq)
                    for seq in range(max(oldest, self._next_seq - tail),
                                     self._next_seq)]
        return out


def merge_journals(members: Dict[str, List[dict]]) -> List[dict]:
    """Deterministic fleet merge (the r14 stitching idiom): events from
    ``{member_name: [event dicts]}`` tagged with their member and
    ordered by ``(ts, member, seq)`` — per-member seqs are monotone, so
    ties on wall time collapse to a stable member+seq order and the
    merge is identical regardless of scrape arrival order."""
    out: List[dict] = []
    for name, events in members.items():
        for ev in events or []:
            tagged = dict(ev)
            tagged["member"] = name
            out.append(tagged)
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("member", ""),
                            e.get("seq", 0)))
    return out
