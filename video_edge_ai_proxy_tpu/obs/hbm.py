"""HBM attribution plane: per-program and per-pool device-memory ledger
with OOM forecasting.

The memory mirror of :mod:`.capacity` (r18 made device TIME a conserved,
forecastable resource; this round does the same for device BYTES —
ISSUE 18, the byte-budget prerequisite for ROADMAP item 3's
device-resident KV/state caches and item 5's per-tenant economics).
No reference counterpart: the reference proxy keeps no device state at
all (frames live in per-camera shm rings, ``rtsp_to_rtmp.py:144-145``);
a fused TPU serving plane accumulates compiled-program footprints,
grow-by-8 clip rings, thumb pools, prefetch double-buffers and canvas
buffers that nothing accounted for until now — the fleet could forecast
running out of time but not running out of HBM.

Three tiers, one object (``HbmTracker``, engine-owned like
``CapacityTracker``):

- **Static program footprints.** Captured once per compiled program at
  the engine's single step-cache-miss site (the same ``_TimedStep``
  success path obs/perf.py taps for compile time + FLOPs):
  ``compiled.memory_analysis()`` argument/output/temp/generated-code
  bytes per ``(model, stem, geometry, bucket, mesh)`` program, with
  donated-argument aliasing credited (``alias_bytes``) so
  ``donate_frames`` shows up as saved bytes. Programs execute serially,
  so the resident model is Σ code bytes (executables persist) plus the
  MAX single-program workspace (argument+output+temp−alias), not the
  sum of every workspace.
- **Dynamic pool accounting.** A ``register_pool(name, nbytes_fn)``
  protocol: each device-resident pool (thumb pools, track-state clip
  rings, prefetch slots, collector host batch buffers) registers a
  zero-argument callable returning its CURRENT bytes — an int, or a
  ``{shard: int}`` mapping for per-chip pools under ``engine.mesh``.
  Reading the pool's own ``.nbytes`` at call time makes the exactness
  invariant (tracked bytes == Σ constituent ``.nbytes``) hold by
  construction; tools/hbm_smoke.py and the dp=2 test pin it anyway.
  Re-registering a name replaces the callable (the engine's sharded
  warmup swaps stay tracked with no unregister dance).
- **Budget + forecast.** Device capacity from ``device.memory_stats()``
  on the real TPU (the engine resolves it at warmup and calls
  :meth:`set_budget`) with a configurable synthetic budget on the CPU
  twin. ``evaluate`` (throttled, engine-tick driven) samples used =
  pools + code + peak workspace, EWMA-smooths the utilization slope and
  extrapolates ``time_to_oom_s`` in the exact r18 forecast shape; burn
  rates follow the SRE fast/slow recipe over window PEAKS (memory is a
  level, not a rate — the windows carry high-water marks). The
  aggregate ``pressure()`` verdict (burning, or OOM forecast inside
  ``pressure_horizon_s``) feeds the resilience ladder so the engine
  sheds/stretches BEFORE the allocator fails.

Metric families (gauges unless noted):

- ``vep_hbm_budget_bytes`` / ``vep_hbm_used_bytes`` — the budget model
- ``vep_hbm_pool_bytes{pool}`` — per registered pool, live
- ``vep_hbm_program_code_bytes`` / ``vep_hbm_program_workspace_bytes``
  — resident executables + the single largest program workspace
- ``vep_hbm_donated_saved_bytes`` — donated-argument aliasing credit
- ``vep_hbm_programs_total`` (counter) — programs footprinted
- ``vep_hbm_utilization{window}`` — window-peak used over budget
- ``vep_hbm_burn_rate{window}`` — utilization over the sustainable
  objective (>1 = trending to OOM faster than sustainable)
- ``vep_hbm_headroom_bytes`` — budget minus used
- ``vep_hbm_time_to_oom_seconds`` — EWMA-slope forecast (-1 = not
  trending toward OOM)

jax-free by design (CLAUDE.md): importable from control-plane code; the
``nbytes_fn`` callables touch device arrays' ``.nbytes`` metadata only,
never their contents — no transfer, no sync.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Union

from . import metrics

# CPU-twin fallback budget when the engine resolves no real device
# budget (device.memory_stats() absent) and the config pins none: big
# enough that the tiny twins never read as pressured, small enough that
# a runaway pool still trips the forecast in soaks.
DEFAULT_SYNTHETIC_BUDGET_BYTES = 4 << 30

PoolBytes = Union[int, Dict[str, int]]


class _PeakRing:
    """Per-bin HIGH-WATER marks over the slow window (the
    obs/capacity.py ``_BusyRing`` idiom with max instead of sum):
    memory is a level, not a rate, so a window total is meaningless —
    the window's peak is what OOM cares about. O(1) record, O(n_bins)
    peak scan at evaluate time."""

    __slots__ = ("_bin_s", "_n", "_peak", "_epochs")

    def __init__(self, span_s: float, bin_s: float):
        self._bin_s = float(bin_s)
        self._n = max(int(math.ceil(span_s / bin_s)) + 1, 2)
        self._peak = [0.0] * self._n
        self._epochs = [-1] * self._n

    def record(self, value: float, now: float) -> None:
        epoch = int(now // self._bin_s)
        i = epoch % self._n
        if self._epochs[i] != epoch:
            self._epochs[i] = epoch
            self._peak[i] = 0.0
        if value > self._peak[i]:
            self._peak[i] = value

    def peak(self, window_s: float, now: float) -> float:
        """Max recorded value across bins younger than ``window_s``."""
        lo_epoch = int((now - window_s) // self._bin_s)
        now_epoch = int(now // self._bin_s)
        peak = 0.0
        for i in range(self._n):
            e = self._epochs[i]
            if lo_epoch < e <= now_epoch and self._peak[i] > peak:
                peak = self._peak[i]
        return peak


class _Program:
    """One compiled program's memory footprint (bytes, from
    ``compiled.memory_analysis()`` via obs/perf.py memory_summary)."""

    __slots__ = ("argument", "output", "temp", "code", "alias", "count")

    def __init__(self, summary: Dict[str, int]):
        self.argument = int(summary.get("argument_bytes", 0))
        self.output = int(summary.get("output_bytes", 0))
        self.temp = int(summary.get("temp_bytes", 0))
        self.code = int(summary.get("code_bytes", 0))
        self.alias = int(summary.get("alias_bytes", 0))
        self.count = 1      # recompiles of the same key overwrite

    @property
    def workspace(self) -> int:
        """Live bytes while THIS program executes: arguments + outputs
        + XLA temp, minus donated-argument aliasing (a donated input
        plane is the output's storage — the credit that makes
        ``donate_frames`` visible as saved bytes)."""
        return max(0, self.argument + self.output + self.temp - self.alias)


class HbmTracker:
    """Engine-owned HBM plane: program footprints + pool ledger +
    budget forecast.

    ``note_program`` is the compile-site tap (drain thread, once per
    step-cache miss); ``register_pool`` arms the dynamic ledger;
    ``evaluate`` is the forecast step (tick thread, throttled to
    ``eval_interval_s``); ``snapshot`` is the read surface. The clock is
    injectable so ramp/forecast math tests run sleep-free.
    """

    def __init__(self, *, budget_bytes: int = 0,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 1800.0,
                 bin_s: float = 1.0,
                 util_objective: float = 0.9,
                 slope_alpha: float = 0.3,
                 eval_interval_s: float = 1.0,
                 pressure_horizon_s: float = 120.0,
                 clock=time.monotonic,
                 registry: Optional[metrics.Registry] = None):
        if not 0.0 < util_objective <= 1.0:
            raise ValueError(
                f"util_objective must be in (0, 1], got {util_objective}")
        if fast_window_s >= slow_window_s:
            raise ValueError(
                f"fast window ({fast_window_s}s) must be shorter than the "
                f"slow window ({slow_window_s}s)")
        if budget_bytes < 0:
            raise ValueError(f"budget_bytes must be >= 0, got {budget_bytes}")
        self.budget_bytes = (int(budget_bytes) if budget_bytes
                             else DEFAULT_SYNTHETIC_BUDGET_BYTES)
        #: True once set_budget() installed a device-reported budget
        #: (the snapshot distinguishes measured from synthetic).
        self.budget_measured = False
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.bin_s = float(bin_s)
        self.util_objective = float(util_objective)
        self.slope_alpha = float(slope_alpha)
        self.eval_interval_s = float(eval_interval_s)
        self.pressure_horizon_s = float(pressure_horizon_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[str, str, str, int, str], _Program] = {}
        self._pools: Dict[str, Callable[[], PoolBytes]] = {}
        self._ring = _PeakRing(slow_window_s, bin_s)
        # Forecast state (updated only in evaluate()).
        self._next_eval = 0.0
        self._prev_util: Optional[float] = None
        self._prev_eval_t: Optional[float] = None
        self._slope_ema: Optional[float] = None   # utilization / second
        self._last: dict = {
            "used_bytes": 0,
            "utilization": {"fast": 0.0, "slow": 0.0},
            "burn": {"fast": 0.0, "slow": 0.0},
            "burning": False,
            "headroom_bytes": self.budget_bytes,
            "slope_per_s": None,
            "time_to_oom_s": None,
            "pressure": False,
        }
        reg = registry if registry is not None else metrics.registry
        self._m_budget = reg.gauge(
            "vep_hbm_budget_bytes",
            "Device memory budget (measured via device.memory_stats() "
            "or the configured synthetic twin budget)").labels()
        self._m_used = reg.gauge(
            "vep_hbm_used_bytes",
            "Modeled resident bytes: pools + program code + peak single-"
            "program workspace").labels()
        self._m_pool = reg.gauge(
            "vep_hbm_pool_bytes",
            "Live bytes per registered device/host pool", ("pool",))
        self._m_code = reg.gauge(
            "vep_hbm_program_code_bytes",
            "Generated-code bytes summed over resident compiled programs"
        ).labels()
        self._m_workspace = reg.gauge(
            "vep_hbm_program_workspace_bytes",
            "Largest single-program execution workspace (arguments + "
            "outputs + temp - donated aliasing)").labels()
        self._m_saved = reg.gauge(
            "vep_hbm_donated_saved_bytes",
            "Bytes saved by donated-argument aliasing across resident "
            "programs (donate_frames evidence)").labels()
        self._m_programs = reg.counter(
            "vep_hbm_programs_total",
            "Compiled programs footprinted at the step-cache-miss site"
        ).labels()
        self._m_util = reg.gauge(
            "vep_hbm_utilization",
            "Window-peak used bytes over the budget", ("window",))
        self._m_burn = reg.gauge(
            "vep_hbm_burn_rate",
            "HBM burn multiple per window (utilization over the "
            "sustainable objective)", ("window",))
        self._m_headroom = reg.gauge(
            "vep_hbm_headroom_bytes",
            "Budget minus modeled used bytes").labels()
        self._m_tto = reg.gauge(
            "vep_hbm_time_to_oom_seconds",
            "EWMA-slope OOM forecast (-1 = not trending toward OOM)"
        ).labels()
        self._m_budget.set(self.budget_bytes)
        self._m_headroom.set(self.budget_bytes)
        self._m_tto.set(-1.0)

    # -- budget ----------------------------------------------------------

    def set_budget(self, budget_bytes: int, *, measured: bool = True) -> None:
        """Install the device-reported budget (engine warmup calls this
        with ``device.memory_stats()['bytes_limit']`` on the real TPU;
        the CPU twin keeps the configured/synthetic budget)."""
        if budget_bytes <= 0:
            return
        with self._lock:
            self.budget_bytes = int(budget_bytes)
            self.budget_measured = bool(measured)
        self._m_budget.set(self.budget_bytes)

    # -- static program footprints (drain thread, once per compile) ------

    def note_program(self, model: str, src_hw: Tuple[int, int], bucket: int,
                     summary: Dict[str, int], *, stem: str = "classic",
                     mesh: str = "") -> None:
        """Record one compiled program's ``memory_analysis()`` summary
        (obs/perf.py ``memory_summary`` dict) under its
        ``(model, stem, geometry, bucket, mesh)`` key. A recompile of
        the same key (engine restart of a bucket) overwrites — the model
        is RESIDENT programs, not compile history."""
        if not summary:
            return
        geometry = f"{src_hw[0]}x{src_hw[1]}"
        key = (str(model), str(stem), geometry, int(bucket), str(mesh))
        with self._lock:
            prev = self._programs.get(key)
            prog = _Program(summary)
            if prev is not None:
                prog.count = prev.count + 1
            self._programs[key] = prog
            code = sum(p.code for p in self._programs.values())
            workspace = max(
                (p.workspace for p in self._programs.values()), default=0)
            saved = sum(p.alias for p in self._programs.values())
        self._m_programs.inc()
        self._m_code.set(code)
        self._m_workspace.set(workspace)
        self._m_saved.set(saved)

    # -- dynamic pool ledger ---------------------------------------------

    def register_pool(self, name: str,
                      nbytes_fn: Callable[[], PoolBytes]) -> None:
        """Arm live byte accounting for one pool. ``nbytes_fn()`` returns
        the pool's CURRENT bytes — an int, or ``{shard: int}`` for
        per-chip pools under a dp mesh. Called at evaluate/snapshot time
        only (metadata reads; keep it cheap and lock-safe). Registering
        an existing name replaces the callable."""
        with self._lock:
            self._pools[str(name)] = nbytes_fn

    def pools(self) -> dict:
        """Live per-pool bytes: ``{"total": int, "pools": {name:
        {"bytes": int, "shards": {shard: int} | None}}}``. A pool whose
        callable raises reads as 0 bytes with ``"error"`` set — the
        forecast degrades, the tick loop never dies."""
        with self._lock:
            fns = list(self._pools.items())
        out: Dict[str, dict] = {}
        total = 0
        for name, fn in fns:
            row: dict = {"bytes": 0, "shards": None}
            try:
                val = fn()
            except Exception as exc:  # noqa: BLE001 — live tap must survive
                row["error"] = f"{type(exc).__name__}: {exc}"
                out[name] = row
                continue
            if isinstance(val, dict):
                shards = {str(k): int(v) for k, v in val.items()}
                row["shards"] = shards
                row["bytes"] = sum(shards.values())
            else:
                row["bytes"] = int(val)
            total += row["bytes"]
            out[name] = row
        return {"total": total, "pools": out}

    # -- forecast (tick thread, throttled) -------------------------------

    def _used(self) -> Tuple[int, dict, int, int, int]:
        """(used, pools, code, workspace, saved) — the budget model."""
        pools = self.pools()
        with self._lock:
            code = sum(p.code for p in self._programs.values())
            workspace = max(
                (p.workspace for p in self._programs.values()), default=0)
            saved = sum(p.alias for p in self._programs.values())
        used = pools["total"] + code + workspace
        return used, pools, code, workspace, saved

    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> dict:
        """Sample used bytes, update the forecast + burn state; throttled
        to ``eval_interval_s`` unless forced. Returns the live state dict
        (also retained for snapshot())."""
        now = self._clock() if now is None else now
        if not force and now < self._next_eval:
            return self._last
        self._next_eval = now + self.eval_interval_s
        used, pools, code, workspace, saved = self._used()
        budget = self.budget_bytes
        self._ring.record(float(used), now)
        u_now = used / budget if budget else 0.0
        u_fast = self._ring.peak(self.fast_window_s, now) / budget \
            if budget else 0.0
        u_slow = self._ring.peak(self.slow_window_s, now) / budget \
            if budget else 0.0
        # EWMA utilization slope (per second) on the INSTANT level — the
        # same forecast shape as obs/capacity.py: ramps register within
        # an eval interval, the EMA keeps one allocation burst from
        # whipsawing the OOM estimate.
        if self._prev_util is not None and self._prev_eval_t is not None \
                and now > self._prev_eval_t:
            slope = (u_now - self._prev_util) / (now - self._prev_eval_t)
            self._slope_ema = (
                slope if self._slope_ema is None
                else self.slope_alpha * slope
                + (1.0 - self.slope_alpha) * self._slope_ema)
        self._prev_util = u_now
        self._prev_eval_t = now
        headroom_frac = max(0.0, 1.0 - u_now)
        headroom_bytes = max(0, budget - used)
        tto: Optional[float] = None
        if self._slope_ema is not None and self._slope_ema > 1e-9:
            tto = headroom_frac / self._slope_ema
        burn_fast = u_fast / self.util_objective
        burn_slow = u_slow / self.util_objective
        burning = burn_fast > 1.0 and burn_slow > 1.0
        pressure = burning or (
            tto is not None and tto <= self.pressure_horizon_s)
        self._last = {
            "used_bytes": used,
            "utilization": {"fast": u_fast, "slow": u_slow},
            "burn": {"fast": burn_fast, "slow": burn_slow},
            "burning": burning,
            "headroom_bytes": headroom_bytes,
            "slope_per_s": self._slope_ema,
            "time_to_oom_s": tto,
            "pressure": pressure,
        }
        self._m_used.set(used)
        self._m_code.set(code)
        self._m_workspace.set(workspace)
        self._m_saved.set(saved)
        self._m_util.labels("fast").set(u_fast)
        self._m_util.labels("slow").set(u_slow)
        self._m_burn.labels("fast").set(burn_fast)
        self._m_burn.labels("slow").set(burn_slow)
        self._m_headroom.set(headroom_bytes)
        self._m_tto.set(tto if tto is not None else -1.0)
        for name, row in pools["pools"].items():
            self._m_pool.labels(name).set(row["bytes"])
        return self._last

    def pressure(self) -> bool:
        """The resilience ladder's aggregate verdict from the last
        evaluate: burning on both windows, or forecast to OOM inside
        ``pressure_horizon_s``. One dict read — the per-tick cost."""
        return bool(self._last["pressure"])

    # -- read surfaces ----------------------------------------------------

    def programs(self) -> Dict[str, dict]:
        """Per-program footprint rows (copies), keyed
        ``model|stem|geometry|bucket|mesh``."""
        with self._lock:
            return {
                "|".join((model, stem, geometry, str(bucket), mesh or "-")): {
                    "argument_bytes": p.argument,
                    "output_bytes": p.output,
                    "temp_bytes": p.temp,
                    "code_bytes": p.code,
                    "alias_bytes": p.alias,
                    "workspace_bytes": p.workspace,
                    "compiles": p.count,
                }
                for (model, stem, geometry, bucket, mesh), p
                in self._programs.items()
            }

    def snapshot(self) -> dict:
        """JSON-able HBM state for /api/v1/hbm, the /api/v1/stats obs
        embed, and the fleet scrape. Runs a (throttled) evaluate so a
        read-only consumer still sees a live forecast."""
        state = self.evaluate()
        used, pools, code, workspace, saved = self._used()
        return {
            "budget_bytes": self.budget_bytes,
            "budget_measured": self.budget_measured,
            "util_objective": self.util_objective,
            "windows_s": {"fast": self.fast_window_s,
                          "slow": self.slow_window_s},
            "used_bytes": used,
            "utilization": {k: round(v, 9)
                            for k, v in state["utilization"].items()},
            "burn": {k: round(v, 9) for k, v in state["burn"].items()},
            "burning": state["burning"],
            "headroom_bytes": state["headroom_bytes"],
            "slope_per_s": state["slope_per_s"],
            "time_to_oom_s": state["time_to_oom_s"],
            "pressure": state["pressure"],
            "program_code_bytes": code,
            "program_workspace_bytes": workspace,
            "donated_saved_bytes": saved,
            "programs": self.programs(),
            "pools": pools,
        }
