"""Declarative SLOs with multi-window burn-rate evaluation.

The BASELINE.md north-star numbers (aggregate >=1000 fps, <40 ms p50
detect latency) existed only as offline bench targets; the reference
proxy has nothing comparable (its stats loop, reference grpcapi.go:141,
counts frames and nothing else). This module makes them *live* service
objectives evaluated the way SRE burn-rate alerting does it (fast 5 m +
slow 1 h windows): an SLO fires only when BOTH windows burn error budget
faster than the threshold, and resolves as soon as the fast window
clears. That shape gives pages that are both fast (the 5 m window reacts
in minutes) and sticky-proof (the 1 h window suppresses blips), per the
multiwindow multi-burn-rate recipe.

Consumers (engine/runner.py): per-frame good/bad latency events, per-tick
fps + stream-availability events; ``SLOEngine.evaluate`` runs ~1/s off
the engine tick and its ``burning`` verdict feeds the resilience
``DegradationLadder`` as an extra pressure signal — sustained SLO burn
starts shedding *before* queues back up.

Design notes:

- **Fixed time-binned rings.** Each SLO keeps good/bad totals in
  ``slow_window_s / bin_s`` preallocated bins (default 360 for 1 h at
  10 s bins); ``record`` is index math on three flat lists — zero
  allocation, safe on the per-frame drain path (allocation-bound test in
  tests/test_obs.py).
- **Warmup guard.** No SLO may fire until ``warmup_s`` of wall time has
  been observed since its first event. Production-sane (no paging off
  sparse boot data) and it deliberately keeps short CPU test runs from
  ever firing the 1000 fps objective, which is unreachable off-chip.
- **Injectable clock.** Burn-rate math is tested under fake clocks
  (fast-burn fires, slow-burn holds, recovery closes the episode)
  without sleeping through real windows.

jax-free by design (CLAUDE.md): importable from the control plane.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, Optional

from . import metrics


@dataclass(frozen=True)
class SLOSpec:
    """Declarative objective: ``objective`` is the target good fraction
    (0.99 = 1% error budget); ``fire_burn_rate`` is the budget-burn
    multiple both windows must exceed to open an episode (14.4 = the
    standard 2%-of-monthly-budget-per-hour page threshold)."""

    name: str
    objective: float
    description: str = ""
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fire_burn_rate: float = 14.4
    warmup_s: float = 60.0
    bin_s: float = 10.0


class _BinRing:
    """Good/bad event totals in fixed time bins covering the slow window.

    Each bin is addressed by its absolute epoch (``now // bin_s``); a
    slot is reset lazily when a new epoch claims it, so recording is
    O(1) with no allocation and window totals are an O(n_bins) scan
    (n_bins ~ 360), done only at evaluate time.
    """

    __slots__ = ("_bin_s", "_n", "_good", "_bad", "_epochs")

    def __init__(self, span_s: float, bin_s: float):
        self._bin_s = float(bin_s)
        self._n = max(int(math.ceil(span_s / bin_s)) + 1, 2)
        self._good = [0.0] * self._n
        self._bad = [0.0] * self._n
        self._epochs = [-1] * self._n

    def record(self, good: float, bad: float, now: float) -> None:
        epoch = int(now // self._bin_s)
        i = epoch % self._n
        if self._epochs[i] != epoch:
            self._epochs[i] = epoch
            self._good[i] = 0.0
            self._bad[i] = 0.0
        self._good[i] += good
        self._bad[i] += bad

    def totals(self, window_s: float, now: float):
        """(good, bad) summed over bins younger than ``window_s``."""
        lo_epoch = int((now - window_s) // self._bin_s)
        now_epoch = int(now // self._bin_s)
        good = bad = 0.0
        for i in range(self._n):
            e = self._epochs[i]
            if lo_epoch < e <= now_epoch:
                good += self._good[i]
                bad += self._bad[i]
        return good, bad


class BurnRateSLO:
    """One objective: records good/bad events, evaluates multi-window
    burn, keeps episode state, and feeds ``vep_slo_*`` gauges."""

    def __init__(self, spec: SLOSpec, *, clock=time.monotonic,
                 registry: Optional[metrics.Registry] = None,
                 journal=None):
        if not 0.0 < spec.objective < 1.0:
            raise ValueError(
                f"SLO {spec.name!r}: objective must be in (0, 1), "
                f"got {spec.objective}")
        reg = registry if registry is not None else metrics.registry
        self.spec = spec
        self.budget = 1.0 - spec.objective
        self._clock = clock
        self._lock = threading.Lock()
        self._ring = _BinRing(spec.slow_window_s, spec.bin_s)
        self._t0: Optional[float] = None   # first recorded event
        self.firing = False
        self.episodes = 0
        self._last: dict = {"fast": None, "slow": None}
        # r23 decision journal: episode open/close events with the burn
        # numbers as trigger. last_open_seq is the cause handle the
        # ladder links its slo_burn-attributed transitions to.
        self.journal = journal
        self.last_open_seq: Optional[int] = None
        self._g_fast = reg.gauge(
            "vep_slo_burn_rate",
            "Error-budget burn-rate multiple per window",
            ("slo", "window")).labels(spec.name, "fast")
        self._g_slow = reg.gauge(
            "vep_slo_burn_rate", "", ("slo", "window")).labels(
                spec.name, "slow")
        self._g_firing = reg.gauge(
            "vep_slo_firing", "1 while the SLO burn episode is open",
            ("slo",)).labels(spec.name)
        self._c_episodes = reg.counter(
            "vep_slo_episodes_total", "Opened SLO burn episodes",
            ("slo",)).labels(spec.name)

    @property
    def name(self) -> str:
        return self.spec.name

    def record(self, good: float = 0.0, bad: float = 0.0) -> None:
        """Count events against the objective (hot path: index math)."""
        now = self._clock()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self._ring.record(good, bad, now)

    def burn_rate(self, window_s: float) -> Optional[float]:
        """Budget-burn multiple over the window: (bad fraction)/budget.
        None when the window holds no events."""
        now = self._clock()
        with self._lock:
            good, bad = self._ring.totals(window_s, now)
        total = good + bad
        if total <= 0.0:
            return None
        return (bad / total) / self.budget

    def evaluate(self, watchdog=None) -> dict:
        """Update episode state from both windows; returns the state
        dict served at /api/v1/slo."""
        spec = self.spec
        now = self._clock()
        fast = self.burn_rate(spec.fast_window_s)
        slow = self.burn_rate(spec.slow_window_s)
        with self._lock:
            covered = (self._t0 is not None
                       and now - self._t0 >= spec.warmup_s)
            burning = (covered and fast is not None and slow is not None
                       and fast > spec.fire_burn_rate
                       and slow > spec.fire_burn_rate)
            opened = closed = False
            if burning and not self.firing:
                self.firing = True
                self.episodes += 1
                self._c_episodes.inc()
                opened = True
            elif self.firing and (fast is None
                                  or fast <= spec.fire_burn_rate):
                # Fast window clearing resolves the episode: budget is no
                # longer burning *now*, even though the slow window still
                # remembers the excursion.
                self.firing = False
                closed = True
            self._last = {"fast": fast, "slow": slow}
        if self.journal is not None and opened:
            self.last_open_seq = self.journal.record(
                "slo", "episode_open", subject=("slo", spec.name),
                trigger={"fast": fast, "slow": slow,
                         "threshold": spec.fire_burn_rate})
        elif self.journal is not None and closed:
            self.journal.record(
                "slo", "episode_close", subject=("slo", spec.name),
                trigger={"fast": fast, "slow": slow,
                         "threshold": spec.fire_burn_rate,
                         "episodes": self.episodes},
                cause=self.last_open_seq)
        if fast is not None:
            self._g_fast.set(fast)
        if slow is not None:
            self._g_slow.set(slow)
        self._g_firing.set(1.0 if self.firing else 0.0)
        if watchdog is not None:
            # Once-per-episode operator log via the threshold watchdog;
            # keyed per SLO so concurrent burns log independently.
            watchdog.check(
                f"slo_burn:{spec.name}",
                fast if (covered and fast is not None) else 0.0,
                above=spec.fire_burn_rate,
                detail=(f"fast={fast} slow={slow} "
                        f"threshold={spec.fire_burn_rate}"))
        return self.state()

    def state(self) -> dict:
        with self._lock:
            return {
                "objective": self.spec.objective,
                "description": self.spec.description,
                "windows_s": {"fast": self.spec.fast_window_s,
                              "slow": self.spec.slow_window_s},
                "fire_burn_rate": self.spec.fire_burn_rate,
                "burn": dict(self._last),
                "firing": self.firing,
                "episodes": self.episodes,
            }


def default_slos(*, latency_ms: float = 40.0, target_fps: float = 1000.0,
                 warmup_s: float = 60.0) -> Iterable[SLOSpec]:
    """The three production objectives from BASELINE.md, as specs.

    The latency objective is a p50 expressed as burn rate: objective 0.5
    means at most half the detect frames may exceed ``latency_ms``; a
    burn multiple > 1.2 therefore reads "the p50 is above target".
    """
    return (
        SLOSpec(
            name="detect_latency_p50",
            objective=0.5,
            description=(f"p50 detect publish->emit latency < "
                         f"{latency_ms:g} ms"),
            fire_burn_rate=1.2,
            warmup_s=warmup_s,
        ),
        SLOSpec(
            name="aggregate_fps",
            objective=0.99,
            description=(f"aggregate emitted fps >= {target_fps:g} "
                         f"(per-tick samples)"),
            fire_burn_rate=14.4,
            warmup_s=warmup_s,
        ),
        SLOSpec(
            name="stream_availability",
            objective=0.99,
            description="inferred streams emitting within the "
                        "availability window (per-stream per-tick "
                        "samples)",
            fire_burn_rate=14.4,
            warmup_s=warmup_s,
        ),
    )


def integrity_slo(*, warmup_s: float = 60.0) -> SLOSpec:
    """The canary-integrity objective (obs/quality.py CanaryChecker):
    virtually every golden-replay cycle must reproduce the committed
    result checksum. Cycles are rare events (one per trace loop, a
    handful per fast window), so a single mismatch burns far above 1.0
    and fires as soon as the window is covered — integrity failures are
    binary, not budgeted like latency."""
    return SLOSpec(
        name="canary_integrity",
        objective=0.99,
        description="canary golden-replay cycles matching the committed "
                    "result checksum",
        fire_burn_rate=1.0,
        warmup_s=warmup_s,
    )


class SLOEngine:
    """A set of burn-rate SLOs with one evaluate/snapshot surface.

    Owned by the inference engine; ``evaluate`` runs off the engine tick
    (throttled there to ~1/s), pushes gauges + once-per-episode watchdog
    lines, and returns the aggregate ``burning`` verdict the degradation
    ladder consumes.
    """

    def __init__(self, specs: Iterable[SLOSpec] = (), *,
                 clock=time.monotonic,
                 registry: Optional[metrics.Registry] = None,
                 watchdog=None, journal=None):
        self._watchdog = watchdog
        self.journal = journal
        self._slos: Dict[str, BurnRateSLO] = {}
        for spec in specs:
            self.add(BurnRateSLO(spec, clock=clock, registry=registry,
                                 journal=journal))

    def add(self, slo: BurnRateSLO) -> BurnRateSLO:
        if slo.journal is None:
            slo.journal = self.journal
        self._slos[slo.name] = slo
        return slo

    def get(self, name: str) -> BurnRateSLO:
        return self._slos[name]

    def names(self):
        return sorted(self._slos)

    def record(self, name: str, *, good: float = 0.0,
               bad: float = 0.0) -> None:
        self._slos[name].record(good=good, bad=bad)

    def evaluate(self) -> dict:
        """Evaluate every SLO; {"burning": any-firing, "slos": {...}}."""
        states = {name: slo.evaluate(self._watchdog)
                  for name, slo in sorted(self._slos.items())}
        return {"burning": any(s["firing"] for s in states.values()),
                "slos": states}

    def burning(self) -> bool:
        """Aggregate verdict from the LAST evaluate (no re-evaluation:
        cheap enough for per-tick ladder reads)."""
        return any(slo.firing for slo in self._slos.values())

    def snapshot(self) -> dict:
        """JSON-able state for /api/v1/slo and the soak artifact."""
        return {"burning": self.burning(),
                "slos": {name: slo.state()
                         for name, slo in sorted(self._slos.items())}}
