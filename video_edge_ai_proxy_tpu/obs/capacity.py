"""Capacity attribution plane: per-stream device-time ledger, headroom
forecasting, and burn-rate accounting that feeds admission.

ROADMAP item 5's measurement prerequisite ("spawn/retire members from the
health ranking, bounded admission latency during storms"): every
autoscaling/placement decision presupposes a signal the other obs planes
never emit — *how much device time each stream actually costs and how
much headroom each member has left*. MultiStream (arxiv 2207.06078) and
the Jetson anomaly-pipeline study (arxiv 2307.16834) both show that
multi-camera edge boxes saturate abruptly unless per-stream cost is
attributed and forecast BEFORE the SLO burns; the r14/r16 fleet plane
(obs/fleet.py, serve/router.py) only ranks members AFTER they degrade.
This module closes that loop forward in time. No reference counterpart:
the reference proxy ships frames to external CPU clients and never
accounts device time at all (its stats loop counts frames,
``server/grpcapi/grpc_api.go:141``).

Three tiers, one object (``CapacityTracker``, engine-owned like
``SLOEngine``):

- **Per-stream device-time ledger.** Every bucketed megastep's measured
  device time (the same ``device_ms`` obs/perf.py attributes per cell)
  is amortized back to its occupant streams: full-frame streams split a
  bucket's cost equally by slot occupancy, ROI canvas streams by their
  packed canvas-area share (``CropPlacement.dst`` rects), cascade
  streams additionally carry their 1/N-cadence temporal-head dispatches
  (raw cost in the ledger, cadence-amortized per-tick EMA alongside).
  Conservation is an INVARIANT, not a hope: shares are computed as
  weight fractions of the measured time, the running attributed and
  measured totals are both exported, and ``conservation()`` verdicts
  them within float tolerance (tools/capacity_smoke.py hard-gates it).
  Rows idle past the slow window expire (r21) so the dict stays bounded
  under stream churn; the conservation counters run independently of
  the live dict, so expiry never unbalances them.
- **Headroom model + forecast.** Busy device-milliseconds accumulate in
  fixed time-binned rings (the obs/slo.py ``_BinRing`` idiom — zero
  allocation on the hot path), per (model, geometry, bucket) cell and
  aggregate. Utilization = busy wall share of the elapsed window;
  ``evaluate`` (throttled, engine-tick driven) EWMA-smooths the
  utilization slope and extrapolates ``time_to_saturation_s`` — the
  forward-looking signal ``StreamRouter.admit`` consumes. Burn rates
  follow the SRE multi-window recipe (fast 1 m / slow 30 m): burn =
  window utilization over the sustainable objective, burning only when
  BOTH windows exceed it (fast reacts, slow suppresses blips).
- **Surfaces.** ``vep_capacity_*`` metric families (below),
  ``snapshot()`` for ``/api/v1/capacity`` + the ``/api/v1/stats`` obs
  embed, and the fleet merge (obs/fleet.py folds member headroom /
  saturation forecasts into the ranked health view).

Metric families (gauges unless noted):

- ``vep_capacity_stream_device_ms_total{stream,kind}`` (counter) —
  attributed device time per stream, kind in full|roi|cascade
- ``vep_capacity_attributed_ms_total`` / ``vep_capacity_measured_ms_total``
  (counters) — the conservation invariant, dashboard-visible
- ``vep_capacity_utilization{window}`` — tick-budget utilization per
  burn window
- ``vep_capacity_burn_rate{window}`` — utilization over the sustainable
  objective (>1 = spending capacity faster than sustainable)
- ``vep_capacity_headroom`` — remaining utilization fraction in [0, 1]
- ``vep_capacity_time_to_saturation_seconds`` — EWMA-slope forecast
  (-1 = not trending toward saturation)
- ``vep_capacity_cell_utilization{model,geometry,bucket}`` — fast-window
  utilization per serving cell

jax-free by design (CLAUDE.md): importable from control-plane code; the
engine taps it from the drain thread (one lock + float math per batch).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics

# Streams a batch could not be attributed to (empty occupant list —
# defensive; the engine always knows its occupants) land here so the
# conservation invariant still holds.
OVERHEAD_STREAM = "_overhead"

# Conservation tolerance: attributed and measured totals are the same
# float sums reordered, so drift is bounded by accumulation rounding.
CONSERVATION_REL_TOL = 1e-6


class _BusyRing:
    """Busy-milliseconds totals in fixed time bins covering the slow
    window (the obs/slo.py ``_BinRing`` idiom, single series): each bin
    is addressed by its absolute epoch and reset lazily when a new epoch
    claims it, so recording is O(1) index math with no allocation and a
    window total is an O(n_bins) scan done only at evaluate time."""

    __slots__ = ("_bin_s", "_n", "_busy", "_epochs")

    def __init__(self, span_s: float, bin_s: float):
        self._bin_s = float(bin_s)
        self._n = max(int(math.ceil(span_s / bin_s)) + 1, 2)
        self._busy = [0.0] * self._n
        self._epochs = [-1] * self._n

    def record(self, busy_ms: float, now: float) -> None:
        epoch = int(now // self._bin_s)
        i = epoch % self._n
        if self._epochs[i] != epoch:
            self._epochs[i] = epoch
            self._busy[i] = 0.0
        self._busy[i] += busy_ms

    def total(self, window_s: float, now: float) -> float:
        """Busy ms summed over bins younger than ``window_s``."""
        lo_epoch = int((now - window_s) // self._bin_s)
        now_epoch = int(now // self._bin_s)
        busy = 0.0
        for i in range(self._n):
            e = self._epochs[i]
            if lo_epoch < e <= now_epoch:
                busy += self._busy[i]
        return busy


class _StreamLedger:
    """Running attribution for one stream (mutated under the tracker
    lock; snapshot() hands out copies)."""

    __slots__ = ("device_ms", "by_kind", "batches", "frames",
                 "ema_ms_per_frame", "amortized_ms", "last_seen")

    def __init__(self):
        self.device_ms = 0.0          # total attributed device time
        self.by_kind: Dict[str, float] = {}
        self.batches = 0
        self.frames = 0
        self.ema_ms_per_frame: Optional[float] = None
        # Cadence-amortized running cost: full/roi shares land 1:1;
        # cascade head shares land divided by their dispatch cadence, so
        # this reads as the stream's steady-state cost per engine tick.
        self.amortized_ms = 0.0
        # Last attribution touch (tracker clock); drives departed-stream
        # expiry once a stream has been idle past the slow window (r21 —
        # the ledger dict must not grow without bound under churn).
        self.last_seen = 0.0


class _Cell:
    """One (model, geometry, bucket) serving cell's utilization ring."""

    __slots__ = ("ring", "busy_ms", "batches", "last_util")

    def __init__(self, slow_window_s: float, bin_s: float):
        self.ring = _BusyRing(slow_window_s, bin_s)
        self.busy_ms = 0.0
        self.batches = 0
        self.last_util = 0.0


class CapacityTracker:
    """Engine-owned capacity plane: ledger + rings + forecast + burn.

    ``note_batch`` is the attribution tap (drain thread, per device
    batch); ``evaluate`` is the forecast step (tick thread, throttled to
    ``eval_interval_s``); ``snapshot`` is the read surface. The clock is
    injectable so ramp/forecast math tests run sleep-free.
    """

    def __init__(self, *, tick_ms: int = 10,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 1800.0,
                 bin_s: float = 1.0,
                 util_objective: float = 0.8,
                 slope_alpha: float = 0.3,
                 eval_interval_s: float = 1.0,
                 clock=time.monotonic,
                 registry: Optional[metrics.Registry] = None):
        if not 0.0 < util_objective <= 1.0:
            raise ValueError(
                f"util_objective must be in (0, 1], got {util_objective}")
        if fast_window_s >= slow_window_s:
            raise ValueError(
                f"fast window ({fast_window_s}s) must be shorter than the "
                f"slow window ({slow_window_s}s)")
        self.tick_ms = int(tick_ms)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.bin_s = float(bin_s)
        self.util_objective = float(util_objective)
        self.slope_alpha = float(slope_alpha)
        self.eval_interval_s = float(eval_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._t0: Optional[float] = None       # first attributed batch
        self._streams: Dict[str, _StreamLedger] = {}
        self._cells: Dict[Tuple[str, str, int], _Cell] = {}
        # Mesh-native serving (ISSUE 17): per-shard conservation ledgers.
        # Data-parallel replication means every chip is busy for the full
        # batch wall time, so a batch adds device_ms to EACH contributing
        # shard's measured AND attributed totals — per-shard drift is
        # 0.0 by construction, same as the aggregate.
        self._shards: Dict[str, Dict[str, float]] = {}
        self._agg = _BusyRing(slow_window_s, bin_s)
        # Conservation invariant state. The running totals are COUNTERS,
        # independent of the per-stream dict: expiring an idle stream's
        # ledger row (below) never unbalances them.
        self.attributed_ms = 0.0
        self.measured_ms = 0.0
        self.max_conservation_rel_err = 0.0
        # Departed-stream expiry (r21): rows idle past the slow window
        # are dropped from the live dict; their attributed totals are
        # folded into these aggregates so snapshot coverage stays whole.
        self.expired_streams = 0
        self.expired_ms = 0.0
        # Forecast state (updated only in evaluate()).
        self._next_eval = 0.0
        self._prev_util: Optional[float] = None
        self._prev_eval_t: Optional[float] = None
        self._slope_ema: Optional[float] = None   # utilization / second
        self._last: dict = {
            "utilization": {"fast": 0.0, "slow": 0.0},
            "burn": {"fast": 0.0, "slow": 0.0},
            "burning": False,
            "headroom": 1.0,
            "slope_per_s": None,
            "time_to_saturation_s": None,
        }
        reg = registry if registry is not None else metrics.registry
        self._m_stream_ms = reg.counter(
            "vep_capacity_stream_device_ms_total",
            "Attributed device time per stream (ms)", ("stream", "kind"))
        self._m_attr = reg.counter(
            "vep_capacity_attributed_ms_total",
            "Device time attributed to streams (conservation numerator)"
        ).labels()
        self._m_meas = reg.counter(
            "vep_capacity_measured_ms_total",
            "Device time measured per batch (conservation denominator)"
        ).labels()
        self._m_shard_attr = reg.counter(
            "vep_capacity_shard_attributed_ms_total",
            "Device time attributed per dp mesh shard (ms)", ("shard",))
        self._m_shard_meas = reg.counter(
            "vep_capacity_shard_measured_ms_total",
            "Device time measured per dp mesh shard (ms; replicated "
            "program — each chip busy the full batch)", ("shard",))
        self._m_util = reg.gauge(
            "vep_capacity_utilization",
            "Tick-budget utilization per burn window", ("window",))
        self._m_burn = reg.gauge(
            "vep_capacity_burn_rate",
            "Capacity burn multiple per window (utilization over the "
            "sustainable objective)", ("window",))
        self._m_headroom = reg.gauge(
            "vep_capacity_headroom",
            "Remaining utilization headroom in [0,1]").labels()
        self._m_tts = reg.gauge(
            "vep_capacity_time_to_saturation_seconds",
            "EWMA-slope saturation forecast (-1 = not saturating)"
        ).labels()
        self._m_cell_util = reg.gauge(
            "vep_capacity_cell_utilization",
            "Fast-window utilization per serving cell",
            ("model", "geometry", "bucket"))

    # -- attribution tap (drain thread) ---------------------------------

    def note_batch(self, model: str, src_hw: Tuple[int, int], bucket: int,
                   device_ms: float, streams: Sequence[str], *,
                   weights: Optional[Sequence[float]] = None,
                   kind: str = "full", amortize_n: int = 1,
                   shard_streams: Optional[Dict[str, Sequence[str]]] = None,
                   now: Optional[float] = None) -> None:
        """Attribute one measured device batch back to its occupant
        streams.

        ``streams``: the occupant stream ids (full-frame: one per real
        slot; ROI canvas: the distinct source streams). ``weights``:
        optional per-stream cost weights (ROI canvas-area shares);
        omitted = equal split. ``amortize_n``: dispatch cadence in ticks
        (cascade head = cfg.cascade_every_n) — raw cost lands in the
        ledger, cost/amortize_n in the steady-state per-tick figure.
        Conservation is exact BY CONSTRUCTION: the float residual of the
        share split is folded into the last share, so the attributed and
        measured running totals advance by the identical float — drift
        reads 0.0, not "within tolerance" (the multichip smoke gates the
        literal zero). The folded residual magnitude is still tracked as
        ``max_batch_rel_err``.

        Mesh-native serving: ``shard_streams`` maps dp shard label ->
        that shard's occupant streams for this batch. Replicated
        programs keep every chip busy for the full wall time, so each
        listed shard's measured AND attributed ledgers advance by the
        full ``device_ms`` (per-shard drift 0.0 by the same
        construction)."""
        now = self._clock() if now is None else now
        device_ms = float(device_ms)
        ids = list(streams) or [OVERHEAD_STREAM]
        if weights is not None and len(weights) == len(ids):
            wsum = float(sum(weights))
            shares = ([device_ms * float(w) / wsum for w in weights]
                      if wsum > 0.0
                      else [device_ms / len(ids)] * len(ids))
        else:
            shares = [device_ms / len(ids)] * len(ids)
        resid = device_ms - sum(shares)
        shares[-1] += resid
        attributed = device_ms
        rel_err = (abs(resid)
                   / max(abs(device_ms), 1e-12)) if device_ms else 0.0
        amortize = max(1, int(amortize_n))
        geometry = f"{src_hw[0]}x{src_hw[1]}"
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self.attributed_ms += attributed
            self.measured_ms += device_ms
            if rel_err > self.max_conservation_rel_err:
                self.max_conservation_rel_err = rel_err
            for sid, share in zip(ids, shares):
                led = self._streams.get(sid)
                if led is None:
                    led = self._streams[sid] = _StreamLedger()
                led.device_ms += share
                led.by_kind[kind] = led.by_kind.get(kind, 0.0) + share
                led.batches += 1
                led.frames += 1
                led.amortized_ms += share / amortize
                led.last_seen = now
                led.ema_ms_per_frame = (
                    share if led.ema_ms_per_frame is None
                    else 0.9 * led.ema_ms_per_frame + 0.1 * share)
            cell = self._cells.get((model, geometry, int(bucket)))
            if cell is None:
                cell = self._cells[(model, geometry, int(bucket))] = _Cell(
                    self.slow_window_s, self.bin_s)
            cell.ring.record(device_ms, now)
            cell.busy_ms += device_ms
            cell.batches += 1
            self._agg.record(device_ms, now)
            if shard_streams:
                for shard in shard_streams:
                    rec = self._shards.get(shard)
                    if rec is None:
                        rec = self._shards[shard] = {
                            "attributed": 0.0, "measured": 0.0}
                    rec["measured"] += device_ms
                    rec["attributed"] += device_ms
        for sid, share in zip(ids, shares):
            self._m_stream_ms.labels(sid, kind).inc(share)
        self._m_attr.inc(attributed)
        self._m_meas.inc(device_ms)
        if shard_streams:
            for shard in shard_streams:
                self._m_shard_attr.labels(str(shard)).inc(device_ms)
                self._m_shard_meas.labels(str(shard)).inc(device_ms)

    def note_coast(self, streams: Sequence[str]) -> None:
        """Register zero-cost occupants (MOSAIC gated-idle coast groups:
        no device work at all) so the ledger's stream coverage matches
        the serving set — a coasting stream reads as costing 0 ms, not
        as missing."""
        now = self._clock()
        with self._lock:
            for sid in streams:
                led = self._streams.get(sid)
                if led is None:
                    led = self._streams[sid] = _StreamLedger()
                led.batches += 1
                led.last_seen = now
                led.by_kind.setdefault("coast", 0.0)

    # -- forecast (tick thread, throttled) ------------------------------

    def _utilization(self, window_s: float, now: float) -> float:
        """Busy share of the elapsed window in [0, ...): busy device ms
        over window wall ms, windows clipped to the observed span so a
        young tracker is not diluted by bins it never lived through."""
        with self._lock:
            t0 = self._t0
            busy = self._agg.total(window_s, now)
        if t0 is None:
            return 0.0
        span_s = max(self.bin_s, min(window_s, now - t0 + self.bin_s))
        return busy / (span_s * 1000.0)

    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> dict:
        """Update the forecast + burn state from the rings; throttled to
        ``eval_interval_s`` unless forced. Returns the live state dict
        (also retained for snapshot())."""
        now = self._clock() if now is None else now
        if not force and now < self._next_eval:
            return self._last
        self._next_eval = now + self.eval_interval_s
        u_fast = self._utilization(self.fast_window_s, now)
        u_slow = self._utilization(self.slow_window_s, now)
        # EWMA utilization slope (per second): the forecast's trend
        # input. Evaluated on the fast window so ramps register within
        # a minute; the EMA keeps single noisy ticks from whipsawing
        # the saturation estimate.
        if self._prev_util is not None and self._prev_eval_t is not None \
                and now > self._prev_eval_t:
            slope = (u_fast - self._prev_util) / (now - self._prev_eval_t)
            self._slope_ema = (
                slope if self._slope_ema is None
                else self.slope_alpha * slope
                + (1.0 - self.slope_alpha) * self._slope_ema)
        self._prev_util = u_fast
        self._prev_eval_t = now
        headroom = max(0.0, 1.0 - u_fast)
        tts: Optional[float] = None
        if self._slope_ema is not None and self._slope_ema > 1e-9:
            tts = headroom / self._slope_ema
        burn_fast = u_fast / self.util_objective
        burn_slow = u_slow / self.util_objective
        burning = burn_fast > 1.0 and burn_slow > 1.0
        self._last = {
            "utilization": {"fast": u_fast, "slow": u_slow},
            "burn": {"fast": burn_fast, "slow": burn_slow},
            "burning": burning,
            "headroom": headroom,
            "slope_per_s": self._slope_ema,
            "time_to_saturation_s": tts,
        }
        self._m_util.labels("fast").set(u_fast)
        self._m_util.labels("slow").set(u_slow)
        self._m_burn.labels("fast").set(burn_fast)
        self._m_burn.labels("slow").set(burn_slow)
        self._m_headroom.set(headroom)
        self._m_tts.set(tts if tts is not None else -1.0)
        # Departed-stream expiry (r21): a stream idle past the slow
        # window has left the serving set (the engine stopped attributing
        # to it); its row no longer informs any live decision, so drop it
        # and fold its total into the expired aggregates. Conservation is
        # untouched — attributed_ms/measured_ms are running counters, not
        # sums over the live dict.
        with self._lock:
            cutoff = now - self.slow_window_s
            gone = [sid for sid, led in self._streams.items()
                    if led.last_seen < cutoff]
            for sid in gone:
                led = self._streams.pop(sid)
                self.expired_streams += 1
                self.expired_ms += led.device_ms
        with self._lock:
            cells = list(self._cells.items())
            t0 = self._t0
        span_s = max(self.bin_s, min(
            self.fast_window_s,
            (now - t0 + self.bin_s) if t0 is not None else self.bin_s))
        for (model, geometry, bucket), cell in cells:
            busy = cell.ring.total(self.fast_window_s, now)
            cell.last_util = busy / (span_s * 1000.0)
            self._m_cell_util.labels(
                model, geometry, str(bucket)).set(cell.last_util)
        return self._last

    # -- read surfaces ---------------------------------------------------

    def conservation(self) -> dict:
        """The ledger invariant's verdict: attributed vs measured device
        time, worst per-batch relative error, and whether the running
        totals agree within tolerance."""
        with self._lock:
            attributed = self.attributed_ms
            measured = self.measured_ms
            max_err = self.max_conservation_rel_err
            shard_recs = {s: dict(rec) for s, rec in self._shards.items()}
        drift = abs(attributed - measured) / max(measured, 1e-9) \
            if measured else 0.0
        out = {
            "attributed_ms": attributed,
            "measured_ms": measured,
            "rel_drift": drift,
            "max_batch_rel_err": max_err,
            "balanced": (drift <= CONSERVATION_REL_TOL
                         and max_err <= CONSERVATION_REL_TOL),
        }
        if shard_recs:
            out["shards"] = {
                s: {
                    "attributed_ms": rec["attributed"],
                    "measured_ms": rec["measured"],
                    "rel_drift": (abs(rec["attributed"] - rec["measured"])
                                  / max(rec["measured"], 1e-9)
                                  if rec["measured"] else 0.0),
                }
                for s, rec in sorted(shard_recs.items())
            }
        return out

    def streams(self) -> Dict[str, dict]:
        """Per-stream ledger rows (copies)."""
        with self._lock:
            return {
                sid: {
                    "device_ms": led.device_ms,
                    "by_kind": dict(led.by_kind),
                    "batches": led.batches,
                    "frames": led.frames,
                    "ema_ms_per_frame": led.ema_ms_per_frame,
                    "amortized_ms": led.amortized_ms,
                }
                for sid, led in self._streams.items()
            }

    def snapshot(self) -> dict:
        """JSON-able capacity state for /api/v1/capacity, the
        /api/v1/stats obs embed, and the fleet scrape. Runs a (throttled)
        evaluate so a read-only consumer still sees a live forecast."""
        state = self.evaluate()
        with self._lock:
            cells = {
                f"{model}|{geometry}|{bucket}": {
                    "busy_ms": round(cell.busy_ms, 3),
                    "batches": cell.batches,
                    "util_fast": round(cell.last_util, 6),
                }
                for (model, geometry, bucket), cell in self._cells.items()
            }
        return {
            "tick_ms": self.tick_ms,
            "util_objective": self.util_objective,
            "windows_s": {"fast": self.fast_window_s,
                          "slow": self.slow_window_s},
            "utilization": {k: round(v, 6)
                            for k, v in state["utilization"].items()},
            "burn": {k: round(v, 6) for k, v in state["burn"].items()},
            "burning": state["burning"],
            "headroom": round(state["headroom"], 6),
            "slope_per_s": state["slope_per_s"],
            "time_to_saturation_s": state["time_to_saturation_s"],
            "conservation": self.conservation(),
            "streams": self.streams(),
            "expired": {"streams": self.expired_streams,
                        "device_ms": round(self.expired_ms, 3)},
            "cells": cells,
        }
