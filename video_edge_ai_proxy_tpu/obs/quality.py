"""Output-quality observability: per-stream health verdicts, detection
drift scores, and the live canary integrity check (ISSUE r7 tentpole).

The obs stack to date proves the engine is *fast* (spans/metrics, perf
attribution, SLO burn, triggered profiling) but nothing on the live path
proves it is *right*: the reference proxy supervises only container
liveness (``server/services/rtsp_process_manager.go:283-335``), so a
black camera, a frozen RTSP feed, or a drifting detection head serves
confidently forever. This module turns the device-computed frame
statistics (``ops/preprocess.py:frame_quality_stats``, folded into the
serving step and fetched alongside results) plus the emitted detections
into host-side quality signals:

- :class:`QualityTracker` — per-stream black / frozen / flatline / ok
  state machines with time-based hysteresis (injectable clock, so the
  windows are fake-clock testable), per-class detection-count EMAs and
  log2 confidence histograms scored against committed or self-adopted
  baselines (detection drift), ``vep_quality_*`` metric families, and
  the ``unhealthy()`` set the degradation ladder consumes so frozen and
  black streams become first-shed candidates.
- :class:`CanaryChecker` — folds per-frame host-side result checksums of
  the replayed golden canary stream once per trace loop and compares the
  folded value against the committed golden: the first content-derived
  correctness signal on the *production* path (the bench checksum only
  guards the offline megastep). A mismatch run opens exactly one
  watchdog episode and burns the ``canary_integrity`` SLO
  (:func:`obs.slo.integrity_slo`).

Jax-free and importable from control-plane code: every input is a plain
float/int handed over by the engine's drain thread, and all state is
lock-guarded (observe() runs on the drain thread, unhealthy() on the
engine tick thread, snapshot() on REST/gRPC threads).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Sequence

from ..utils.logging import get_logger
from . import metrics as metrics_mod

log = get_logger("obs.quality")

#: Verdicts in priority order — when several conditions hold at once the
#: earlier one wins (a black frame is also frozen; black explains more).
VERDICTS = ("black", "frozen", "flatline", "ok")

#: log2 confidence histogram: bin ``i`` holds scores in ``(2^-(i+1), 2^-i]``;
#: the last bin absorbs everything at or below ``2^-(CONF_BINS-1)``.
CONF_BINS = 8

#: A stream only flatlines if it historically detected at least this
#: per-frame count EMA — a stream that never detects anything is idle
#: scenery, not a failed head.
_FLATLINE_MIN_EMA = 0.5

# Twin of replay/checksum.py CHECKSUM_MASK (int32 non-negative range),
# duplicated so the obs plane does not import the replay package.
_MASK = 0x7FFFFFFF


def _conf_bin(score: float) -> int:
    """log2 bucket index for a confidence in (0, 1]."""
    s = float(score)
    if s >= 1.0:
        return 0
    if s <= 0.0:
        return CONF_BINS - 1
    return min(int(math.floor(-math.log2(s))), CONF_BINS - 1)


def _drift_score(base: dict, cur: dict) -> float:
    """Blend of confidence-histogram total-variation distance and mean
    relative per-class rate shift, clipped to [0, 1]. The 0.5 rate floor
    keeps a rare class (baseline ~0 per frame) from dominating."""
    hist_d = 0.5 * sum(abs(a - b) for a, b in zip(base["hist"], cur["hist"]))
    classes = set(base["rate"]) | set(cur["rate"])
    if classes:
        shift = sum(
            abs(cur["rate"].get(c, 0.0) - base["rate"].get(c, 0.0))
            / max(base["rate"].get(c, 0.0), 0.5)
            for c in classes
        ) / len(classes)
    else:
        shift = 0.0
    return min(1.0, 0.5 * hist_d + 0.5 * min(1.0, shift))


class _StreamState:
    __slots__ = (
        "verdict", "since", "samples", "cond_since", "clear_since",
        "luma", "luma_var", "diff", "last_det_t", "det_ema", "peak_det_ema",
        "class_ema", "win_hist", "win_counts", "win_frames", "win_start",
        "baseline", "drift", "drifting", "transitions", "drift_events",
    )

    def __init__(self, now: float):
        self.verdict = "ok"
        self.since = now
        self.samples = 0
        self.cond_since: Dict[str, float] = {}
        self.clear_since: Optional[float] = None
        self.luma: Optional[float] = None
        self.luma_var: Optional[float] = None
        self.diff: Optional[float] = None
        self.last_det_t: Optional[float] = None
        self.det_ema = 0.0
        self.peak_det_ema = 0.0
        self.class_ema: Dict[int, float] = {}
        self.win_hist = [0] * CONF_BINS
        self.win_counts: Dict[int, int] = {}
        self.win_frames = 0
        self.win_start = now
        self.baseline: Optional[dict] = None
        self.drift = 0.0
        self.drifting = False
        self.transitions: deque = deque(maxlen=64)
        self.drift_events: deque = deque(maxlen=32)


class QualityTracker:
    """Black / frozen / flatline / ok state machines + drift scoring.

    Hysteresis is time-based and symmetric: a condition must hold
    continuously for ``enter_s`` to enter a bad verdict, and EVERY
    condition must stay clear continuously for ``exit_s`` to return to
    ok — oscillation at either boundary resets the opposing run, so the
    verdict cannot flap (tests/test_quality.py proves both directions).
    Flatline (zero detections for ``flatline_s`` on a stream that
    historically detected) carries its window in the condition itself
    and enters immediately once true.
    """

    def __init__(
        self,
        *,
        black_luma: float = 0.04,
        black_var: float = 5e-4,
        freeze_diff: float = 1e-6,
        enter_s: float = 2.0,
        exit_s: float = 2.0,
        flatline_s: float = 10.0,
        window_s: float = 5.0,
        drift_threshold: float = 0.35,
        ema_alpha: float = 0.05,
        baselines: Optional[Dict[str, dict]] = None,
        clock: Callable[[], float] = time.monotonic,
        registry: Optional[metrics_mod.Registry] = None,
        on_transition: Optional[Callable[[str, str, str], None]] = None,
    ):
        self._black_luma = black_luma
        self._black_var = black_var
        self._freeze_diff = freeze_diff
        self._enter_s = enter_s
        self._exit_s = exit_s
        self._flatline_s = flatline_s
        self._window_s = window_s
        self._drift_threshold = drift_threshold
        self._ema_alpha = ema_alpha
        self._baselines = dict(baselines or {})
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._streams: Dict[str, _StreamState] = {}

        reg = registry if registry is not None else metrics_mod.registry
        self._g_state = reg.gauge(
            "vep_quality_state",
            "Per-stream health verdict (1 on the labeled verdict)",
            ("stream", "verdict"))
        self._c_trans = reg.counter(
            "vep_quality_transitions_total",
            "Quality verdict transitions per stream",
            ("stream", "verdict"))
        self._g_luma = reg.gauge(
            "vep_quality_luma",
            "Device-computed thumbnail-domain luma mean (0..1)",
            ("stream",))
        self._g_diff = reg.gauge(
            "vep_quality_diff_energy",
            "Device-computed inter-frame thumbnail MSE",
            ("stream",))
        self._g_drift = reg.gauge(
            "vep_quality_drift_score",
            "Detection drift vs baseline (0..1; histogram + rate blend)",
            ("stream",))
        self._g_unhealthy = reg.gauge(
            "vep_quality_unhealthy_streams",
            "Streams currently black, frozen or flatlined").labels()

    # -- hot path (drain thread) ------------------------------------------

    def observe(
        self,
        stream: str,
        *,
        luma_mean: Optional[float] = None,
        luma_var: Optional[float] = None,
        diff_energy: Optional[float] = None,
        classes: Sequence[int] = (),
        scores: Sequence[float] = (),
    ) -> str:
        """Fold one emitted frame's device stats + detections into the
        stream's state machine; returns the current verdict."""
        now = self._clock()
        fired = None
        with self._lock:
            st = self._streams.get(stream)
            if st is None:
                st = self._streams[stream] = _StreamState(now)
                st.baseline = self._baselines.get(stream)
            first = st.samples == 0
            st.samples += 1

            cur_luma = float(luma_mean) if luma_mean is not None else None
            cur_var = float(luma_var) if luma_var is not None else None
            # The first sample's diff is measured against the zero
            # thumbnail the device state starts from — meaningless either
            # way (a huge diff on a static scene, zero on a black one);
            # drop it so neither direction can mislead the state machine.
            cur_diff = (float(diff_energy)
                        if diff_energy is not None and not first else None)
            if cur_luma is not None:
                st.luma, st.luma_var = cur_luma, cur_var
                self._g_luma.labels(stream).set(cur_luma)
            if cur_diff is not None:
                st.diff = cur_diff
                self._g_diff.labels(stream).set(cur_diff)

            n_det = len(classes)
            a = self._ema_alpha
            st.det_ema += a * (n_det - st.det_ema)
            st.peak_det_ema = max(st.peak_det_ema, st.det_ema)
            counts: Dict[int, int] = {}
            for c in classes:
                counts[int(c)] = counts.get(int(c), 0) + 1
            for c in set(counts) | set(st.class_ema):
                prev = st.class_ema.get(c, 0.0)
                st.class_ema[c] = prev + a * (counts.get(c, 0) - prev)
            if n_det:
                st.last_det_t = now
            elif st.last_det_t is None:
                st.last_det_t = now  # flatline epoch for never-detected-yet

            for c, s in zip(classes, scores):
                st.win_counts[int(c)] = st.win_counts.get(int(c), 0) + 1
                st.win_hist[_conf_bin(s)] += 1
            st.win_frames += 1
            if now - st.win_start >= self._window_s and st.win_frames:
                self._roll_window(stream, st, now)
                st.win_start = now

            black = (cur_luma is not None and cur_luma < self._black_luma
                     and (cur_var is None or cur_var < self._black_var))
            frozen = cur_diff is not None and cur_diff < self._freeze_diff
            flatline = (not black and not frozen
                        and st.peak_det_ema >= _FLATLINE_MIN_EMA
                        and st.last_det_t is not None
                        and now - st.last_det_t >= self._flatline_s)

            for name, cond in (("black", black), ("frozen", frozen),
                               ("flatline", flatline)):
                if cond:
                    st.cond_since.setdefault(name, now)
                else:
                    st.cond_since.pop(name, None)

            candidate = None
            for name, need in (("black", self._enter_s),
                               ("frozen", self._enter_s),
                               ("flatline", 0.0)):
                t0 = st.cond_since.get(name)
                if t0 is not None and now - t0 >= need:
                    candidate = name
                    break

            if candidate is not None:
                st.clear_since = None
                if candidate != st.verdict:
                    fired = self._transition(stream, st, candidate, now)
            elif st.verdict != "ok":
                if black or frozen or flatline:
                    # Condition re-appeared before the exit window closed:
                    # restart the all-clear run (no flap back to ok).
                    st.clear_since = None
                else:
                    if st.clear_since is None:
                        st.clear_since = now
                    if now - st.clear_since >= self._exit_s:
                        fired = self._transition(stream, st, "ok", now)
                        st.clear_since = None
            self._g_unhealthy.set(sum(
                1 for s in self._streams.values() if s.verdict != "ok"))
            verdict = st.verdict
        if fired is not None:
            _, old, new = fired
            (log.info if new == "ok" else log.warning)(
                "stream %s quality verdict %s -> %s", stream, old, new)
            if self._on_transition is not None:
                try:
                    self._on_transition(stream, old, new)
                except Exception:
                    log.exception("quality transition callback failed")
        return verdict

    def _transition(self, stream: str, st: _StreamState, verdict: str,
                    now: float):
        old = st.verdict
        st.verdict = verdict
        st.since = now
        st.transitions.append((now, verdict))
        self._c_trans.labels(stream, verdict).inc()
        for v in VERDICTS:
            self._g_state.labels(stream, v).set(1.0 if v == verdict else 0.0)
        return (stream, old, verdict)

    def _roll_window(self, stream: str, st: _StreamState, now: float) -> None:
        total = sum(st.win_hist)
        cur = {
            "hist": ([h / total for h in st.win_hist] if total
                     else [0.0] * CONF_BINS),
            "rate": {c: n / st.win_frames
                     for c, n in st.win_counts.items()},
        }
        if st.baseline is None:
            if total:
                # Self-adopt: the first window that saw detections becomes
                # the reference distribution (committed replay-derived
                # baselines, when passed in, pre-empt this).
                st.baseline = cur
        else:
            st.drift = _drift_score(st.baseline, cur)
            self._g_drift.labels(stream).set(st.drift)
            was = st.drifting
            st.drifting = st.drift > self._drift_threshold
            if st.drifting and not was:
                st.drift_events.append((now, round(st.drift, 4)))
                log.warning("stream %s detection drift %.3f over threshold "
                            "%.3f", stream, st.drift, self._drift_threshold)
        st.win_hist = [0] * CONF_BINS
        st.win_counts = {}
        st.win_frames = 0

    # -- consumers (tick loop / REST / harness) ---------------------------

    def unhealthy(self) -> frozenset:
        """Streams the degradation ladder should shed first: black or
        frozen verdicts (flatline means the head went quiet, not that the
        frames are worthless — keep serving those)."""
        with self._lock:
            return frozenset(
                name for name, st in self._streams.items()
                if st.verdict in ("black", "frozen"))

    def verdict(self, stream: str) -> str:
        with self._lock:
            st = self._streams.get(stream)
            return st.verdict if st is not None else "ok"

    def forget(self, stream: str) -> None:
        """GC a removed stream's state (engine stream churn)."""
        with self._lock:
            self._streams.pop(stream, None)

    def reset(self) -> None:
        with self._lock:
            self._streams.clear()
            self._g_unhealthy.set(0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "config": {
                    "black_luma": self._black_luma,
                    "black_var": self._black_var,
                    "freeze_diff": self._freeze_diff,
                    "enter_s": self._enter_s,
                    "exit_s": self._exit_s,
                    "flatline_s": self._flatline_s,
                    "window_s": self._window_s,
                    "drift_threshold": self._drift_threshold,
                },
                "unhealthy": sorted(
                    name for name, st in self._streams.items()
                    if st.verdict != "ok"),
                "streams": {
                    name: {
                        "verdict": st.verdict,
                        "since": round(st.since, 3),
                        "samples": st.samples,
                        "luma": st.luma,
                        "luma_var": st.luma_var,
                        "diff_energy": st.diff,
                        "det_ema": round(st.det_ema, 3),
                        "drift": round(st.drift, 4),
                        "drifting": st.drifting,
                        "baseline": st.baseline is not None,
                        "transitions": [[round(t, 3), v]
                                        for t, v in st.transitions],
                        "drift_events": [[round(t, 3), d]
                                         for t, d in st.drift_events],
                    }
                    for name, st in sorted(self._streams.items())
                },
            }


class CanaryChecker:
    """Golden-replay integrity: fold host-side per-frame result checksums
    of the canary stream once per trace loop, compare to the golden.

    Cycle accounting keys off the replayed frame's packet index (the
    trace player preserves it, replay/player.py ``meta_for``), NOT wall
    time: a cycle closes when the packet index wraps, must contain
    exactly ``loop_len`` distinct packets (dropped or duplicated frames
    make the cycle *void* — not checked, so scheduling jitter can never
    manufacture a false mismatch), and its checksums fold in packet
    order so the comparison is timing-independent. ``golden=None``
    adopts the first complete cycle's value (first-run semantics, same
    as replay/checksum.py record-only goldens).
    """

    def __init__(
        self,
        *,
        loop_len: int,
        stream: str = "_canary",
        golden: Optional[int] = None,
        registry: Optional[metrics_mod.Registry] = None,
        watchdog=None,
        slo=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if loop_len <= 0:
            raise ValueError(f"loop_len must be positive, got {loop_len}")
        self._loop_len = int(loop_len)
        self.stream = stream
        self._golden = int(golden) if golden else None
        self.adopted = False
        self._watchdog = watchdog
        self._slo = slo
        self._clock = clock
        self._lock = threading.Lock()
        self._cycle: Dict[int, int] = {}
        self._last_packet: Optional[int] = None
        self.match_cycles = 0
        self.mismatch_cycles = 0
        self.void_cycles = 0
        self.last_value: Optional[int] = None
        reg = registry if registry is not None else metrics_mod.registry
        self._c_cycles = reg.counter(
            "vep_quality_canary_cycles_total",
            "Canary golden-replay cycles checked, by result",
            ("result",))
        self._g_ok = reg.gauge(
            "vep_quality_canary_ok",
            "1 while the canary checksum matches its golden "
            "(0 during a mismatch run)").labels()
        self._g_ok.set(1)

    def note(self, packet: int, checksum: int) -> None:
        """One emitted canary frame: its packet index and host-side
        content checksum (replay/checksum.py ``host_slot_checksum``)."""
        with self._lock:
            p = int(packet)
            if self._last_packet is not None and p <= self._last_packet:
                self._close_cycle_locked()
            self._cycle[p] = int(checksum) & _MASK
            self._last_packet = p

    def _close_cycle_locked(self) -> None:
        cycle, self._cycle = self._cycle, {}
        if (len(cycle) != self._loop_len
                or sorted(cycle) != list(range(self._loop_len))):
            self.void_cycles += 1
            self._c_cycles.labels("void").inc()
            return
        value = 0
        for p in range(self._loop_len):
            value = (value * 1000003 + cycle[p]) & _MASK
        self.last_value = value
        if self._golden is None:
            self._golden = value
            self.adopted = True
            log.info("canary %s adopted golden checksum %d over %d frames",
                     self.stream, value, self._loop_len)
        if value == self._golden:
            self.match_cycles += 1
            self._c_cycles.labels("match").inc()
            self._g_ok.set(1)
            if self._slo is not None:
                self._slo.record(good=1.0)
            if self._watchdog is not None:
                self._watchdog.check("canary_integrity", 0.0, above=0.5)
        else:
            self.mismatch_cycles += 1
            self._c_cycles.labels("mismatch").inc()
            self._g_ok.set(0)
            log.error("canary %s cycle checksum %d != golden %d",
                      self.stream, value, self._golden)
            if self._slo is not None:
                self._slo.record(bad=1.0)
            if self._watchdog is not None:
                self._watchdog.check(
                    "canary_integrity", 1.0, above=0.5,
                    detail=f"cycle checksum {value} != golden "
                           f"{self._golden}")

    @property
    def golden(self) -> Optional[int]:
        with self._lock:
            return self._golden

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "stream": self.stream,
                "loop_len": self._loop_len,
                "golden": self._golden,
                "adopted": self.adopted,
                "match_cycles": self.match_cycles,
                "mismatch_cycles": self.mismatch_cycles,
                "void_cycles": self.void_cycles,
                "last_value": self.last_value,
                "pending_frames": len(self._cycle),
            }
