"""Sharded training step (fine-tune / pretrain path).

The reference has nothing to train (SURVEY.md §5.4 — "no model
checkpoints (no models)"); this module exists because our framework puts
models on the TPU, and an edge fleet that runs models wants to fine-tune
them. One train step, jitted over the mesh: data parallel over ``dp``,
params/optimizer sharded per `sharding.DEFAULT_RULES` (fsdp/tp/ep), and —
through the encoder's `attn_fn` hook — ring attention over ``sp``.
Collectives are never written out; they fall out of the shardings.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from . import sharding as shd
from .ring_attention import make_ring_attn_fn
from .ulysses import make_ulysses_attn_fn


@struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    # Frozen non-param collections (e.g. BatchNorm stats for convnet
    # fine-tuning with frozen statistics). Not updated by the step.
    aux: Any = None


@dataclass
class Trainer:
    """Owns the model, optimizer, mesh, and the compiled train step."""

    model: nn.Module
    mesh: Mesh
    tx: optax.GradientTransformation
    train_step: Callable[[TrainState, jnp.ndarray, jnp.ndarray], tuple]

    def init_state(self, rng: jax.Array, example: jnp.ndarray) -> TrainState:
        variables = jax.jit(functools.partial(self.model.init, train=False))(
            rng, example
        )
        return self.init_state_from(variables)

    def init_state_from(self, variables: Any) -> TrainState:
        """TrainState from restored variables (``{"params": ..., aux
        collections...}`` — the unboxed msgpack format `utils.checkpoint`
        writes and the engine serves). This is the fine-tune entrypoint:
        start from an imported / previously-trained checkpoint instead of
        a fresh init. Re-box first (engine `_rebox`) if the model family
        carries logical sharding names and the mesh should honor them."""
        params = shd.place_params(self.mesh, variables["params"])
        aux = {k: jax.device_put(shd.unbox(v), shd.replicated(self.mesh))
               for k, v in variables.items() if k != "params"} or None
        opt_state = jax.jit(self.tx.init)(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, aux=aux)

    def shard_batch(self, x: jnp.ndarray) -> jnp.ndarray:
        return jax.device_put(x, shd.batch_sharding(self.mesh, x.ndim))


# Weight on sown auxiliary objectives (e.g. the switch-MoE load-balance
# loss) — the Switch Transformer default.
AUX_LOSS_WEIGHT = 0.01


def cross_entropy_loss(model: nn.Module, params, aux, batch, labels) -> jnp.ndarray:
    # BatchNorm models fine-tune with frozen statistics (train=True would
    # try to mutate the immutable batch_stats collection); stat-less models
    # (ViT family) get train=True so dropout stays active.
    train = not (aux and "batch_stats" in aux)
    # mutable=["losses"] collects nn.sow'd auxiliaries (no-op for models
    # that sow nothing) so e.g. routed-MoE balance pressure reaches grads.
    logits, sown = model.apply(
        {"params": params, **(aux or {})}, batch, train=train,
        mutable=["losses"],
    )
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    aux_terms = jax.tree_util.tree_leaves(sown.get("losses", {}))
    if aux_terms:
        loss = loss + AUX_LOSS_WEIGHT * sum(jnp.sum(a) for a in aux_terms)
    return loss


def make_trainer(
    model: nn.Module,
    mesh: Mesh,
    learning_rate=1e-4,
    weight_decay: float = 0.05,
    loss_fn: Optional[Callable] = None,
    clip_norm: Optional[float] = None,
    mutable_aux: bool = False,
) -> Trainer:
    """Build a Trainer whose step is jitted over ``mesh``.

    ``loss_fn(model, params, aux, batch, labels) -> scalar`` defaults to
    softmax cross entropy (classification fine-tune, configs 1/3/4/5);
    ``aux`` carries non-param collections (BatchNorm stats).
    ``learning_rate`` may be an optax schedule. ``clip_norm`` prepends
    global-norm gradient clipping — detection fine-tunes need it: the
    TAL/BCE loss starts in the hundreds on fresh heads, and one unclipped
    bf16 step can overflow activations into NaN.

    ``mutable_aux=True`` changes the loss_fn contract to
    ``-> (scalar, new_aux)`` and threads the returned collections back
    into the state each step — REQUIRED when training BatchNorm models
    from scratch (or far from their import distribution): frozen
    random-init statistics mis-normalize every layer and the deep
    features degenerate to input-independent constants (observed: a
    detector whose class probabilities were identical on every frame).
    Frozen stats remain the right stance for near-distribution
    fine-tunes of imported checkpoints.
    """
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    if clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    loss_fn = loss_fn or cross_entropy_loss

    def step_fn(state: TrainState, batch, labels):
        if mutable_aux:
            (loss, new_aux), grads = jax.value_and_grad(
                lambda p: loss_fn(model, p, state.aux, batch, labels),
                has_aux=True,
            )(state.params)
        else:
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(model, p, state.aux, batch, labels)
            )(state.params)
            new_aux = state.aux
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(step=state.step + 1, params=params,
                       opt_state=opt_state, aux=new_aux),
            loss,
        )

    train_step = jax.jit(step_fn, donate_argnums=(0,))
    return Trainer(model=model, mesh=mesh, tx=tx, train_step=train_step)


def with_ring_attention(model_cls, cfg, mesh: Mesh, dtype=jnp.bfloat16):
    """Instantiate an encoder-family model with sequence-parallel attention
    over the mesh's ``sp`` axis (ViT / VideoMAE both take `attn_fn`)."""
    return model_cls(cfg, dtype, attn_fn=make_ring_attn_fn(mesh))


def with_ulysses_attention(model_cls, cfg, mesh: Mesh, dtype=jnp.bfloat16):
    """Same hook, all-to-all (Ulysses) sequence parallelism — see
    `ulysses.py` for the ring-vs-all-to-all trade-off."""
    return model_cls(cfg, dtype, attn_fn=make_ulysses_attn_fn(mesh))
