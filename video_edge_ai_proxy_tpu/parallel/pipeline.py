"""Pipeline parallelism over the ``pp`` mesh axis (GPipe schedule).

The sixth and last parallelism axis: layer *stages* live on different
chips, microbatches stream through the ring, and activations hop stage to
stage over ICI via `lax.ppermute`. Expressed entirely inside one
`shard_map` — stage s's params are simply shard s of a stacked param tree,
so there is no per-stage program, no RPC layer, and the whole schedule
jits and differentiates like any other function (grads flow back through
the ppermute chain automatically).

Schedule: the classic M + S - 1 tick loop. Every tick, every stage applies
its block to either a fresh microbatch (stage 0), its neighbor's activation
(inner stages), or garbage it discards (bubble ticks, predicated writes).
Bubble fraction (S-1)/(M+S-1) — pick M >= S for efficiency.

Scope: a pipeline stage must be shape-preserving ([B, T, D] -> [B, T, D]),
which transformer blocks are; embed/head stay replicated outside the
pipelined trunk (the standard megatron-style split).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax

from .compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .train import TrainState


def init_stages(rng: jax.Array, stage_module, example: jnp.ndarray, n_stages: int):
    """Init one param tree per stage and stack them on a leading axis
    (shard it over ``pp`` with `place_stages`)."""
    rngs = jax.random.split(rng, n_stages)
    jit_init = jax.jit(stage_module.init)   # one compile, n_stages calls
    trees = [jit_init(r, example) for r in rngs]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _check_stage_count(stacked_params, n_stages: int) -> None:
    got = jax.tree.leaves(stacked_params)[0].shape[0]
    if got != n_stages:
        # shard_map would otherwise split the stage axis silently and each
        # device would run the wrong (or only part of the) stage stack.
        raise ValueError(f"param tree has {got} stages but mesh pp={n_stages}")


def place_stages(mesh: Mesh, stacked_params):
    """Shard the stage axis over pp (stage s's weights live on pp=s)."""
    _check_stage_count(stacked_params, mesh.shape["pp"])

    def spec_for(a):
        return NamedSharding(mesh, P("pp", *([None] * (a.ndim - 1))))

    return jax.tree.map(lambda a: jax.device_put(a, spec_for(a)), stacked_params)


def pipeline_apply(
    mesh: Mesh,
    apply_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params,
    x: jnp.ndarray,
    n_microbatches: int,
):
    """Run the pipelined trunk: x [B, ...] -> [B, ...].

    ``apply_fn(stage_params, microbatch)`` applies ONE stage (e.g.
    ``stage_module.apply``); ``stacked_params`` has a leading stage axis
    sharded over pp. B must divide into ``n_microbatches``.
    """
    n_stages = mesh.shape["pp"]
    _check_stage_count(stacked_params, n_stages)
    m = n_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")

    param_specs = jax.tree.map(
        lambda a: P("pp", *([None] * (a.ndim - 1))), stacked_params
    )

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P(),
        check_vma=False,
    )
    def run(stage_params, x):
        # local shard of the stacked tree: leading dim 1 == this stage
        params = jax.tree.map(lambda a: a[0], stage_params)
        idx = lax.axis_index("pp")
        mbs = x.reshape((m, b // m) + x.shape[1:])
        outs = jnp.zeros_like(mbs)
        recv0 = jnp.zeros_like(mbs[0])
        fwd = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(t, carry):
            recv, outs = carry
            feed_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(idx == 0, mbs[feed_idx], recv)
            out = apply_fn(params, inp)
            # last stage owns microbatch t-(S-1) this tick (predicated write)
            out_idx = t - (n_stages - 1)
            j = jnp.clip(out_idx, 0, m - 1)
            write = (idx == n_stages - 1) & (out_idx >= 0)
            cur = lax.dynamic_index_in_dim(outs, j, 0, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, out, cur), j, 0
            )
            recv = lax.ppermute(out, "pp", fwd)
            return recv, outs

        _, outs = lax.fori_loop(0, m + n_stages - 1, tick, (recv0, outs))
        # broadcast the last stage's results to every device (out_specs P())
        outs = lax.psum(
            jnp.where(idx == n_stages - 1, outs, jnp.zeros_like(outs)), "pp"
        )
        return outs.reshape(x.shape)

    return run(stacked_params, x)


@dataclass
class PipelineTrainer:
    """Trains a pipelined trunk end to end: embed/head replicated closures
    around the staged middle, optimizer state sharded like the params
    (stage axis on pp), gradients flowing back through the ppermute chain.
    """

    mesh: Mesh
    apply_fn: Callable
    tx: optax.GradientTransformation
    n_microbatches: int

    def init_state(self, stacked_params) -> TrainState:
        placed = place_stages(self.mesh, stacked_params)
        opt_state = jax.jit(self.tx.init)(placed)
        return TrainState(step=jnp.zeros((), jnp.int32), params=placed,
                          opt_state=opt_state)

    def make_step(self, loss_of_output: Callable[[jnp.ndarray, Any], jnp.ndarray]):
        """Build the jitted train step. ``loss_of_output(trunk_out, labels)``
        maps the pipelined trunk's output (e.g. [B, T, D] tokens) plus
        labels to a scalar — pooling/head logic lives there, replicated."""

        def step(state: TrainState, x, labels):
            def loss_fn(params):
                out = pipeline_apply(
                    self.mesh, self.apply_fn, params, x, self.n_microbatches
                )
                return loss_of_output(out, labels)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            updates, opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return TrainState(step=state.step + 1, params=params,
                              opt_state=opt_state), loss

        return jax.jit(step, donate_argnums=(0,))


def make_pipeline_trainer(
    mesh: Mesh,
    apply_fn: Callable,
    n_microbatches: int,
    learning_rate: float = 1e-3,
    weight_decay: float = 0.0,
) -> PipelineTrainer:
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    return PipelineTrainer(
        mesh=mesh, apply_fn=apply_fn, tx=tx, n_microbatches=n_microbatches
    )
