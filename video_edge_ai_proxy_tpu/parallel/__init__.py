"""Device-parallelism: mesh, sharding rules, ring attention, train step.

The TPU-native replacement for the distributed story in SURVEY.md §2.3/§2.4
— data parallel over cameras (P7), plus fsdp/tp/sp/ep axes the reference
never had, all expressed as shardings over one `jax.sharding.Mesh`.
"""

from . import pipeline
from .distributed import initialize as initialize_distributed
from .mesh import AXES, factor_mesh, make_mesh, single_device_mesh
from .ring_attention import make_ring_attn_fn, ring_attention_local
from .sharding import (
    DEFAULT_RULES, assemble_sharded, batch_sharding, param_shardings,
    place_params, replicated, shard_put, unbox,
)
from .train import (
    TrainState, Trainer, cross_entropy_loss, make_trainer,
    with_ring_attention, with_ulysses_attention,
)
from .ulysses import make_ulysses_attn_fn, ulysses_attention_local

__all__ = [
    "AXES", "factor_mesh", "make_mesh", "single_device_mesh",
    "initialize_distributed", "pipeline",
    "make_ring_attn_fn", "ring_attention_local",
    "make_ulysses_attn_fn", "ulysses_attention_local",
    "DEFAULT_RULES", "assemble_sharded", "batch_sharding", "param_shardings",
    "place_params", "replicated", "shard_put", "unbox",
    "TrainState", "Trainer", "cross_entropy_loss", "make_trainer",
    "with_ring_attention", "with_ulysses_attention",
]
