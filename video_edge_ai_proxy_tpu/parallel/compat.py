"""jax API compat for shard_map.

``jax.shard_map`` (with its ``check_vma`` flag) became a public top-level
API after the 0.4.x line; older runtimes ship the same transform as
``jax.experimental.shard_map.shard_map`` with the equivalent flag named
``check_rep``. Every in-repo user imports ``shard_map`` from here so one
site owns the mapping and the package imports on both runtimes.
"""

from __future__ import annotations

try:  # jax >= 0.6: public top-level API
    from jax import shard_map as _impl

    _CHECK_KW = "check_vma"
except ImportError:  # 0.4.x line
    from jax.experimental.shard_map import shard_map as _impl

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` signature (keyword ``check_vma``), dispatched to
    whichever implementation this runtime provides."""
    return _impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def axis_size(axis_name: str) -> int:
    """``lax.axis_size`` appeared after the 0.4.x line; ``psum(1, axis)``
    is the portable spelling (constant-folded, no collective issued)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
