"""Logical-axis → mesh-axis sharding rules.

Model code names its weight axes logically (`models/transformer.py` uses
"embed"/"qkv"/"mlp" via `nn.with_logical_partitioning`); this module owns
the single mapping from those names onto mesh axes, so changing the
parallelism layout never touches a model file — the scaling-book recipe:
pick a mesh, annotate shardings, let XLA insert the collectives.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rules: tensor-parallel over head/mlp width, fsdp over embed,
# experts over ep. Entries absent -> replicated.
DEFAULT_RULES = (
    ("embed", "fsdp"),
    ("qkv", "tp"),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("expert", "ep"),
    ("batch", "dp"),
    ("seq", "sp"),
)


def param_shardings(mesh: Mesh, params: Any, rules=DEFAULT_RULES):
    """Tree of NamedShardings for a (possibly nn.Partitioned-boxed) param
    tree. Unannotated leaves are fully replicated."""
    specs = nn.get_partition_spec(params)
    return nn.logical_to_mesh_sharding(specs, mesh, rules)


def batch_sharding(mesh: Mesh, ndim: int, batch_axes=("dp",)) -> NamedSharding:
    """Shard the leading (batch) dim over ``batch_axes``, replicate the rest."""
    return NamedSharding(mesh, P(batch_axes, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_put(frames: Any, sharding: NamedSharding):
    """Sharded H2D with one async ``device_put`` per mesh slice.

    ``jax.device_put(host_array, NamedSharding)`` routes through a single
    synchronous transfer path on several backends; issuing one per-slice
    ``device_put`` lets every chip's DMA engine pull its own slice
    concurrently, and ``make_array_from_single_device_arrays`` stitches
    the committed pieces back into one global array with the requested
    sharding (no data movement). Slices of a C-contiguous host array
    along the leading (batch) axis are themselves contiguous views, so
    each transfer is a single flat copy. Falls back to the plain put when
    the sharding cannot enumerate per-device index maps."""
    try:
        dmap = sharding.addressable_devices_indices_map(frames.shape)
    except Exception:
        return jax.device_put(frames, sharding)
    arrs = [jax.device_put(frames[idx], d) for d, idx in dmap.items()]
    return jax.make_array_from_single_device_arrays(
        frames.shape, sharding, arrs)


def assemble_sharded(pieces: Any, shape: tuple, sharding: NamedSharding):
    """Stitch per-shard single-device arrays into one global dp-sharded
    array with NO data movement on the common dp-only mesh.

    ``pieces[s]`` is shard s's batch segment (``shape[0]/len(pieces)``
    rows) already committed on that shard's primary device — e.g. a
    per-shard state-pool gather. When an extra mesh axis replicates the
    batch block over several devices, the piece is device_put to the
    replicas (device-to-device)."""
    seg = shape[0] // max(1, len(pieces))
    arrs = []
    for d, idx in sharding.addressable_devices_indices_map(shape).items():
        s = (idx[0].start or 0) // seg if seg else 0
        piece = pieces[s]
        if d not in piece.devices():
            piece = jax.device_put(piece, d)
        arrs.append(piece)
    return jax.make_array_from_single_device_arrays(shape, sharding, arrs)


def unbox(params: Any) -> Any:
    """Strip nn.Partitioned boxes (for code that wants raw arrays)."""
    return nn.meta.unbox(params)


def place_params(mesh: Mesh, params: Any, rules=DEFAULT_RULES):
    """Unbox a Partitioned param tree and device-put it onto the mesh per
    the rules (host -> sharded device buffers)."""
    shardings = param_shardings(mesh, params, rules)
    return jax.device_put(unbox(params), shardings)
