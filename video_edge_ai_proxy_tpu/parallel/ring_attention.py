"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context support is first-class even though today's clips are short
(SURVEY.md §5.7): when the token count outgrows one chip's HBM, the
sequence is sharded across ``sp`` and attention runs blockwise — each step
attends the local Q block against the resident K/V block while
`lax.ppermute` rotates K/V around the ring, overlapping the ICI transfer
with the matmuls. Softmax is accumulated online (flash-attention style
running max/denominator), so the result is *exactly* full softmax
attention, never an approximation.

Drops into the encoder via the `attn_fn` hook (`models/transformer.py`):
`make_ring_attn_fn(mesh)` returns a function with the same [B, T, H, D]
signature as `default_attention`, implemented as a nested `shard_map`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from .compat import axis_size, shard_map


_NEG = -1e30  # "masked" logit; avoids -inf NaNs when a whole block is masked


def _online_block(q, k_blk, v_blk, key_valid, m, l, o):
    """One blockwise-softmax accumulation step.

    q: [B, Tq, H, D]; k_blk/v_blk: [B, Tk, H, D]; key_valid: [Tk] bool;
    m, l: [B, H, Tq] running max / denominator; o: [B, Tq, H, D] numerator.
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bthd,bshd->bhts", q, k_blk).astype(jnp.float32) * scale
    logits = jnp.where(key_valid[None, None, None, :], logits, _NEG)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    alpha = jnp.exp(m - m_new)                       # rescale old accumulators
    p = jnp.exp(logits - m_new[..., None])           # [B, H, Tq, Tk]
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhts,bshd->bthd", p.astype(v_blk.dtype), v_blk
    ).astype(jnp.float32)
    return m_new, l_new, o_new


def ring_attention_local(q, k, v, axis_name: str = "sp", true_t: Optional[int] = None):
    """Attention over a sequence sharded on ``axis_name``; call under
    shard_map. q/k/v: local shards [B, T_local, H, D].

    ``true_t``: global unpadded token count. Key positions >= true_t (the
    right-pad added to make T divisible by the ring size) are masked out of
    the softmax; the mask for each in-flight block is derived from which
    shard the block originated on (after s rotations, device i holds the
    block that started on device (i - s) mod n).
    """
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    m0 = jnp.full((b, h, tq), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    local_pos = jnp.arange(tq)

    def body(carry, s):
        k_blk, v_blk, m, l, o = carry
        if true_t is None:
            key_valid = jnp.ones((tq,), bool)
        else:
            src = (my - s) % n
            key_valid = src * tq + local_pos < true_t
        m, l, o = _online_block(q, k_blk, v_blk, key_valid, m, l, o)
        # Rotate K/V around the ring; XLA overlaps the ppermute with the
        # next iteration's matmuls (async collective).
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (_, _, m, l, o), _ = lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(n), length=n
    )
    out = o / jnp.maximum(l.transpose(0, 2, 1)[..., None], 1e-30)
    return out.astype(q.dtype)


def make_seq_parallel_attn_fn(
    mesh: Mesh,
    choose_local,
    batch_axis: Optional[str] = "dp",
    seq_axis: str = "sp",
    head_axis: Optional[str] = "tp",
):
    """Shared wrapper for sequence-parallel attention variants: global
    [B, T, H, D] in/out, sequence sharded over ``seq_axis`` inside the
    shard_map, batch and heads partitioned over ``batch_axis``/``head_axis``.

    ``choose_local(h_local)`` picks the per-shard attention body (ring,
    all-to-all, ...) given the per-device head count after head-axis
    sharding — the one place the variants differ. The padding/fallback
    subtleties live here exactly once:

    - Sequences whose length is not divisible by the ``seq_axis`` size
      (e.g. ViT's 196 patches + 1 cls token) are right-padded before the
      shard_map and the pad keys masked out of the softmax, so the result
      is bit-equal to dense attention on the unpadded sequence.
    - Axes that don't divide the actual (static) shape fall back to
      replication — e.g. model.init traces with batch 1 under dp=2.
    """
    n_sp = mesh.shape[seq_axis]

    def attn(q, k, v):
        ba = batch_axis if batch_axis and q.shape[0] % mesh.shape[batch_axis] == 0 else None
        ha = head_axis if head_axis and q.shape[2] % mesh.shape[head_axis] == 0 else None
        h_local = q.shape[2] // (mesh.shape[head_axis] if ha else 1)
        spec = P(ba, seq_axis, ha, None)
        t = q.shape[1]
        t_pad = -(-t // n_sp) * n_sp
        sharded = shard_map(
            functools.partial(
                choose_local(h_local), axis_name=seq_axis,
                true_t=None if t_pad == t else t,
            ),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        if t_pad != t:
            pad = ((0, 0), (0, t_pad - t), (0, 0), (0, 0))
            q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
        out = sharded(q, k, v)
        return out[:, :t] if t_pad != t else out

    return attn


def make_ring_attn_fn(
    mesh: Mesh,
    batch_axis: Optional[str] = "dp",
    seq_axis: str = "sp",
    head_axis: Optional[str] = "tp",
):
    """Build a ring-attention `attn_fn` for `models/transformer.Encoder`
    (see `make_seq_parallel_attn_fn` for the shared padding/fallback
    behavior)."""
    return make_seq_parallel_attn_fn(
        mesh, lambda h_local: ring_attention_local,
        batch_axis=batch_axis, seq_axis=seq_axis, head_axis=head_axis,
    )
