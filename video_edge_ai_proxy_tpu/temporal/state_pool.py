"""Device-resident per-track clip ring for the temporal cascade.

Modeled on the r12 quality thumbnail pool (engine/runner.py
``_ThumbPool``), re-keyed from stream to track: one static-shape device
array ``[slots, clip_len, side, side, 3] uint8`` holds every live
track's last ``clip_len`` crop tiles as a ring. Slot assignment is a
host-side dict (track key -> row) plus a free list; per-row write
cursors and fill counts also live on the host, so the ONLY host<->device
traffic is the new tiles themselves plus two small int32 index vectors
per scatter (``vep_h2d_*`` aux bytes) — the clip contents NEVER round-
trip to the host between ticks (ISSUE 14 acceptance: no per-tick D2H of
the state pool; the head consumes clips via a device-side gather).

Row 0 is permanently zero and is the gather target for padded bucket
slots, so a padded head batch reads all-zero clips instead of stale
track state. Capacity grows in ``_GROW``-row increments via ``jnp.pad``
(device-to-device copy); scatter/gather batch sizes are bucketed by the
caller, so program shapes stay bounded. Slot reuse needs no device-side
zeroing: ``gather`` only ever returns rows whose fill count reached
``clip_len``, by which point the new occupant overwrote every time
position.

Lazy jax imports (CLAUDE.md): constructing the pool is backend-free;
the device array materializes on first ``scatter``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class TrackStatePool:
    """Per-track device clip ring with host-side slot bookkeeping."""

    _GROW = 8

    __slots__ = ("side", "clip_len", "_slots", "_free", "_cursor", "_fill",
                 "_pool", "_capacity", "_high")

    def __init__(self, side: int, clip_len: int):
        self.side = int(side)
        self.clip_len = int(clip_len)
        self._slots: Dict[str, int] = {}      # track key -> row (>= 1)
        self._free: List[int] = []
        self._cursor: Dict[int, int] = {}     # row -> next write position
        self._fill: Dict[int, int] = {}       # row -> frames written (<= T)
        self._pool = None                     # [cap, T, side, side, 3] u8
        self._capacity = 0
        self._high = 0                        # highest row ever assigned

    # -- dict-protocol surface (mirrors _ThumbPool so GC reads the same) --

    def __bool__(self) -> bool:
        return bool(self._slots)

    def __len__(self) -> int:
        return len(self._slots)

    def __iter__(self):
        return iter(self._slots)

    def __contains__(self, key: str) -> bool:
        return key in self._slots

    def pop(self, key: str, default=None):
        """Release a track's slot back to the free list."""
        row = self._slots.pop(key, None)
        if row is None:
            return default
        self._free.append(row)
        self._cursor.pop(row, None)
        self._fill.pop(row, None)
        return row

    # -- occupancy ---------------------------------------------------------

    @property
    def high_water(self) -> int:
        """Highest row ever assigned (slot-conservation evidence: stays
        bounded across track churn because freed rows are reused)."""
        return self._high

    def slots_in_use(self) -> int:
        return len(self._slots)

    @property
    def array(self):
        """The live device array (None before the first scatter). Exposed
        for the no-D2H invariant test, never for host fetches."""
        return self._pool

    def full(self, key: str) -> bool:
        """True once the track has a complete ``clip_len``-frame clip."""
        row = self._slots.get(key)
        return row is not None and self._fill.get(row, 0) >= self.clip_len

    # -- device ring -------------------------------------------------------

    def _ensure(self, rows: int) -> None:
        import jax.numpy as jnp

        need = rows + 1
        if self._pool is None:
            cap = ((max(need, 2) + self._GROW - 1)
                   // self._GROW) * self._GROW
            self._pool = jnp.zeros(
                (cap, self.clip_len, self.side, self.side, 3), jnp.uint8)
            self._capacity = cap
        elif need > self._capacity:
            grow = ((need - self._capacity + self._GROW - 1)
                    // self._GROW) * self._GROW
            self._pool = jnp.pad(
                self._pool, ((0, grow), (0, 0), (0, 0), (0, 0), (0, 0)))
            self._capacity += grow

    def _row_for(self, key: str) -> int:
        row = self._slots.get(key)
        if row is None:
            row = self._free.pop() if self._free else self._high + 1
            self._high = max(self._high, row)
            self._slots[key] = row
            self._cursor[row] = 0
            self._fill[row] = 0
        return row

    def scatter(self, keys: Sequence[str], tiles: np.ndarray,
                bucket: Optional[int] = None) -> int:
        """Append one new crop tile per track to its ring.

        ``tiles`` is ``uint8 [n, side, side, 3]`` host frames (one per
        key, keys unique). With ``bucket`` the index vectors and tile
        batch are padded to that length by REPEATING the last entry —
        a duplicate write of identical data to the same cell, harmless
        and shape-stable (bounded program count). Returns the aux index
        bytes shipped (the two int32 vectors); the caller adds the tile
        bytes for ``vep_h2d_*`` accounting.
        """
        import jax.numpy as jnp

        rows = [self._row_for(k) for k in keys]
        self._ensure(max(rows))
        pos = [self._cursor[r] for r in rows]
        if bucket is not None and bucket > len(rows):
            pad = bucket - len(rows)
            rows_v = rows + [rows[-1]] * pad
            pos_v = pos + [pos[-1]] * pad
            tiles = np.concatenate(
                [tiles, np.repeat(tiles[-1:], pad, axis=0)], axis=0)
        else:
            rows_v, pos_v = rows, pos
        rows_np = np.asarray(rows_v, np.int32)
        pos_np = np.asarray(pos_v, np.int32)
        self._pool = self._pool.at[rows_np, pos_np].set(jnp.asarray(tiles))
        for r in rows:
            self._cursor[r] = (self._cursor[r] + 1) % self.clip_len
            self._fill[r] = min(self._fill[r] + 1, self.clip_len)
        return int(rows_np.nbytes + pos_np.nbytes)

    def gather_indices(self, keys: Sequence[str],
                       bucket: int) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side index plan for a time-ordered device gather.

        Returns ``(slot_idx [bucket], time_idx [bucket, T])`` int32.
        ``time_idx[i]`` unrolls track i's ring oldest-first (the cursor
        points at the next overwrite target, which for a full ring is
        the oldest frame). Padded slots index permanent-zero row 0.
        """
        T = self.clip_len
        slot_idx = np.zeros((bucket,), np.int32)
        time_idx = np.zeros((bucket, T), np.int32)
        base = np.arange(T, dtype=np.int32)
        for i, key in enumerate(keys[:bucket]):
            row = self._slots.get(key)
            if row is None:
                continue
            slot_idx[i] = row
            time_idx[i] = (self._cursor.get(row, 0) + base) % T
        return slot_idx, time_idx

    def gather(self, slot_idx: np.ndarray, time_idx: np.ndarray):
        """Time-ordered clips ``[bucket, T, side, side, 3] uint8`` as a
        DEVICE array (eager jnp take/take_along_axis, same pattern as the
        r12 quality gather): the pool contents never touch the host."""
        import jax.numpy as jnp

        clips = jnp.take(self._pool, jnp.asarray(slot_idx), axis=0)
        t = jnp.asarray(time_idx)[:, :, None, None, None]
        return jnp.take_along_axis(clips, t, axis=1)
