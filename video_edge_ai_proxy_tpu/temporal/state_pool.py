"""Device-resident per-track clip ring for the temporal cascade.

Modeled on the r12 quality thumbnail pool (engine/runner.py
``_ThumbPool``), re-keyed from stream to track: one static-shape device
array ``[slots, clip_len, side, side, 3] uint8`` holds every live
track's last ``clip_len`` crop tiles as a ring. Slot assignment is a
host-side dict (track key -> row) plus a free list; per-row write
cursors and fill counts also live on the host, so the ONLY host<->device
traffic is the new tiles themselves plus two small int32 index vectors
per scatter (``vep_h2d_*`` aux bytes) — the clip contents NEVER round-
trip to the host between ticks (ISSUE 14 acceptance: no per-tick D2H of
the state pool; the head consumes clips via a device-side gather).

Row 0 is permanently zero and is the gather target for padded bucket
slots, so a padded head batch reads all-zero clips instead of stale
track state. Capacity grows in ``_GROW``-row increments via ``jnp.pad``
(device-to-device copy); scatter/gather batch sizes are bucketed by the
caller, so program shapes stay bounded. Slot reuse needs no device-side
zeroing: ``gather`` only ever returns rows whose fill count reached
``clip_len``, by which point the new occupant overwrote every time
position.

Lazy jax imports (CLAUDE.md): constructing the pool is backend-free;
the device array materializes on first ``scatter``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class TrackStatePool:
    """Per-track device clip ring with host-side slot bookkeeping."""

    _GROW = 8

    __slots__ = ("side", "clip_len", "device", "_slots", "_free", "_cursor",
                 "_fill", "_pool", "_capacity", "_high")

    def __init__(self, side: int, clip_len: int, device=None):
        self.side = int(side)
        self.clip_len = int(clip_len)
        # Mesh-sharded serving: each shard's sub-pool commits its ring to
        # that shard's chip, so scatter/gather traffic stays local to the
        # chip that serves the shard's streams. None = default placement
        # (single-chip behavior unchanged).
        self.device = device
        self._slots: Dict[str, int] = {}      # track key -> row (>= 1)
        self._free: List[int] = []
        self._cursor: Dict[int, int] = {}     # row -> next write position
        self._fill: Dict[int, int] = {}       # row -> frames written (<= T)
        self._pool = None                     # [cap, T, side, side, 3] u8
        self._capacity = 0
        self._high = 0                        # highest row ever assigned

    # -- dict-protocol surface (mirrors _ThumbPool so GC reads the same) --

    def __bool__(self) -> bool:
        return bool(self._slots)

    def __len__(self) -> int:
        return len(self._slots)

    def __iter__(self):
        return iter(self._slots)

    def __contains__(self, key: str) -> bool:
        return key in self._slots

    def pop(self, key: str, default=None):
        """Release a track's slot back to the free list."""
        row = self._slots.pop(key, None)
        if row is None:
            return default
        self._free.append(row)
        self._cursor.pop(row, None)
        self._fill.pop(row, None)
        return row

    # -- occupancy ---------------------------------------------------------

    @property
    def high_water(self) -> int:
        """Highest row ever assigned (slot-conservation evidence: stays
        bounded across track churn because freed rows are reused)."""
        return self._high

    def slots_in_use(self) -> int:
        return len(self._slots)

    @property
    def array(self):
        """The live device array (None before the first scatter). Exposed
        for the no-D2H invariant test, never for host fetches."""
        return self._pool

    def full(self, key: str) -> bool:
        """True once the track has a complete ``clip_len``-frame clip."""
        row = self._slots.get(key)
        return row is not None and self._fill.get(row, 0) >= self.clip_len

    def nbytes(self) -> int:
        """Device bytes held by the ring RIGHT NOW (0 before the array
        materializes) — the obs/hbm.py ``register_pool`` protocol.
        Capacity-based, not occupancy-based: grow-by-8 rows stay
        allocated after their tracks churn out, and the HBM plane
        accounts for what the allocator holds, not what is logically
        live. Metadata read only (``.nbytes``) — no transfer, no sync."""
        return int(self._pool.nbytes) if self._pool is not None else 0

    # -- device ring -------------------------------------------------------

    def _ensure(self, rows: int) -> None:
        import jax.numpy as jnp

        need = rows + 1
        if self._pool is None:
            cap = ((max(need, 2) + self._GROW - 1)
                   // self._GROW) * self._GROW
            self._pool = jnp.zeros(
                (cap, self.clip_len, self.side, self.side, 3), jnp.uint8)
            if self.device is not None:
                import jax

                # Committed arrays stay put: every later .at[].set / pad
                # keeps the ring on this shard's chip.
                self._pool = jax.device_put(self._pool, self.device)
            self._capacity = cap
        elif need > self._capacity:
            grow = ((need - self._capacity + self._GROW - 1)
                    // self._GROW) * self._GROW
            self._pool = jnp.pad(
                self._pool, ((0, grow), (0, 0), (0, 0), (0, 0), (0, 0)))
            self._capacity += grow

    def _row_for(self, key: str) -> int:
        row = self._slots.get(key)
        if row is None:
            row = self._free.pop() if self._free else self._high + 1
            self._high = max(self._high, row)
            self._slots[key] = row
            self._cursor[row] = 0
            self._fill[row] = 0
        return row

    def scatter(self, keys: Sequence[str], tiles: np.ndarray,
                bucket: Optional[int] = None) -> int:
        """Append one new crop tile per track to its ring.

        ``tiles`` is ``uint8 [n, side, side, 3]`` host frames (one per
        key, keys unique). With ``bucket`` the index vectors and tile
        batch are padded to that length by REPEATING the last entry —
        a duplicate write of identical data to the same cell, harmless
        and shape-stable (bounded program count). Returns the aux index
        bytes shipped (the two int32 vectors); the caller adds the tile
        bytes for ``vep_h2d_*`` accounting.
        """
        import jax.numpy as jnp

        rows = [self._row_for(k) for k in keys]
        self._ensure(max(rows))
        pos = [self._cursor[r] for r in rows]
        if bucket is not None and bucket > len(rows):
            pad = bucket - len(rows)
            rows_v = rows + [rows[-1]] * pad
            pos_v = pos + [pos[-1]] * pad
            tiles = np.concatenate(
                [tiles, np.repeat(tiles[-1:], pad, axis=0)], axis=0)
        else:
            rows_v, pos_v = rows, pos
        rows_np = np.asarray(rows_v, np.int32)
        pos_np = np.asarray(pos_v, np.int32)
        self._pool = self._pool.at[rows_np, pos_np].set(jnp.asarray(tiles))
        for r in rows:
            self._cursor[r] = (self._cursor[r] + 1) % self.clip_len
            self._fill[r] = min(self._fill[r] + 1, self.clip_len)
        return int(rows_np.nbytes + pos_np.nbytes)

    def gather_indices(self, keys: Sequence[str],
                       bucket: int) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side index plan for a time-ordered device gather.

        Returns ``(slot_idx [bucket], time_idx [bucket, T])`` int32.
        ``time_idx[i]`` unrolls track i's ring oldest-first (the cursor
        points at the next overwrite target, which for a full ring is
        the oldest frame). Padded slots index permanent-zero row 0.
        """
        T = self.clip_len
        slot_idx = np.zeros((bucket,), np.int32)
        time_idx = np.zeros((bucket, T), np.int32)
        base = np.arange(T, dtype=np.int32)
        for i, key in enumerate(keys[:bucket]):
            row = self._slots.get(key)
            if row is None:
                continue
            slot_idx[i] = row
            time_idx[i] = (self._cursor.get(row, 0) + base) % T
        return slot_idx, time_idx

    def gather(self, slot_idx: np.ndarray, time_idx: np.ndarray):
        """Time-ordered clips ``[bucket, T, side, side, 3] uint8`` as a
        DEVICE array (eager jnp take/take_along_axis, same pattern as the
        r12 quality gather): the pool contents never touch the host."""
        import jax.numpy as jnp

        clips = jnp.take(self._pool, jnp.asarray(slot_idx), axis=0)
        t = jnp.asarray(time_idx)[:, :, None, None, None]
        return jnp.take_along_axis(clips, t, axis=1)


def shard_devices(mesh, shards: int) -> list:
    """Primary device per dp index: shard s's pools commit here. With
    extra mesh axes the dp block spans several devices; the first is the
    primary (assemble_sharded replicates to the rest on demand)."""
    axis = list(mesh.axis_names).index("dp")
    blocks = np.moveaxis(np.asarray(mesh.devices), axis, 0)
    blocks = blocks.reshape(shards, -1)
    return [blocks[s][0] for s in range(shards)]


class ShardedTrackStatePool:
    """dp-sharded twin of TrackStatePool for mesh-native cascade serving.

    One sub-ring per mesh shard, committed to that shard's chip, so a
    track's clip state lives where its stream is served (streams are
    pinned to shards by ``engine.collector.stream_shard``). Presents the
    same dict-protocol + scatter/gather surface the scheduler and the
    engine GC already consume, plus :meth:`plan` — the shard-segmented
    head-batch layout (the scheduler maps head outputs back through the
    returned rows). ``gather`` stitches the per-shard sub-gathers into
    one dp-sharded device batch (``parallel.sharding.assemble_sharded``)
    so the cascade head program reads every chip's clips locally — the
    state pool never migrates clips between chips and never round-trips
    them through the host.
    """

    def __init__(self, side: int, clip_len: int, *, mesh, shards: int,
                 shard_of, buckets: Sequence[int] = (4, 8, 16, 32, 64)):
        self.side = int(side)
        self.clip_len = int(clip_len)
        self.mesh = mesh
        self.shards = max(1, int(shards))
        self._shard_of = shard_of            # track key -> shard index
        self._buckets = tuple(
            sorted(b for b in buckets if b % self.shards == 0)
        ) or (self.shards,)
        self.pools = [TrackStatePool(side, clip_len, device=d)
                      for d in shard_devices(mesh, self.shards)]

    # -- dict-protocol surface (same as TrackStatePool) --------------------

    def _pool_for(self, key: str) -> TrackStatePool:
        return self.pools[self._shard_of(key)]

    def __bool__(self) -> bool:
        return any(len(p) for p in self.pools)

    def __len__(self) -> int:
        return sum(len(p) for p in self.pools)

    def __iter__(self):
        for p in self.pools:
            yield from p

    def __contains__(self, key: str) -> bool:
        return key in self._pool_for(key)

    def pop(self, key: str, default=None):
        return self._pool_for(key).pop(key, default)

    @property
    def high_water(self) -> int:
        return max(p.high_water for p in self.pools)

    def slots_in_use(self) -> int:
        return sum(p.slots_in_use() for p in self.pools)

    @property
    def array(self):
        """Per-shard device arrays (None before first scatter)."""
        return [p.array for p in self.pools]

    def full(self, key: str) -> bool:
        return self._pool_for(key).full(key)

    def nbytes(self) -> Dict[str, int]:
        """Per-shard ring bytes ``{shard: bytes}`` — the obs/hbm.py
        sharded ``register_pool`` shape (the tracker sums shards for the
        aggregate; the exactness pin checks each shard against its
        sub-ring's ``.nbytes``)."""
        return {str(s): p.nbytes() for s, p in enumerate(self.pools)}

    # -- sharded scatter / gather ------------------------------------------

    def scatter(self, keys: Sequence[str], tiles: np.ndarray,
                bucket: Optional[int] = None) -> int:
        """Route each track's tile to its shard's sub-ring. ``bucket``
        (the caller's aggregate pad target) is recomputed PER SHARD from
        the bucket ladder — each chip's scatter program stays
        shape-stable independently."""
        per: List[list] = [[] for _ in range(self.shards)]
        for i, key in enumerate(keys):
            per[self._shard_of(key)].append((i, key))
        cap = self._buckets[-1] // self.shards
        aux = 0
        for s, entries in enumerate(per):
            if not entries:
                continue
            entries = entries[:cap]
            sub_keys = [k for _, k in entries]
            sub_tiles = tiles[[i for i, _ in entries]]
            sub_bucket = next(
                (b for b in self._buckets
                 if b // self.shards >= len(entries)), None)
            aux += self.pools[s].scatter(
                sub_keys, sub_tiles,
                bucket=(sub_bucket // self.shards) if sub_bucket else None)
        return aux

    def plan(self, keys: Sequence[str]):
        """Shard-segmented head-batch layout for ``keys`` (due tracks):
        ``(slot_idx [B], time_idx [B, T], rows, B)``. ``rows[i]`` is the
        global batch row of ``keys[i]`` (-1 = dropped: that shard's
        segment overflowed the largest bucket; the track stays due and
        rides the next cadence). Padded rows gather each sub-ring's
        permanent-zero row 0."""
        S = self.shards
        per: List[list] = [[] for _ in range(S)]
        rows = [-1] * len(keys)
        cap = self._buckets[-1] // S
        for i, key in enumerate(keys):
            s = self._shard_of(key)
            if len(per[s]) < cap:
                per[s].append((i, key))
        need = max((len(p) for p in per), default=0) or 1
        bucket = next(b for b in self._buckets if b // S >= need)
        seg = bucket // S
        T = self.clip_len
        slot_idx = np.zeros((bucket,), np.int32)
        time_idx = np.zeros((bucket, T), np.int32)
        for s, entries in enumerate(per):
            if not entries:
                continue
            sub_slot, sub_time = self.pools[s].gather_indices(
                [k for _, k in entries], seg)
            slot_idx[s * seg:(s + 1) * seg] = sub_slot
            time_idx[s * seg:(s + 1) * seg] = sub_time
            for j, (i, _key) in enumerate(entries):
                rows[i] = s * seg + j
        return slot_idx, time_idx, rows, bucket

    def gather(self, slot_idx: np.ndarray, time_idx: np.ndarray):
        """dp-sharded clips ``[B, T, side, side, 3] uint8``: per-shard
        local gathers stitched with no cross-chip movement."""
        from ..parallel.sharding import assemble_sharded, batch_sharding

        bucket = int(slot_idx.shape[0])
        seg = bucket // self.shards
        pieces = []
        for s, pool in enumerate(self.pools):
            if pool.array is None:
                pool._ensure(0)   # committed zero ring (idle shard)
            pieces.append(pool.gather(
                slot_idx[s * seg:(s + 1) * seg],
                time_idx[s * seg:(s + 1) * seg]))
        shape = (bucket, self.clip_len, self.side, self.side, 3)
        return assemble_sharded(pieces, shape, batch_sharding(self.mesh, 5))
