"""Stage-wise temporal serving (CASCADE, ROADMAP item 2).

Detector every tick, tracker-keyed ROI crops into a device-resident
clip ring, temporal head at cadence 1/N as its own bucketed program in
the engine step cache, event verdicts out through uplink / archive /
metrics. Composition of existing plumbing (ViCoStream, arxiv 2606.19849
stage-wise coordination; Jetson anomaly pipeline, arxiv 2307.16834
end-to-end template): the r13 ``CropPlacement`` lineage and the r12
``_ThumbPool`` device-state pattern, re-keyed from stream to track.

Import-light: jax, the model registry, and the canvas packer load
lazily on first use so control-plane imports never initialize a
backend (CLAUDE.md rule).
"""

from .events import TrackEventTracker
from .scheduler import CascadeScheduler, CascadeTickResult
from .state_pool import TrackStatePool

__all__ = [
    "CascadeScheduler",
    "CascadeTickResult",
    "TrackEventTracker",
    "TrackStatePool",
]
