"""Per-track event hysteresis for the temporal cascade.

Same two-sided debounce shape as the stream-quality verdict machine
(obs/quality.py): a track's anomaly score must clear the threshold for
``enter_n`` CONSECUTIVE cascade observations before an "enter" event
fires, and sit below it for ``exit_n`` consecutive observations before
the matching "exit" — a score that flaps across the threshold resets
the run and fires nothing. Counts, not seconds: cascade observations
are cadence-quantized (one per temporal-head pass, every
``cascade_every_n`` ticks), so wall-clock debounce would alias against
the head cadence.

Exactly-once by construction: a transition fires only at the moment the
active flag flips, so each enter/exit boundary produces exactly one
event no matter how long the condition persists (the exactly-once
uplink-delivery gate in CASCADE_r01.json rests on this).

Pure Python, jax-free, no locking — the owning scheduler serializes
access under its own lock.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class TrackEventTracker:
    """Enter/exit hysteresis state machines keyed by track."""

    __slots__ = ("threshold", "enter_n", "exit_n", "_state")

    def __init__(self, threshold: float = 0.5, enter_n: int = 2,
                 exit_n: int = 2):
        self.threshold = float(threshold)
        self.enter_n = max(1, int(enter_n))
        self.exit_n = max(1, int(exit_n))
        # key -> [active, consecutive run toward the opposite state]
        self._state: Dict[str, list] = {}

    def observe(self, key: str, score: float) -> Optional[str]:
        """Feed one cascade observation; returns "enter"/"exit" when the
        track transitions, else None."""
        st = self._state.setdefault(key, [False, 0])
        hot = float(score) >= self.threshold
        if st[0] == hot:
            # Confirmation of the current state: any partial run toward
            # the opposite state was a flap — reset it.
            st[1] = 0
            return None
        st[1] += 1
        if st[1] < (self.enter_n if hot else self.exit_n):
            return None
        st[0] = hot
        st[1] = 0
        return "enter" if hot else "exit"

    def active(self, key: str) -> bool:
        st = self._state.get(key)
        return bool(st and st[0])

    def active_keys(self) -> List[str]:
        return [k for k, st in self._state.items() if st[0]]

    def pop(self, key: str, default=None):
        """Drop a track's machine (track expired or stream GC'd). A
        reappearing key starts cold — no event fires for the removal."""
        return self._state.pop(key, default)

    def __len__(self) -> int:
        return len(self._state)

    def __contains__(self, key: str) -> bool:
        return key in self._state
