"""Cascade scheduler: detect every tick, temporal head at cadence 1/N.

Stage-wise coordinated serving (ViCoStream, arxiv 2606.19849; Jetson
anomaly pipeline, arxiv 2307.16834): the per-frame detect megastep runs
unchanged every engine tick; this scheduler taps its emitted detections
(``harvest``, drain thread), letterboxes each tracked detection's box
through the MOSAIC ``CanvasPacker`` into a ``side``×``side`` tile whose
``CropPlacement`` provenance is keyed by TRACK ("stream#track_id")
rather than stream, appends the tile to that track's device-resident
clip ring (:class:`temporal.state_pool.TrackStatePool`), and every
``every_n`` ticks dispatches the expensive stage — the VideoMAE
temporal head plus a logistic anomaly scorer over pooled clip features
— over all tracks holding a complete clip, as a separate bucketed
program in the engine's step cache. Multi-rate programs, not dynamic
control flow: the detect program never branches on the cascade.

Threading: ``harvest`` runs on the engine drain thread (inside
``_emit_slot``), ``tick`` on the engine tick thread, stream GC ``pop``
under the engine state lock — all serialized by one internal lock,
which is RELEASED around the head dispatch so device compile/compute
never stalls result emission.

The head itself is engine-owned (it needs the model registry, the step
cache, and perf attribution): the engine assigns ``self.head`` a
callable ``(pool, slot_idx, time_idx, n_real) -> (outputs, device_ms)``
where ``outputs`` holds host arrays ``event_score [bucket]``,
``features [bucket, 3]``, ``logits [bucket, num_classes]``. The pool
array itself never crosses to the host (ISSUE 14 no-D2H acceptance).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from .events import TrackEventTracker
from .state_pool import TrackStatePool

log = logging.getLogger(__name__)

# Head/scatter batch-size buckets (slot counts, same closed-shape-set
# discipline as the frame-batch buckets in engine/collector.py). Due
# tracks beyond the max bucket wait for the next cadence tick.
BUCKETS = (4, 8, 16, 32, 64)


def bucket_for(n: int) -> int:
    for b in BUCKETS:
        if n <= b:
            return b
    return BUCKETS[-1]


@dataclass
class _Track:
    """Host-side per-track record; the clip lives in the device pool."""

    stream: str
    track_id: str
    tile: Optional[np.ndarray] = None      # latest [side, side, 3] u8
    updated: bool = False                  # tile pending scatter
    placement: object = None               # CropPlacement provenance
    meta: object = None                    # source FrameMeta (span ids)
    last_seen: int = 0                     # scheduler tick of last harvest
    last_score: Optional[float] = None
    observed: int = 0                      # head passes consumed
    history: deque = field(default_factory=deque)  # archive tiles


@dataclass
class CascadeTickResult:
    """One tick's outward-facing outcome, consumed by the engine."""

    events: List[dict]
    head_tracks: List[Tuple[str, object]]  # (stream, meta) per due track
    head_ms: Optional[float]


class CascadeScheduler:
    """Tracker-keyed temporal state + cadence dispatch + event machine."""

    def __init__(self, *, model: str, every_n: int = 4, crop: int = 0,
                 clip_len: int = 0, threshold: float = 0.5,
                 enter_n: int = 2, exit_n: int = 2, ttl_ticks: int = 30,
                 perf=None, history_keep: int = 0, events_keep: int = 64):
        self.model = str(model)
        self.every_n = max(1, int(every_n))
        # Cadence stretch under pressure (r23): the effective dispatch
        # cadence is every_n * stretch ticks. 1 (default) = the pinned
        # bit-identical cadence; the engine raises it while the
        # degradation ladder sits at shed or deeper, shedding temporal-
        # head FLOPs before streams are shed to the fleet.
        self.stretch = 1
        self._crop = int(crop)
        self._clip_len = int(clip_len)
        self.ttl_ticks = max(1, int(ttl_ticks))
        self.perf = perf
        self._history_keep = int(history_keep)
        self._lock = threading.Lock()
        self._tracks: Dict[str, _Track] = {}
        self._by_stream: Dict[str, Set[str]] = {}
        self._events = TrackEventTracker(
            threshold=threshold, enter_n=enter_n, exit_n=exit_n)
        self._pool: Optional[TrackStatePool] = None
        self._packer = None
        # Mesh-native serving (engine.configure_mesh before first
        # harvest): _resolve builds a dp-sharded pool instead.
        self._mesh = None
        self._mesh_shards = 1
        self._mesh_shard_of = None
        self.side = 0
        self.clip_len = 0
        self.ticks = 0
        self.head_dispatches = 0
        self.head_ticks: deque = deque(maxlen=256)
        self.harvested = 0
        self._event_counts: Dict[str, int] = {}
        self._events_log: deque = deque(maxlen=int(events_keep))
        # Engine-assigned: (pool, slot_idx, time_idx, n_real) ->
        # (host outputs dict, device_ms).
        self.head: Optional[Callable] = None

    # -- lazy geometry (registry imports jax; CLAUDE.md lazy-import rule) --

    def configure_mesh(self, *, mesh, shards: int, shard_of) -> None:
        """Engine wiring (before the first harvest resolves geometry):
        clip rings become per-shard device pools so each chip's cascade
        state lives where its streams are served. ``shard_of`` maps a
        STREAM id to its dp shard (engine/collector.stream_shard); track
        keys are ``stream#track_id`` so the pool wrapper strips the
        track suffix before routing."""
        self._mesh = mesh
        self._mesh_shards = max(1, int(shards))
        self._mesh_shard_of = shard_of

    def repin_mesh(self, *, mesh, shards: int, shard_of) -> Dict[str, int]:
        """Survivor-mesh failover (device-fault domain, r22): counted-
        reset of the sharded cascade state. The dead chip's clip rings
        are gone and the survivors' pool slots were laid out for the old
        shard map, so the whole pool evacuates: tracks and their event
        machines clear WITHOUT firing (mid-fault exit events would be
        fabrications — the objects did not leave, the chip did), and the
        pool rebuilds lazily on the next harvest under the new routing.
        Returns the evacuation counts the engine folds into the failover
        event ({kind: n} — FaultLedger evidence, not silent loss)."""
        with self._lock:
            n_tracks = len(self._tracks)
            n_streams = len(self._by_stream)
            n_slots = (self._pool.slots_in_use()
                       if self._pool is not None
                       and hasattr(self._pool, "slots_in_use") else 0)
            for key in list(self._tracks):
                self._events.pop(key, None)
            self._tracks.clear()
            self._by_stream.clear()
            self._pool = None           # _resolve rebuilds on new mesh
            self._mesh = mesh
            self._mesh_shards = max(1, int(shards))
            self._mesh_shard_of = shard_of
        return {
            "cascade_tracks": n_tracks,
            "cascade_streams": n_streams,
            "cascade_slots": n_slots,
        }

    def _resolve(self) -> None:
        if self._pool is not None:
            return
        from ..models import registry

        spec = registry.get(self.model)
        self.side = int(self._crop or spec.input_size)
        self.clip_len = int(self._clip_len or spec.clip_len or 4)
        from ..engine.collector import CanvasPacker

        # One tile per pack call: max_canvases=1 makes the canvas the
        # tile; gap=0 because there is nothing to separate. The packer's
        # power-of-two decimation + min_crop inflation + 114-gray
        # letterbox background all carry over unchanged.
        self._packer = CanvasPacker(
            side=self.side, gap=0, max_canvases=1,
            min_crop=min(16, self.side))
        if self._mesh is not None:
            # Any mesh (even dp=1) takes the sharded pool so the head
            # batch carries the mesh sharding the compiled program
            # expects (a committed single-device array would force a
            # second program variant).
            from .state_pool import ShardedTrackStatePool

            stream_shard_of = self._mesh_shard_of

            def _key_shard(key: str) -> int:
                return stream_shard_of(key.split("#", 1)[0])

            self._pool = ShardedTrackStatePool(
                self.side, self.clip_len, mesh=self._mesh,
                shards=self._mesh_shards, shard_of=_key_shard,
                buckets=BUCKETS)
        else:
            self._pool = TrackStatePool(self.side, self.clip_len)

    # -- stream-keyed dict protocol (engine GC union membership) -----------

    def __bool__(self) -> bool:
        return bool(self._by_stream)

    def __len__(self) -> int:
        return len(self._by_stream)

    def __iter__(self):
        with self._lock:
            return iter(list(self._by_stream))

    def pop(self, stream: str, default=None):
        """Drop ALL of a stream's tracks (engine GC: stream left the
        bus). Pool slots return to the free list; event machines clear
        without firing (the stream is gone — no consumer)."""
        with self._lock:
            keys = self._by_stream.pop(stream, None)
            if not keys:
                return default
            for key in keys:
                self._tracks.pop(key, None)
                if self._pool is not None:
                    self._pool.pop(key, None)
                self._events.pop(key, None)
            return keys

    # -- drain-thread tap ---------------------------------------------------

    def harvest(self, stream: str, frame: np.ndarray, detections,
                meta=None) -> int:
        """Tap one emitted detect slot: letterbox each tracked
        detection's box into this track's tile, pending scatter at the
        next tick. ``frame`` is the leased host buffer — the packer blit
        copies out of it, nothing retains a reference."""
        tracked = [d for d in detections if getattr(d, "track_id", "")]
        if not tracked:
            return 0
        self._resolve()
        n = 0
        with self._lock:
            tick = self.ticks
            for det in tracked:
                x0 = det.box.left
                y0 = det.box.top
                box = (x0, y0, x0 + det.box.width, y0 + det.box.height)
                key = f"{stream}#{det.track_id}"
                canvases, placements, overflow = self._packer.pack(
                    [(key, meta, frame, box)])
                if overflow or not len(placements):
                    continue
                rec = self._tracks.get(key)
                if rec is None:
                    rec = _Track(stream=stream, track_id=str(det.track_id))
                    if self._history_keep:
                        rec.history = deque(maxlen=self._history_keep)
                    else:
                        rec.history = deque(maxlen=2 * self.clip_len)
                    self._tracks[key] = rec
                    self._by_stream.setdefault(stream, set()).add(key)
                rec.tile = canvases[0]
                rec.updated = True
                rec.placement = placements[0]
                rec.meta = meta
                rec.last_seen = tick
                rec.history.append(canvases[0])
                n += 1
            self.harvested += n
        return n

    def set_stretch(self, factor: int) -> bool:
        """Set the cadence-stretch multiplier; returns True when the
        value changed (the engine journals the edge, not the steady
        state). Only ever called from the tick thread, but locked so a
        concurrent snapshot reads a consistent cadence."""
        factor = max(1, int(factor))
        with self._lock:
            changed = factor != self.stretch
            self.stretch = factor
        return changed

    # -- tick-thread drive ---------------------------------------------------

    def tick(self) -> CascadeTickResult:
        """One engine tick: batched scatter of harvested tiles, stale-
        track expiry, and — on cadence ticks — the temporal-head pass
        plus hysteresis evaluation. Returns fired events and the head
        pass's (stream, meta) list for lineage spans."""
        import time as _time

        events: List[dict] = []
        head_tracks: List[Tuple[str, object]] = []
        head_ms: Optional[float] = None
        due: List[str] = []
        with self._lock:
            self.ticks += 1
            tick = self.ticks
            if self.perf is not None:
                self.perf.note_cascade_tick()
            updated = [(k, r) for k, r in self._tracks.items() if r.updated]
            if updated:
                self._resolve()
                keys = [k for k, _ in updated]
                tiles = np.stack([r.tile for _, r in updated])
                bucket = bucket_for(len(keys))
                t0 = _time.perf_counter()
                aux = self._pool.scatter(keys, tiles, bucket=bucket)
                dt = _time.perf_counter() - t0
                if self.perf is not None:
                    self.perf.note_h2d(
                        f"cascade/{self.model}", bucket,
                        tiles.nbytes + aux, dt)
                for _, r in updated:
                    r.updated = False
            # Track TTL: a track the detector stopped matching frees its
            # slot (IoUTracker coasts max_misses frames first, so the
            # TTL only fires once the tracker itself gave up).
            stale = [k for k, r in self._tracks.items()
                     if tick - r.last_seen > self.ttl_ticks]
            for key in stale:
                self._drop_track_locked(key)
            if (self.head is not None and self._pool is not None
                    and tick % (self.every_n * max(1, self.stretch)) == 0):
                due = [k for k in self._tracks if self._pool.full(k)]
                due = due[:BUCKETS[-1]]
                if due:
                    plan = getattr(self._pool, "plan", None)
                    if plan is not None:
                        # Sharded pool: shard-segmented batch layout.
                        # due_rows[i] = global row of due[i]; -1 means
                        # that shard's segment overflowed — the track
                        # stays full and rides the next cadence tick.
                        slot_idx, time_idx, due_rows, _ = plan(due)
                    else:
                        due_rows = None
                        bucket = bucket_for(len(due))
                        slot_idx, time_idx = self._pool.gather_indices(
                            due, bucket)
                    pool = self._pool
        if due:
            # Head dispatch OUTSIDE the lock: compile on a cache miss
            # takes seconds and must not stall harvest on the drain
            # thread. The pool array snapshot is immutable (functional
            # updates replace, never mutate), so a concurrent scatter
            # cannot corrupt the gather.
            try:
                outputs, head_ms = self.head(pool, slot_idx, time_idx,
                                             len(due))
            except Exception:
                log.exception("cascade head dispatch failed; continuing")
                outputs = None
            if outputs is not None:
                with self._lock:
                    self.head_dispatches += 1
                    self.head_ticks.append(tick)
                    if self.perf is not None:
                        self.perf.note_cascade_head(len(due))
                    for i, key in enumerate(due):
                        row = due_rows[i] if due_rows is not None else i
                        if row < 0:           # dropped by the shard plan
                            continue
                        rec = self._tracks.get(key)
                        if rec is None:       # expired mid-dispatch
                            continue
                        score = float(outputs["event_score"][row])
                        rec.last_score = score
                        rec.observed += 1
                        head_tracks.append((rec.stream, rec.meta))
                        kind = self._events.observe(key, score)
                        if kind is None:
                            continue
                        ev = {
                            "kind": kind,
                            "stream": rec.stream,
                            "track_id": rec.track_id,
                            "score": score,
                            "tick": tick,
                            "features": [float(v)
                                         for v in outputs["features"][row]],
                            "logits": [float(v)
                                       for v in outputs["logits"][row]],
                            "meta": rec.meta,
                            "history": (list(rec.history)
                                        if kind == "enter" else []),
                        }
                        events.append(ev)
                        self._event_counts[kind] = (
                            self._event_counts.get(kind, 0) + 1)
                        self._events_log.append({
                            k: v for k, v in ev.items()
                            if k not in ("meta", "history")
                        })
        if self.perf is not None and self._pool is not None:
            self.perf.note_cascade_slots(
                self._pool.slots_in_use(), self._pool.high_water)
        return CascadeTickResult(events, head_tracks, head_ms)

    def _drop_track_locked(self, key: str) -> None:
        rec = self._tracks.pop(key, None)
        if rec is not None:
            keys = self._by_stream.get(rec.stream)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    self._by_stream.pop(rec.stream, None)
        if self._pool is not None:
            self._pool.pop(key, None)
        self._events.pop(key, None)

    # -- introspection -------------------------------------------------------

    def pool_nbytes(self):
        """Device bytes held by the track-state clip ring: an int for the
        single-chip pool, ``{shard: bytes}`` for the sharded pool, 0
        before the pool resolves — the engine's obs/hbm.py
        ``register_pool`` tap (the callable closes over the scheduler,
        so the configure_mesh pool swap stays tracked)."""
        return self._pool.nbytes() if self._pool is not None else 0

    def snapshot(self) -> dict:
        """JSON-able state for /api/v1/cascade and the obs.cascade stats
        section (r9 convention: quiet numbers, no device sync)."""
        with self._lock:
            tracks = {
                key: {
                    "stream": rec.stream,
                    "track_id": rec.track_id,
                    "last_seen_tick": rec.last_seen,
                    "last_score": rec.last_score,
                    "observed": rec.observed,
                    "active": self._events.active(key),
                    "clip_full": (self._pool.full(key)
                                  if self._pool is not None else False),
                }
                for key, rec in self._tracks.items()
            }
            return {
                "model": self.model,
                "every_n": self.every_n,
                "stretch": self.stretch,
                "effective_every_n": self.every_n * max(1, self.stretch),
                "side": self.side,
                "clip_len": self.clip_len,
                "threshold": self._events.threshold,
                "enter_n": self._events.enter_n,
                "exit_n": self._events.exit_n,
                "ticks": self.ticks,
                "harvested": self.harvested,
                "head_dispatches": self.head_dispatches,
                "head_ticks": list(self.head_ticks),
                "head_cadence": (round(self.ticks / self.head_dispatches, 2)
                                 if self.head_dispatches else None),
                "tracks": tracks,
                "slots_in_use": (self._pool.slots_in_use()
                                 if self._pool is not None else 0),
                "slot_high_water": (self._pool.high_water
                                    if self._pool is not None else 0),
                "event_counts": dict(self._event_counts),
                "events": list(self._events_log),
            }
