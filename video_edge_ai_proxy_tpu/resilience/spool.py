"""Bounded on-disk dead-letter spool for annotation batches.

Fixes a reference data-loss path: the reference drops an annotation
batch on any cloud POST failure (``grpc_server.go:204-217`` logs and
moves on). Here a batch that exhausts its retries is persisted as one
file under the spool directory and re-drained oldest-first once the
uplink recovers, so a cloud outage costs latency, not data.

Format: per batch, one ``<seq>.batch`` file — magic header, ``<I`` item
count, then per item ``<I`` length + raw bytes (the serialized
AnnotateRequest protos exactly as queued). Writes are atomic (tmp file +
``os.replace``) so a crash mid-write never leaves a torn batch; drain
nevertheless tolerates one (external truncation, non-atomic copies) by
salvaging the intact item prefix and counting only the torn tail as
dropped — a damaged file costs its tail, not the whole batch. The
spool is bounded by ``max_bytes``/``max_batches``; when full, the
*oldest* batches are evicted (and counted in ``dropped_batches``) so
accounting still balances: published = delivered + queue-dropped +
spool-dropped + pending.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
from typing import Callable, List, Optional, Sequence

from ..obs import registry as obs_registry

log = logging.getLogger(__name__)

__all__ = ["DeadLetterSpool"]

_MAGIC = b"VEPSPOOL1\n"
_U32 = struct.Struct("<I")


class DeadLetterSpool:
    """One directory of length-prefixed batch files, oldest-first drain."""

    SUFFIX = ".batch"

    def __init__(
        self,
        directory: str,
        *,
        max_bytes: int = 64 << 20,
        max_batches: int = 4096,
    ):
        self.directory = directory
        self.max_bytes = int(max_bytes)
        self.max_batches = int(max_batches)
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        existing = self._files_locked()
        self._seq = 0
        if existing:
            self._seq = int(os.path.basename(existing[-1]).split(".")[0]) + 1
        # Conservation counters (batches and events) for soak artifacts.
        self.spooled_batches = 0
        self.spooled_events = 0
        self.drained_batches = 0
        self.drained_events = 0
        self.dropped_batches = 0
        self.dropped_events = 0
        self.truncated_batches = 0
        self._m_pending = obs_registry.gauge(
            "vep_spool_pending_batches", "Dead-letter batches awaiting re-drain", ("spool",)
        ).labels(os.path.basename(directory) or "spool")
        self._m_spooled = obs_registry.counter(
            "vep_spool_spooled_total", "Batches persisted to the dead-letter spool", ("spool",)
        ).labels(os.path.basename(directory) or "spool")
        self._m_drained = obs_registry.counter(
            "vep_spool_drained_total", "Spooled batches re-delivered on recovery", ("spool",)
        ).labels(os.path.basename(directory) or "spool")
        self._m_dropped = obs_registry.counter(
            "vep_spool_dropped_total", "Spooled batches evicted by size bounds", ("spool",)
        ).labels(os.path.basename(directory) or "spool")
        self._m_truncated = obs_registry.counter(
            "vep_spool_truncated_total",
            "Spooled batches with a torn tail salvaged on drain",
            ("spool",),
        ).labels(os.path.basename(directory) or "spool")
        self._m_pending.set(len(existing))

    # -- internal ---------------------------------------------------------

    def _files_locked(self) -> List[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.directory) if n.endswith(self.SUFFIX)
            )
        except FileNotFoundError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    @staticmethod
    def _encode(batch: Sequence[bytes]) -> bytes:
        parts = [_MAGIC, _U32.pack(len(batch))]
        for item in batch:
            parts.append(_U32.pack(len(item)))
            parts.append(item)
        return b"".join(parts)

    @staticmethod
    def _salvage(blob: bytes) -> tuple:
        """(items, missing) — the valid item prefix of a batch blob plus
        how many declared items the tail lost. A crash mid-write (or
        external truncation) tears the file at an arbitrary byte: every
        length-prefixed item before the tear is intact and recoverable,
        only the torn tail is gone. (None, 0) when nothing is usable —
        bad magic or a header too short to carry the count."""
        if not blob.startswith(_MAGIC):
            return None, 0
        off = len(_MAGIC)
        try:
            (count,) = _U32.unpack_from(blob, off)
        except struct.error:
            return None, 0
        off += _U32.size
        items: List[bytes] = []
        for _ in range(count):
            try:
                (n,) = _U32.unpack_from(blob, off)
            except struct.error:
                break
            off += _U32.size
            item = blob[off : off + n]
            if len(item) != n:
                break
            items.append(item)
            off += n
        return items, count - len(items)

    @staticmethod
    def _decode(blob: bytes) -> Optional[List[bytes]]:
        """Strict decode: a torn tail is corruption (None). The drain
        path uses :meth:`_salvage` instead — skip-and-count."""
        items, missing = DeadLetterSpool._salvage(blob)
        return items if items is not None and not missing else None

    def _evict_locked(self, incoming_bytes: int) -> None:
        files = self._files_locked()
        total = sum(os.path.getsize(p) for p in files)
        while files and (
            total + incoming_bytes > self.max_bytes or len(files) + 1 > self.max_batches
        ):
            victim = files.pop(0)
            try:
                size = os.path.getsize(victim)
                blob = open(victim, "rb").read()
                os.remove(victim)
            except OSError:
                continue
            total -= size
            items = self._decode(blob)
            self.dropped_batches += 1
            self.dropped_events += len(items) if items else 0
            self._m_dropped.inc()
            log.warning(
                "spool %s over bounds; evicted oldest batch %s",
                self.directory,
                os.path.basename(victim),
            )

    # -- public -----------------------------------------------------------

    def put(self, batch: Sequence[bytes]) -> Optional[str]:
        """Persist a batch; returns the file path, or None if it cannot fit."""
        blob = self._encode(batch)
        if len(blob) > self.max_bytes:
            return None
        with self._lock:
            self._evict_locked(len(blob))
            path = os.path.join(self.directory, f"{self._seq:012d}{self.SUFFIX}")
            self._seq += 1
            tmp = path + ".tmp"
            try:
                with open(tmp, "wb") as fh:
                    fh.write(blob)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
            except OSError as exc:
                log.error("spool write failed (%s); batch not persisted", exc)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return None
            self.spooled_batches += 1
            self.spooled_events += len(batch)
            self._m_spooled.inc()
            self._m_pending.set(len(self._files_locked()))
            return path

    def drain(self, handler: Callable[[List[bytes]], bool]) -> int:
        """Re-deliver spooled batches oldest-first through ``handler``.

        ``handler(items) -> True`` deletes the file and continues; False
        stops the drain so order is preserved for the next attempt (an
        exception propagates with the file likewise left in place).
        Returns the number of batches delivered.

        A batch with a torn tail (crash mid-write, external truncation)
        is *salvaged*, not dropped wholesale: the intact item prefix is
        delivered and only the missing tail items are counted into
        ``dropped_events`` (plus ``truncated_batches``). Files unusable
        past the header (bad magic, short header) are removed and
        counted as dropped batches.
        """
        delivered = 0
        while True:
            with self._lock:
                files = self._files_locked()
                if not files:
                    break
                path = files[0]
                try:
                    blob = open(path, "rb").read()
                except OSError:
                    break
                items, missing = self._salvage(blob)
                if items is None or not items:
                    # Nothing recoverable: bad magic/header, or the tear
                    # landed before the first item survived.
                    log.error("spool: corrupt batch %s removed", os.path.basename(path))
                    os.remove(path)
                    self.dropped_batches += 1
                    self.dropped_events += missing if items is not None else 0
                    self._m_dropped.inc()
                    self._m_pending.set(len(self._files_locked()))
                    continue
                if missing:
                    self.truncated_batches += 1
                    self.dropped_events += missing
                    self._m_truncated.inc()
                    log.warning(
                        "spool: batch %s torn mid-write; salvaged %d of %d items",
                        os.path.basename(path),
                        len(items),
                        len(items) + missing,
                    )
            # Handler runs outside the lock: it may post to the network.
            if not handler(items):
                break
            with self._lock:
                try:
                    os.remove(path)
                except OSError:
                    pass
                self.drained_batches += 1
                self.drained_events += len(items)
                self._m_drained.inc()
                self._m_pending.set(len(self._files_locked()))
            delivered += 1
        return delivered

    def pending(self) -> int:
        with self._lock:
            return len(self._files_locked())

    def pending_bytes(self) -> int:
        with self._lock:
            return sum(os.path.getsize(p) for p in self._files_locked())

    def pending_events(self) -> int:
        with self._lock:
            total = 0
            for path in self._files_locked():
                try:
                    items = self._decode(open(path, "rb").read())
                except OSError:
                    continue
                total += len(items) if items else 0
            return total

    def snapshot(self) -> dict:
        return {
            "dir": self.directory,
            "pending_batches": self.pending(),
            "pending_events": self.pending_events(),
            "spooled_batches": self.spooled_batches,
            "spooled_events": self.spooled_events,
            "drained_batches": self.drained_batches,
            "drained_events": self.drained_events,
            "dropped_batches": self.dropped_batches,
            "dropped_events": self.dropped_events,
            "truncated_batches": self.truncated_batches,
        }
