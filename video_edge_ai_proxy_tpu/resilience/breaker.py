"""Per-dependency circuit breaker (closed / open / half-open).

No reference counterpart: the reference hammers a dead dependency at
full call rate (go-redis reconnects per command, ``grpc_server.go``
posts every batch) and relies on the caller's error path. A
:class:`CircuitBreaker` turns a failing dependency into a *state*:
after ``failure_threshold`` consecutive failures the breaker opens and
callers fail fast (or degrade) without touching the network; after
``recovery_timeout_s`` one probe call is admitted (half-open) and its
outcome decides between closing and re-opening.

State and transition counters live in the obs metrics registry
(``vep_breaker_state{dep}``, ``vep_breaker_transitions_total{dep,to}``)
so soak artifacts and ``/metrics`` expose them; an optional
:class:`~..obs.watch.Watchdog` bound flags a breaker stuck open longer
than ``max_open_s`` once per episode.

The clock is injectable so tier-1 tests run sleep-free.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type

from ..obs import registry as obs_registry

log = logging.getLogger(__name__)

__all__ = ["BreakerOpen", "CircuitBreaker"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class BreakerOpen(RuntimeError):
    """Raised by :meth:`CircuitBreaker.call` when the breaker rejects a call."""

    def __init__(self, name: str, retry_in_s: float):
        super().__init__(f"circuit breaker '{name}' is open (retry in {retry_in_s:.1f}s)")
        self.name = name
        self.retry_in_s = retry_in_s


class CircuitBreaker:
    """Thread-safe three-state breaker guarding one named dependency.

    Instances share the registry metric families; the ``dep`` label
    separates dependencies. ``allow()``/``record_success()``/
    ``record_failure()`` compose with hand-rolled call sites (the bus
    read path degrades instead of raising); ``call(fn)`` wraps the
    common raise-on-open shape.
    """

    def __init__(
        self,
        name: str,
        *,
        failure_threshold: int = 5,
        recovery_timeout_s: float = 30.0,
        max_open_s: float = 300.0,
        clock: Callable[[], float] = time.monotonic,
        watchdog=None,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.recovery_timeout_s = float(recovery_timeout_s)
        self.max_open_s = float(max_open_s)
        self._clock = clock
        self._watchdog = watchdog
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_at: Optional[float] = None
        #: transition counts by target state, for soak artifacts.
        self.transitions: Dict[str, int] = {}
        self._m_state = obs_registry.gauge(
            "vep_breaker_state",
            "Circuit breaker state (0=closed, 1=open, 2=half_open)",
            ("dep",),
        ).labels(name)
        self._m_trans = obs_registry.counter(
            "vep_breaker_transitions_total",
            "Circuit breaker state transitions",
            ("dep", "to"),
        )
        self._m_state.set(0)

    # -- state machine ----------------------------------------------------

    def _transition(self, to: str, now: float) -> None:
        # Caller holds self._lock.
        if to == self._state:
            return
        level = logging.WARNING if to == OPEN else logging.INFO
        log.log(level, "breaker '%s': %s -> %s", self.name, self._state, to)
        self._state = to
        self.transitions[to] = self.transitions.get(to, 0) + 1
        self._m_state.set(_STATE_CODE[to])
        self._m_trans.labels(self.name, to).inc()
        if to == OPEN:
            self._opened_at = now
            self._probe_at = None
        elif to == CLOSED:
            self._failures = 0
            self._probe_at = None

    def allow(self) -> bool:
        """True if a call may proceed now (admits the half-open probe)."""
        now = self._clock()
        with self._lock:
            if self._state == OPEN:
                open_for = now - self._opened_at
                if self._watchdog is not None:
                    self._watchdog.check(
                        f"breaker_{self.name}_open",
                        open_for,
                        above=self.max_open_s,
                        detail=f"breaker '{self.name}' open for {open_for:.0f}s",
                    )
                if open_for >= self.recovery_timeout_s:
                    self._transition(HALF_OPEN, now)
                else:
                    return False
            if self._state == HALF_OPEN:
                # One probe in flight at a time; if the probe's owner died
                # without recording an outcome, re-admit after another
                # recovery window rather than wedging half-open forever.
                if self._probe_at is not None and now - self._probe_at < self.recovery_timeout_s:
                    return False
                self._probe_at = now
                return True
            if self._watchdog is not None:
                self._watchdog.check(
                    f"breaker_{self.name}_open", 0.0, above=self.max_open_s
                )
            return True

    def record_success(self) -> None:
        now = self._clock()
        with self._lock:
            self._failures = 0
            if self._state != CLOSED:
                self._transition(CLOSED, now)

    def record_failure(self) -> None:
        now = self._clock()
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition(OPEN, now)
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._transition(OPEN, now)

    # -- conveniences -----------------------------------------------------

    def call(self, fn: Callable[[], object], *, excluded: Tuple[Type[BaseException], ...] = ()):
        """Run ``fn`` under the breaker; raise :class:`BreakerOpen` if open.

        Exceptions in ``excluded`` count as the dependency *answering*
        (e.g. an HTTP 403): they record success and re-raise.
        """
        if not self.allow():
            with self._lock:
                retry_in = max(
                    0.0, self.recovery_timeout_s - (self._clock() - self._opened_at)
                )
            raise BreakerOpen(self.name, retry_in)
        try:
            out = fn()
        except excluded:
            self.record_success()
            raise
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return out

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def time_in_open_s(self) -> float:
        """Seconds the breaker has currently been open (0 unless open)."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._clock() - self._opened_at)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "state": self._state,
                "failures": self._failures,
                "transitions": dict(self.transitions),
            }
