"""Retry policy with decorrelated-jitter backoff + deadline budgets.

No reference counterpart: the reference proxy retries nothing — a failed
annotation POST is dropped (``grpc_server.go:204-217``) and a failed
Redis call surfaces to the caller; recovery is Docker restart-always.
Here every remote call site composes an explicit :class:`RetryPolicy`
bounded by a :class:`Deadline`, so retries never exceed the caller's
remaining time budget and never synchronize across a fleet (decorrelated
jitter, AWS architecture-blog algorithm: ``delay = min(cap,
uniform(base, prev * 3))``).

Clock, sleep, and RNG are injectable so tier-1 tests and the replay
harness stay deterministic and sleep-free.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Sequence, Tuple, Type

__all__ = ["Deadline", "DeadlineExceeded", "RetryPolicy"]


class DeadlineExceeded(TimeoutError):
    """A deadline budget was exhausted before the work completed."""


class Deadline:
    """An absolute point on a monotonic clock that nested calls share.

    Pass one ``Deadline`` down a call chain and clamp every per-attempt
    timeout with :meth:`clamp`; the sum of nested waits can then never
    exceed the top-level budget, no matter how retries interleave.
    """

    __slots__ = ("_at", "_clock")

    def __init__(self, at_s: float, *, clock: Callable[[], float] = time.monotonic):
        self._at = float(at_s)
        self._clock = clock

    @classmethod
    def after(
        cls, seconds: float, *, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        """Deadline ``seconds`` from now on ``clock``."""
        return cls(clock() + float(seconds), clock=clock)

    def remaining(self) -> float:
        """Seconds left; never negative."""
        return max(0.0, self._at - self._clock())

    @property
    def expired(self) -> bool:
        return self._clock() >= self._at

    def clamp(self, timeout_s: float) -> float:
        """Shrink a per-attempt timeout to the remaining budget."""
        return min(float(timeout_s), self.remaining())

    def check(self, what: str = "deadline") -> None:
        """Raise :class:`DeadlineExceeded` if the budget is spent."""
        if self.expired:
            raise DeadlineExceeded(f"{what}: deadline exceeded")

    def sub(self, seconds: float) -> "Deadline":
        """A child budget: at most ``seconds`` from now, never past self."""
        return Deadline(min(self._at, self._clock() + float(seconds)), clock=self._clock)


class RetryPolicy:
    """Bounded retries with decorrelated-jitter exponential backoff.

    ``next_delay(prev)`` draws ``min(cap, uniform(base, max(base, prev*3)))``
    — decorrelated jitter spreads a fleet's retries instead of
    synchronizing them into thundering herds. ``run(fn)`` drives the loop:
    attempts are capped by ``max_attempts`` and, when a ``deadline`` is
    given, sleeps are clamped so the whole loop fits the caller's budget.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        base_s: float = 0.1,
        cap_s: float = 5.0,
        *,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self._sleep = sleep

    def next_delay(self, prev_s: Optional[float] = None) -> float:
        """Next backoff delay given the previous one (None = first retry)."""
        prev = self.base_s if not prev_s else float(prev_s)
        return min(self.cap_s, self._rng.uniform(self.base_s, max(self.base_s, prev * 3.0)))

    def run(
        self,
        fn: Callable[[], object],
        *,
        retry_on: Tuple[Type[BaseException], ...] = (Exception,),
        abort_on: Tuple[Type[BaseException], ...] = (),
        should_retry: Optional[Callable[[BaseException], bool]] = None,
        deadline: Optional[Deadline] = None,
        on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    ) -> object:
        """Call ``fn`` until it succeeds, retries exhaust, or deadline spends.

        An exception is retried iff ``should_retry(exc)`` (when given) or
        ``isinstance(exc, retry_on) and not isinstance(exc, abort_on)``.
        Terminal exceptions re-raise immediately. With a ``deadline``, the
        loop never sleeps past the remaining budget: if the next delay
        would overrun it, the last failure re-raises instead.
        """
        prev_delay: Optional[float] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except BaseException as exc:  # noqa: B902 - classified below
                if should_retry is not None:
                    retryable = should_retry(exc)
                else:
                    retryable = isinstance(exc, retry_on) and not isinstance(exc, abort_on)
                if not retryable or attempt >= self.max_attempts:
                    raise
                delay = self.next_delay(prev_delay)
                if deadline is not None:
                    budget = deadline.remaining()
                    if budget <= 0.0 or delay > budget:
                        raise
                    delay = min(delay, budget)
                prev_delay = delay
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                if delay > 0.0:
                    self._sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover
