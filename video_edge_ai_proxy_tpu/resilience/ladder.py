"""Engine overload degradation ladder (state machine only; the engine
applies the rungs).

No reference counterpart: under overload the reference's per-camera
decode loops simply fall behind (latest-frame-wins ring hides the lag,
``rtsp_to_rtmp.py:144-145``) and the annotation queue sheds newest-first
at ``unacked_limit``. A fused TPU serving plane needs an *explicit*
policy instead, because one slow tick stalls every stream in the batch.

Rungs, in escalation order (each includes the previous):

1. ``normal``           — nothing.
2. ``shed``             — drop frames older than a staleness bound
                          before dispatch (oldest-first, per group).
3. ``shed_to_fleet``    — ask the FLEET ROUTER to move this engine's
                          lowest-priority streams to healthy peers
                          (serve/router.py scrapes ``vep_ladder_rung``
                          and executes the drain→cutover→resume
                          migration). Engine-side behavior is identical
                          to ``shed``; the rung exists so horizontal
                          re-placement engages BEFORE the local ladder
                          starts shrinking device programs. Skipped
                          entirely (the walk goes shed →
                          bucket_downshift, same as pre-r16) unless a
                          router registered via :meth:`register_fleet`
                          — single-engine deployments never see it.
4. ``bucket_downshift`` — cap the collector's batch bucket at the
                          next-smaller size so device programs shrink.
5. ``admission_pause``  — pause admission for a deterministic half of
                          the streams; the rest keep their latency SLO.

Pressure is ``queue_depth >= depth_threshold`` (drain backpressure),
``tick_lag_s > lag_factor * tick_budget_s`` (tick staleness), or — since
r9 — ``slo_burning`` (a sustained multi-window SLO budget burn,
obs/slo.py), so the engine starts shedding while the *user-visible*
objective degrades, before queues physically back up. The ladder
escalates one rung after ``escalate_after_s`` of *continuous* pressure
(the timer restarts at each transition, so reaching rung N takes N
windows) and recovers one rung per ``recover_after_s`` pressure-free.
Transitions are counted in the obs registry (``vep_ladder_rung``,
``vep_ladder_transitions_total{to}``) and a degraded episode is logged
once via the engine watchdog, not once per tick.

The clock is injectable so rung tests run on fake time, sleep-free.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from ..obs import registry as obs_registry

log = logging.getLogger(__name__)

__all__ = ["RUNGS", "DegradationLadder"]

RUNGS = ("normal", "shed", "shed_to_fleet", "bucket_downshift",
         "admission_pause")
_FLEET_IDX = RUNGS.index("shed_to_fleet")


class DegradationLadder:
    """Hysteretic escalate/recover state machine over :data:`RUNGS`."""

    def __init__(
        self,
        *,
        escalate_after_s: float = 0.5,
        recover_after_s: float = 2.0,
        depth_threshold: int = 2,
        lag_factor: float = 3.0,
        clock: Callable[[], float] = time.monotonic,
        watchdog=None,
        journal=None,
    ):
        self.escalate_after_s = float(escalate_after_s)
        self.recover_after_s = float(recover_after_s)
        self.depth_threshold = int(depth_threshold)
        self.lag_factor = float(lag_factor)
        self._clock = clock
        self._watchdog = watchdog
        # r23 decision journal: every transition is an audit event whose
        # trigger is the pressure breakdown observe() stashed, and whose
        # cause links back — deeper escalations chain to the previous
        # transition; a fresh escalation under SLO burn chains to the
        # slo episode_open event (the "SLO burn -> ladder rung" link).
        self.journal = journal
        self.last_transition_seq: Optional[int] = None
        self._pressure_detail: Dict = {}
        self._lock = threading.Lock()
        self._rung = 0
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        # Fleet-router hook (r16): None means the shed_to_fleet rung is
        # skipped by the escalate/recover walk, preserving the exact
        # pre-r16 rung sequence and timings for single-engine engines.
        self._fleet_cb: Optional[Callable[[bool], None]] = None
        self._fleet_info: Optional[Dict] = None
        #: transition counts by target rung name, for soak artifacts.
        self.transitions: Dict[str, int] = {}
        self._m_rung = obs_registry.gauge(
            "vep_ladder_rung",
            "Engine degradation ladder rung (0=normal .. 4=admission_pause;"
            " 2=shed_to_fleet only when a fleet router is attached)",
        ).labels()
        self._m_trans = obs_registry.counter(
            "vep_ladder_transitions_total", "Degradation ladder transitions", ("to",)
        )
        self._m_rung.set(0)

    def _to(self, idx: int) -> None:
        # Caller holds self._lock.
        prev = self._rung
        name = RUNGS[idx]
        seq = None
        if self.journal is not None:
            trigger = dict(self._pressure_detail)
            trigger["from"] = RUNGS[prev]
            trigger["to"] = name
            if idx > prev:
                action = "escalate"
                cause = self.last_transition_seq if prev != 0 else None
                if cause is None and trigger.get("slo_burning"):
                    # Fresh excursion attributed to SLO burn: root the
                    # chain at the slo episode_open event.
                    cause = self.journal.latest_seq(
                        actor="slo", action="episode_open")
            else:
                action = "recover"
                cause = self.last_transition_seq
            seq = self.journal.record(
                "ladder", action, subject=("ladder", "engine"),
                trigger=trigger, cause=cause)
            self.last_transition_seq = seq
        level = logging.WARNING if idx > prev else logging.INFO
        log.log(level, "degradation ladder: %s -> %s", RUNGS[prev], name,
                extra={"vep_actor": "ladder",
                       "vep_subject": "ladder:engine",
                       "vep_journal_seq": seq})
        self._rung = idx
        self.transitions[name] = self.transitions.get(name, 0) + 1
        self._m_rung.set(idx)
        self._m_trans.labels(name).inc()

    def _step(self, direction: int) -> int:
        """Next rung index one step in ``direction`` (+1 escalate /
        -1 recover), skipping shed_to_fleet when no router is registered
        so unrouted deployments keep the pre-r16 4-rung walk. Caller
        holds self._lock."""
        nxt = self._rung + direction
        if nxt == _FLEET_IDX and self._fleet_cb is None:
            nxt += direction
        return nxt

    # -- fleet router hook (r16) --

    def register_fleet(self, callback: Callable[[bool], None],
                       info: Optional[Dict] = None) -> None:
        """Arm the shed_to_fleet rung. ``callback(active)`` fires with
        True on entering the rung and False on leaving it (either
        direction) — outside the ladder lock, exceptions swallowed; keep
        it non-blocking (set a flag/gauge, wake a router thread).
        ``info`` is surfaced verbatim in :meth:`snapshot` and the
        /api/v1/router state route (who attached, from where)."""
        with self._lock:
            self._fleet_cb = callback
            self._fleet_info = dict(info or {})

    def unregister_fleet(self) -> None:
        """Disarm shed_to_fleet (walk reverts to the 4-rung sequence).
        If currently AT the rung, the next transition steps over it."""
        with self._lock:
            self._fleet_cb = None
            self._fleet_info = None

    @property
    def fleet_info(self) -> Optional[Dict]:
        with self._lock:
            return dict(self._fleet_info) if self._fleet_info is not None \
                else None

    def observe(self, *, queue_depth: int, tick_lag_s: float,
                tick_budget_s: float, slo_burning: bool = False,
                hbm_pressure: bool = False) -> str:
        """Feed one tick's pressure signals; returns the current rung name.
        ``slo_burning`` is the SLO engine's aggregate burn verdict — an
        SLO-level pressure source ORed with the queue-level ones, subject
        to the same escalate/recover hysteresis. ``hbm_pressure`` (r21,
        obs/hbm.py) is the device-memory verdict — burning HBM or an OOM
        forecast inside the horizon sheds/stretches BEFORE the allocator
        fails, under the same hysteresis."""
        now = self._clock()
        pressure = (
            queue_depth >= self.depth_threshold
            or tick_lag_s > self.lag_factor * tick_budget_s
            or slo_burning
            or hbm_pressure
        )
        fleet_edge: Optional[bool] = None
        with self._lock:
            # Stash the breakdown so a transition this tick can journal
            # WHICH signal forced it (r23 trigger attribution).
            self._pressure_detail = {
                "queue_depth": int(queue_depth),
                "tick_lag_s": round(float(tick_lag_s), 4),
                "tick_budget_s": round(float(tick_budget_s), 4),
                "slo_burning": bool(slo_burning),
                "hbm_pressure": bool(hbm_pressure),
            }
            was_fleet = self._rung == _FLEET_IDX
            if pressure:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (
                    now - self._pressure_since >= self.escalate_after_s
                    and self._rung < len(RUNGS) - 1
                ):
                    self._to(self._step(+1))
                    self._pressure_since = now
            else:
                self._pressure_since = None
                if self._rung > 0:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif now - self._calm_since >= self.recover_after_s:
                        self._to(self._step(-1))
                        self._calm_since = now
                else:
                    self._calm_since = None
            is_fleet = self._rung == _FLEET_IDX
            if is_fleet != was_fleet:
                fleet_edge = is_fleet
            cb = self._fleet_cb
            rung = self._rung
        if fleet_edge is not None and cb is not None:
            try:
                cb(fleet_edge)
            except Exception:  # noqa: BLE001 — router hook must not kill ticks
                log.exception("fleet shed callback failed")
        if self._watchdog is not None:
            # Watchdog opens one "degraded" episode across the whole
            # excursion and logs recovery when the ladder returns to normal.
            self._watchdog.check(
                "engine_degraded",
                float(rung),
                above=0.0,
                detail=f"degradation ladder at '{RUNGS[rung]}'",
            )
        return RUNGS[rung]

    @property
    def rung(self) -> str:
        with self._lock:
            return RUNGS[self._rung]

    @property
    def rung_index(self) -> int:
        with self._lock:
            return self._rung

    def snapshot(self) -> dict:
        with self._lock:
            out = {"rung": RUNGS[self._rung],
                   "transitions": dict(self.transitions),
                   "fleet_attached": self._fleet_cb is not None}
            if self._fleet_info is not None:
                out["fleet"] = dict(self._fleet_info)
            return out
