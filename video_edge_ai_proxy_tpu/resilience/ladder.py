"""Engine overload degradation ladder (state machine only; the engine
applies the rungs).

No reference counterpart: under overload the reference's per-camera
decode loops simply fall behind (latest-frame-wins ring hides the lag,
``rtsp_to_rtmp.py:144-145``) and the annotation queue sheds newest-first
at ``unacked_limit``. A fused TPU serving plane needs an *explicit*
policy instead, because one slow tick stalls every stream in the batch.

Rungs, in escalation order (each includes the previous):

1. ``normal``           — nothing.
2. ``shed``             — drop frames older than a staleness bound
                          before dispatch (oldest-first, per group).
3. ``bucket_downshift`` — cap the collector's batch bucket at the
                          next-smaller size so device programs shrink.
4. ``admission_pause``  — pause admission for a deterministic half of
                          the streams; the rest keep their latency SLO.

Pressure is ``queue_depth >= depth_threshold`` (drain backpressure),
``tick_lag_s > lag_factor * tick_budget_s`` (tick staleness), or — since
r9 — ``slo_burning`` (a sustained multi-window SLO budget burn,
obs/slo.py), so the engine starts shedding while the *user-visible*
objective degrades, before queues physically back up. The ladder
escalates one rung after ``escalate_after_s`` of *continuous* pressure
(the timer restarts at each transition, so reaching rung N takes N
windows) and recovers one rung per ``recover_after_s`` pressure-free.
Transitions are counted in the obs registry (``vep_ladder_rung``,
``vep_ladder_transitions_total{to}``) and a degraded episode is logged
once via the engine watchdog, not once per tick.

The clock is injectable so rung tests run on fake time, sleep-free.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from ..obs import registry as obs_registry

log = logging.getLogger(__name__)

__all__ = ["RUNGS", "DegradationLadder"]

RUNGS = ("normal", "shed", "bucket_downshift", "admission_pause")


class DegradationLadder:
    """Hysteretic escalate/recover state machine over :data:`RUNGS`."""

    def __init__(
        self,
        *,
        escalate_after_s: float = 0.5,
        recover_after_s: float = 2.0,
        depth_threshold: int = 2,
        lag_factor: float = 3.0,
        clock: Callable[[], float] = time.monotonic,
        watchdog=None,
    ):
        self.escalate_after_s = float(escalate_after_s)
        self.recover_after_s = float(recover_after_s)
        self.depth_threshold = int(depth_threshold)
        self.lag_factor = float(lag_factor)
        self._clock = clock
        self._watchdog = watchdog
        self._lock = threading.Lock()
        self._rung = 0
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        #: transition counts by target rung name, for soak artifacts.
        self.transitions: Dict[str, int] = {}
        self._m_rung = obs_registry.gauge(
            "vep_ladder_rung",
            "Engine degradation ladder rung (0=normal .. 3=admission_pause)",
        ).labels()
        self._m_trans = obs_registry.counter(
            "vep_ladder_transitions_total", "Degradation ladder transitions", ("to",)
        )
        self._m_rung.set(0)

    def _to(self, idx: int) -> None:
        # Caller holds self._lock.
        name = RUNGS[idx]
        level = logging.WARNING if idx > self._rung else logging.INFO
        log.log(level, "degradation ladder: %s -> %s", RUNGS[self._rung], name)
        self._rung = idx
        self.transitions[name] = self.transitions.get(name, 0) + 1
        self._m_rung.set(idx)
        self._m_trans.labels(name).inc()

    def observe(self, *, queue_depth: int, tick_lag_s: float,
                tick_budget_s: float, slo_burning: bool = False) -> str:
        """Feed one tick's pressure signals; returns the current rung name.
        ``slo_burning`` is the SLO engine's aggregate burn verdict — an
        SLO-level pressure source ORed with the queue-level ones, subject
        to the same escalate/recover hysteresis."""
        now = self._clock()
        pressure = (
            queue_depth >= self.depth_threshold
            or tick_lag_s > self.lag_factor * tick_budget_s
            or slo_burning
        )
        with self._lock:
            if pressure:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (
                    now - self._pressure_since >= self.escalate_after_s
                    and self._rung < len(RUNGS) - 1
                ):
                    self._to(self._rung + 1)
                    self._pressure_since = now
            else:
                self._pressure_since = None
                if self._rung > 0:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif now - self._calm_since >= self.recover_after_s:
                        self._to(self._rung - 1)
                        self._calm_since = now
                else:
                    self._calm_since = None
            rung = self._rung
        if self._watchdog is not None:
            # Watchdog opens one "degraded" episode across the whole
            # excursion and logs recovery when the ladder returns to normal.
            self._watchdog.check(
                "engine_degraded",
                float(rung),
                above=0.0,
                detail=f"degradation ladder at '{RUNGS[rung]}'",
            )
        return RUNGS[rung]

    @property
    def rung(self) -> str:
        with self._lock:
            return RUNGS[self._rung]

    @property
    def rung_index(self) -> int:
        with self._lock:
            return self._rung

    def snapshot(self) -> dict:
        with self._lock:
            return {"rung": RUNGS[self._rung], "transitions": dict(self.transitions)}
