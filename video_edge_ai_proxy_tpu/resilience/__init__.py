"""Fault-domain isolation primitives (retry/backoff, deadlines, breakers,
dead-letter spooling, engine degradation ladder).

No reference counterpart: the reference proxy leans on Docker
``--restart always`` (``rtsp_process_manager.go:76``) and go-redis
connection pools for all of its fault handling, so every remote
dependency is one naked call deep. Here failure is a first-class,
bounded state: callers compose a RetryPolicy (decorrelated-jitter
backoff under a Deadline budget), a per-dependency CircuitBreaker, and —
for data that must not be dropped — a bounded on-disk DeadLetterSpool.
The engine's overload behavior is the DegradationLadder.

Everything in this package is pure Python (no jax), deterministic under
injected clocks, and safe to import from control-plane code.
"""

from .breaker import BreakerOpen, CircuitBreaker
from .ladder import RUNGS, DegradationLadder
from .policy import Deadline, DeadlineExceeded, RetryPolicy
from .spool import DeadLetterSpool

__all__ = [
    "BreakerOpen",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "DegradationLadder",
    "DeadLetterSpool",
    "RetryPolicy",
    "RUNGS",
]
