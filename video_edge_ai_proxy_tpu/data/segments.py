"""Training data from the edge archive.

Closes the loop the reference leaves open: ingest workers already archive
GOP segments to disk (`ingest/archive.py`, naming contract
``<device_id>/<start_ms>_<duration_ms>.{mp4,npz}`` from the reference's
``python/archive.py:75``); this module turns that archive into training
batches for `parallel.make_trainer` — fine-tune on the site's own footage.

Segments are read with OpenCV (mp4) or numpy (npz fallback written when no
encoder backend existed). Decoding happens in a background thread pool so
the accelerator never waits on video IO (host pipeline, SURVEY.md §2.3 P2).
"""

from __future__ import annotations

import os
import queue
import random
import threading
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import get_logger

log = get_logger("data.segments")


@dataclass(frozen=True)
class SegmentRef:
    device_id: str
    path: str
    start_ms: int
    duration_ms: int


@dataclass(frozen=True)
class SampleMeta:
    """Identity of one training sample: which camera, which archived
    segment, which frame within it (the clip's first frame for clips).
    This is the join key supervised fine-tuning needs to attach per-frame
    labels — `examples/self_train.py` pools labels because the loader
    used to discard identity; `Loader(with_meta=True)` closes that gap."""

    device_id: str
    start_ms: int
    frame_idx: int


def scan_archive(root: str, device_ids: Optional[Sequence[str]] = None) -> List[SegmentRef]:
    """Walk ``<root>/<device_id>/<start>_<dur>.{mp4,npz}`` into refs,
    sorted by (device, start time)."""
    refs: List[SegmentRef] = []
    if not os.path.isdir(root):
        return refs
    for device_id in sorted(os.listdir(root)):
        if device_ids is not None and device_id not in device_ids:
            continue
        dev_dir = os.path.join(root, device_id)
        if not os.path.isdir(dev_dir):
            continue
        for name in sorted(os.listdir(dev_dir)):
            stem, ext = os.path.splitext(name)
            if ext not in (".mp4", ".npz"):
                continue
            parts = stem.split("-")[0].split("_")
            try:
                start_ms, dur_ms = int(parts[0]), int(parts[1])
            except (IndexError, ValueError):
                continue
            refs.append(SegmentRef(device_id, os.path.join(dev_dir, name),
                                   start_ms, dur_ms))
    # Numeric, not lexicographic: '10000_' sorts before '9000_' as strings.
    refs.sort(key=lambda r: (r.device_id, r.start_ms))
    return refs


def read_segment(ref: SegmentRef) -> np.ndarray:
    """Decode one segment -> [T, H, W, 3] uint8 BGR."""
    if ref.path.endswith(".npz"):
        with np.load(ref.path) as z:
            return np.asarray(z["frames"], np.uint8)
    import cv2

    cap = cv2.VideoCapture(ref.path)
    frames = []
    try:
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            frames.append(frame)
    finally:
        cap.release()
    if not frames:
        raise IOError(f"no frames decodable in {ref.path}")
    return np.stack(frames).astype(np.uint8)


class SegmentDataset:
    """Iterable of fixed-shape samples drawn from archived segments.

    ``clip_len=0`` yields single frames [H, W, 3]; ``clip_len=T`` yields
    clips [T, H, W, 3] cut from consecutive frames. All samples are resized
    (anisotropically — no crop) to ``size`` so batches are
    shape-homogeneous regardless of per-camera resolutions.
    """

    def __init__(
        self,
        root: str,
        *,
        size: Tuple[int, int] = (224, 224),
        clip_len: int = 0,
        device_ids: Optional[Sequence[str]] = None,
        seed: int = 0,
    ):
        self.refs = scan_archive(root, device_ids)
        self.size = size
        self.clip_len = clip_len
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        return len(self.refs)

    def _fit(self, frames: np.ndarray) -> np.ndarray:
        import cv2

        h, w = self.size
        if frames.shape[1:3] != (h, w):
            frames = np.stack(
                [cv2.resize(f, (w, h), interpolation=cv2.INTER_AREA)
                 for f in frames]
            )
        return frames

    def samples_from(self, ref: SegmentRef) -> Iterator[np.ndarray]:
        for _, sample in self.indexed_samples_from(ref):
            yield sample

    def indexed_samples_from(
        self, ref: SegmentRef
    ) -> Iterator[Tuple[int, np.ndarray]]:
        """Like `samples_from` but yields ``(frame_idx, sample)`` so callers
        can join per-frame labels (`SampleMeta`)."""
        try:
            frames = self._fit(read_segment(ref))
        except Exception as exc:
            log.warning("skipping unreadable segment %s: %s", ref.path, exc)
            return
        if self.clip_len:
            for start in range(0, len(frames) - self.clip_len + 1, self.clip_len):
                yield start, frames[start:start + self.clip_len]
        else:
            for i, frame in enumerate(frames):
                yield i, frame

    def shuffled_refs(self) -> List[SegmentRef]:
        refs = list(self.refs)
        self._rng.shuffle(refs)
        return refs


class Loader:
    """Background-decoded, shuffled batcher: iterate numpy batches
    [B, (T,) H, W, 3] uint8, ready for `Trainer.shard_batch`.

    ``with_meta=True`` yields ``(batch, metas)`` instead, where ``metas``
    is a list of `SampleMeta` aligned with batch rows — the label join for
    supervised fine-tuning on archived footage."""

    def __init__(self, dataset: SegmentDataset, batch_size: int,
                 prefetch: int = 4, drop_last: bool = True,
                 with_meta: bool = False):
        if prefetch < 1:
            # queue.Queue(0) would mean UNBOUNDED readahead, not none.
            raise ValueError("prefetch must be >= 1")
        self.dataset = dataset
        self.batch_size = batch_size
        self.prefetch = prefetch
        self.drop_last = drop_last
        self.with_meta = with_meta

    def __iter__(self) -> Iterator[np.ndarray]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        DONE = object()
        stop = threading.Event()
        error: List[BaseException] = []

        def put(item) -> bool:
            # Bounded put that notices consumer abandonment, so a
            # steps-bounded training loop doesn't leak a blocked thread.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def emit(batch, metas) -> bool:
            stacked = np.stack(batch)
            return put((stacked, metas) if self.with_meta else stacked)

        def producer():
            try:
                batch: List[np.ndarray] = []
                metas: List[SampleMeta] = []
                for ref in self.dataset.shuffled_refs():
                    if stop.is_set():
                        return
                    for idx, sample in self.dataset.indexed_samples_from(ref):
                        batch.append(sample)
                        metas.append(SampleMeta(ref.device_id, ref.start_ms, idx))
                        if len(batch) == self.batch_size:
                            if not emit(batch, metas):
                                return
                            batch, metas = [], []
                if batch and not self.drop_last:
                    emit(batch, metas)
            except BaseException as exc:  # surfaced in the consumer
                error.append(exc)
            finally:
                put(DONE)

        thread = threading.Thread(target=producer, name="segment-loader",
                                  daemon=True)
        thread.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()
