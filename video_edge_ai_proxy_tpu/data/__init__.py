"""Training data pipeline: archived edge footage -> device batches."""

from .segments import (
    Loader, SampleMeta, SegmentDataset, SegmentRef, read_segment, scan_archive,
)

__all__ = ["Loader", "SampleMeta", "SegmentDataset", "SegmentRef",
           "read_segment", "scan_archive"]
