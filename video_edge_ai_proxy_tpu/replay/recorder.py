"""Flight-recorder taps (ISSUE r6 tentpole part 1).

Two tap points, matching where frames exist in the pipeline:

- Ingest worker (``ingest/worker.py``): set ``vep_trace_dir`` (env / the
  ``--trace_dir`` flag) and every worker writes
  ``<dir>/<device_id>.vtrace`` as it publishes — packet-level truth
  (pts/dts/keyframe flags, arrival offsets). Synthetic sources record the
  pattern seed instead of pixels (tiny traces, byte-identical replay).
- Bus publish path: wrap any FrameBus in :class:`RecordingBus` and every
  ``publish`` is captured — the tap for embedded/in-process pipelines
  (the soak harness) where there is no worker subprocess.

``record_synthetic_trace`` synthesizes a trace directly (no pipeline
required): the deterministic traffic generator for soak/e2e runs, with
exact fps-grid arrival times so two recordings of the same spec are
identical files (modulo the header timestamp).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .trace import TraceWriter


class TraceRecorder:
    """Thread-safe facade over TraceWriter with per-stream bookkeeping."""

    def __init__(self, path: str):
        self._w = TraceWriter(path)
        self._lock = threading.Lock()
        self._streams: set[str] = set()

    @property
    def path(self) -> str:
        return self._w.path

    def record_stream(
        self, device_id: str, *, width: int, height: int,
        fps: float = 0.0, gop: int = 0, kind: str = "",
    ) -> None:
        with self._lock:
            if device_id in self._streams:
                return
            self._streams.add(device_id)
        self._w.stream_event(
            device_id, width=width, height=height, fps=fps, gop=gop,
            kind=kind)

    def record_frame(
        self, device_id: str, frame: np.ndarray, meta,
        synth: Optional[dict] = None,
    ) -> None:
        """One published frame. ``meta`` is a bus FrameMeta (or anything
        with pts/dts/is_keyframe/packet/timestamp_ms/time_base). ``synth``
        = {"w","h","n"} replaces the payload with a pattern seed."""
        if device_id not in self._streams:
            self.record_stream(
                device_id, width=frame.shape[1], height=frame.shape[0])
        self._w.frame_event(
            device_id,
            pts=getattr(meta, "pts", 0),
            dts=getattr(meta, "dts", 0),
            is_keyframe=bool(getattr(meta, "is_keyframe", False)),
            packet=int(getattr(meta, "packet", 0)),
            timestamp_ms=int(getattr(meta, "timestamp_ms", 0)),
            time_base=float(getattr(meta, "time_base", 1.0 / 90000.0)),
            synth=synth,
            frame=None if synth is not None else frame,
        )

    def close(self) -> None:
        self._w.close()


class RecordingBus:
    """FrameBus proxy that records every publish into a trace — the bus
    publish tap. Everything else (reads, KV, doorbell) delegates
    untouched, so it drops in anywhere a FrameBus goes."""

    def __init__(self, bus, recorder: TraceRecorder,
                 synth_of: Optional[callable] = None):
        self._bus = bus
        self._recorder = recorder
        # synth_of(device_id, meta) -> {"w","h","n"} | None: lets callers
        # that KNOW their frames are synthetic (soak harness) store seeds
        # instead of payloads.
        self._synth_of = synth_of

    def __getattr__(self, name):
        return getattr(self._bus, name)

    def publish(self, device_id: str, frame, meta) -> int:
        synth = self._synth_of(device_id, meta) if self._synth_of else None
        self._recorder.record_frame(device_id, frame, meta, synth=synth)
        return self._bus.publish(device_id, frame, meta)


def record_synthetic_trace(
    path: str, device_ids, *, width: int, height: int, fps: float = 30.0,
    gop: int = 30, frames: int = 300, start_ms: int = 1_700_000_000_000,
) -> str:
    """Write a deterministic multi-camera trace of SyntheticSource
    traffic without running any pipeline: frame n of camera i arrives at
    t = n/fps (all cameras in phase, like a fleet of genlocked test
    cameras), pts on the 90 kHz grid, keyframes every ``gop``. Epoch
    timestamps start at the fixed ``start_ms`` so two recordings of the
    same spec replay identically."""
    w = TraceWriter(path)
    # Bypass the wall clock entirely: events carry computed t_ms.
    for device_id in device_ids:
        w.append({
            "ev": "stream", "device": device_id, "t_ms": 0.0,
            "w": int(width), "h": int(height), "fps": float(fps),
            "gop": int(gop), "kind": "synthetic",
        })
    for n in range(frames):
        t_ms = round(n * 1000.0 / fps, 3)
        pts = int(n * 90000 / fps)
        for device_id in device_ids:
            w.append({
                "ev": "frame", "device": device_id, "t_ms": t_ms,
                "pts": pts, "dts": pts, "key": (n % gop == 0),
                "packet": n, "ts_ms": int(start_ms + t_ms),
                "tb": 1.0 / 90000.0,
                "synth": {"w": int(width), "h": int(height), "n": n},
            })
    w.close()
    return path
