"""Replay-driven soak + determinism harness (ISSUE r6 tentpole part 3).

Four entry points, all consumed by ``tools/soak_replay.py``:

- :func:`lockstep_checksum` — deterministic replay of a trace through the
  real pipeline stages (bus -> collector -> serving step), folding the
  shared content checksum (replay/checksum.py) over every output. No wall
  clock, no threads: every frame is delivered exactly once, so two runs
  of the same trace are bit-identical — THE record->replay determinism
  claim, and the host for the seeded-numerics-fault test.
- :func:`run_fleet_soak` — in-process fleet soak: N replay-driven cameras
  (6 detect + 5 embed + 5 classify by default) on the in-proc bus, one
  InferenceEngine with per-stream model routing, the REAL annotation
  uplink handler (retry + breaker + dead-letter spool) over a flaky fake
  cloud, a scripted FaultPlan (camera kill/re-add, frame gaps, bus
  stall/flap, slow subscriber, uplink down, device stall), recording
  per-family latency percentiles, bucket_fill over time, step-cache
  stability, cross-family result misrouting, and a "resilience" section
  (ladder transitions, breaker states, annotation conservation).
- :func:`run_e2e` — the FULL single-process pipeline: a real Server
  (subprocess ingest worker reading ``replay://``, bus, collector,
  engine, gRPC serve) with a client measuring publish->receive latency —
  the first true single-path e2e percentile artifact (``E2E_r06.json``).
- :func:`run_fleet_obs` — r14 fleet telemetry soak: N member Server
  SUBPROCESSES (``--fleet N``), a FleetAggregator scraping them, gRPC
  clients recording the trace_id echo, and hard gates on merged-page
  lint, member presence, cross-process trace stitching and counter
  conservation (``FLEETOBS_r01.json``).

jax/server imports live inside functions: this module is imported by the
tools layer before the backend is chosen.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from .checksum import (
    CHECKSUM_MASK,
    device_checksum,
    finalize_checksum,
    zero_class_prior,
)
from .faults import QUALITY_KINDS, FaultPlan
from .player import TracePlayer, meta_for
from .recorder import record_synthetic_trace
from .trace import decode_frame

# The north-star fleet split per backend: real models on the chip, the
# structurally-identical tiny twins on the CPU backend (same serving
# families, same orchestration load, laptop-sized programs).
FLEET_TPU = {"yolov8n": 6, "resnet50": 5, "vit_b16": 5}
FLEET_CPU = {"tiny_yolov8": 6, "tiny_resnet": 5, "tiny_vit": 5}


def default_fleet(backend: str) -> dict:
    return dict(FLEET_TPU) if backend == "tpu" else dict(FLEET_CPU)


def _pct(values, points=(50, 90, 95, 99)) -> Optional[dict]:
    if not values:
        return None
    arr = np.asarray(values, dtype=np.float64)
    out = {f"p{p}": round(float(np.percentile(arr, p)), 2) for p in points}
    out["n"] = len(values)
    return out


# ---------------------------------------------------------------------------
# Lockstep determinism replay
# ---------------------------------------------------------------------------


def lockstep_checksum(
    trace_path: str, *, model: str = "tiny_yolov8",
    device_id: Optional[str] = None, limit: int = 0,
    perturb=None, zero_prior: bool = True, mesh=None,
) -> dict:
    """Replay a trace deterministically through bus -> collector ->
    serving step and fold the content checksum over every emitted batch.

    Frames go through the REAL pipeline stages (publish, cursor tracking,
    pooled-buffer assembly, bucket padding) one publish per collect so
    latest-wins can never drop a frame — replay order is trace order and
    the fold is exact, not racy. ``perturb(variables) -> variables`` is
    the seeded-fault hook (tests perturb one weight and the checksum must
    move). ``mesh`` (r17) places every batch dp-sharded through the
    mesh-serving H2D path (parallel.shard_put) instead of a plain
    transfer — at dp=1 the checksum must stay bit-identical to the
    single-chip golden, the smoke gate pinning mesh-native serving to
    the exact same numerics. Returns {"checksum", "frames", "batches",
    "model"}.
    """
    import jax
    import jax.numpy as jnp

    from ..bus.memory_bus import MemoryFrameBus
    from ..engine.collector import Collector
    from ..engine.runner import build_serving_step
    from ..models import registry

    spec = registry.get(model)
    net, variables = spec.init_params(jax.random.PRNGKey(0))
    if zero_prior and spec.kind == "detect":
        variables = zero_class_prior(variables)
    if perturb is not None:
        variables = perturb(variables)
    step = jax.jit(lambda v, u8: device_checksum(build_serving_step(net, spec)(v, u8)))

    player = TracePlayer(trace_path)
    bus = MemoryFrameBus()
    col = Collector(
        bus, buckets=(1, 2, 4, 8, 16), default_model=spec.name,
        clip_len=spec.clip_len,
    )
    created: set[str] = set()
    carry = 0
    frames = 0
    batches = 0
    try:
        for dev, frame, meta in player.iter_frames(device_id):
            if limit and frames >= limit:
                break
            if dev not in created:
                bus.create_stream(dev, frame.nbytes)
                created.add(dev)
            bus.publish(dev, frame, meta)
            frames += 1
            for group in col.collect():
                batches += 1
                if mesh is not None:
                    from ..parallel import batch_sharding, shard_put

                    placed = shard_put(
                        np.ascontiguousarray(group.frames),
                        batch_sharding(mesh, group.frames.ndim))
                else:
                    placed = jnp.asarray(group.frames)
                part = int(np.asarray(step(variables, placed)))
                carry = (carry + part) & CHECKSUM_MASK
    finally:
        bus.close()
    return {
        "checksum": finalize_checksum(carry),
        "frames": frames,
        "batches": batches,
        "model": spec.name,
    }


# ---------------------------------------------------------------------------
# In-process fleet soak
# ---------------------------------------------------------------------------


class StallBus:
    """FrameBus proxy whose publish path can be stalled for a window —
    the ``bus_stall`` fault (a wedged shm writer / slow Redis) — or made
    to fail fast for a window — the ``bus_flap`` fault (a flapping link:
    publishes raise ``ConnectionError`` instead of blocking). Everything
    else delegates."""

    def __init__(self, bus):
        self._bus = bus
        self._stall_until = 0.0
        self._flap_until = 0.0

    def __getattr__(self, name):
        return getattr(self._bus, name)

    def stall_for(self, duration_s: float) -> None:
        self._stall_until = time.monotonic() + duration_s

    def flap_for(self, duration_s: float) -> None:
        self._flap_until = time.monotonic() + duration_s

    def publish(self, device_id, frame, meta):
        while time.monotonic() < self._stall_until:
            time.sleep(0.01)
        if time.monotonic() < self._flap_until:
            raise ConnectionError("bus_flap (scripted fault)")
        return self._bus.publish(device_id, frame, meta)


class _FlakyCloud:
    """CloudClient stand-in for the soak's annotation uplink: delivery is
    an in-memory count, and the ``uplink_down`` fault makes every post
    raise ``URLError`` for a window — the transport-failure class the
    real handler retries, breaks on, and spools through. Exactly-once by
    construction (a post either raises before counting or delivers), so
    the artifact's conservation check is exact."""

    def __init__(self):
        self.down_until = 0.0
        self.posts = 0
        self.post_failures = 0
        self.delivered = 0

    def post_annotations(self, url, annotations, deadline=None):
        import urllib.error

        self.posts += 1
        if time.monotonic() < self.down_until:
            self.post_failures += 1
            raise urllib.error.URLError("uplink_down (scripted fault)")
        self.delivered += len(annotations)
        return b"{}"


class _ReplayCamera(threading.Thread):
    """One replay-driven camera: publishes its trace stream at recorded
    cadence (looping past the end), honoring kill/gap fault flags."""

    def __init__(self, bus, device_id: str, events: list, stop: threading.Event):
        super().__init__(name=f"replay-cam-{device_id}", daemon=True)
        self.bus = bus
        self.device_id = device_id
        self.events = events
        self.stop_ev = stop
        self.killed = threading.Event()
        self.gap_until = 0.0
        # Output-quality faults (ISSUE r10): while black_until is open the
        # camera publishes all-zero frames (lens cap / dead sensor); while
        # frozen_until is open it republishes the window's first frame (a
        # wedged decoder). Both keep the publish cadence — the stream
        # stays live, only its CONTENT degrades, which is exactly the
        # failure class obs/quality.py exists to see.
        self.black_until = 0.0
        self.frozen_until = 0.0
        self._frozen_frame = None
        self.published = 0
        self.suppressed = 0

    def run(self) -> None:
        ev0 = self.events[0]
        base = ev0["t_ms"]
        span = self.events[-1]["t_ms"] - base + (
            self.events[1]["t_ms"] - base if len(self.events) > 1 else 33.0)
        shape = ev0.get("shape") or [ev0["synth"]["h"], ev0["synth"]["w"], 3]
        self.bus.create_stream(self.device_id, shape[0] * shape[1] * shape[2])
        alive = True
        t0 = time.monotonic()
        i = 0
        while not self.stop_ev.is_set():
            ev = self.events[i % len(self.events)]
            due = t0 + ((ev["t_ms"] - base)
                        + (i // len(self.events)) * span) / 1000.0
            delay = due - time.monotonic()
            if delay > 0 and self.stop_ev.wait(delay):
                break
            i += 1
            if self.killed.is_set():
                alive = False
                self.suppressed += 1
                continue
            if not alive:
                # Re-added after a kill: the stream was dropped from the
                # bus; re-create it (a restarted worker does the same).
                self.bus.create_stream(
                    self.device_id, shape[0] * shape[1] * shape[2])
                alive = True
            if time.monotonic() < self.gap_until:
                self.suppressed += 1
                continue
            frame = decode_frame(ev)
            now_mono = time.monotonic()
            if now_mono < self.black_until:
                frame = np.zeros_like(frame)
            elif now_mono < self.frozen_until:
                if self._frozen_frame is None:
                    self._frozen_frame = frame
                frame = self._frozen_frame
            else:
                self._frozen_frame = None
            meta = meta_for(ev, frame, timestamp_ms=int(time.time() * 1000))
            try:
                self.bus.publish(self.device_id, frame, meta)
            except ConnectionError:
                # bus_flap: the link dropped the publish but the stream
                # itself is intact — count suppressed and keep the
                # cursor (re-creating the stream would reset its seq and
                # confuse the collector for no reason).
                self.suppressed += 1
                continue
            except ValueError:
                # Raced a camera_kill's drop_stream: treat as suppressed
                # and re-create on the next live frame.
                alive = False
                self.suppressed += 1
                continue
            self.published += 1


def run_fleet_soak(
    *, duration_s: float = 120.0, fleet: Optional[dict] = None,
    src_hw: tuple = (96, 128), fps: float = 30.0, tick_ms: int = 10,
    trace_path: Optional[str] = None, fault_plan: Optional[FaultPlan] = None,
    warmup_timeout_s: float = 1800.0, sample_every_s: float = 2.0,
    timeline_bin_s: float = 10.0, trace_sample_every: int = 4,
    profile_on_burn: bool = False, prof_dir: Optional[str] = None,
    quality_kinds: tuple = (), engine_overrides: Optional[dict] = None,
) -> dict:
    """The >=120 s chaos soak. Returns the artifact's "soak" section.

    ``profile_on_burn`` arms the r10 trigger path (obs/prof.py): the
    engine fires a bounded jax.profiler capture when an SLO episode
    opens or the ladder escalates, at soak-scale settings (200 ms
    captures, 5 s rate limit — a 20 s smoke must be able to catch its
    own excursion). The bundle manifests land in the artifact's "prof"
    section; tools/soak_replay.py --profile-on-burn hard-gates on them.

    ``quality_kinds`` (ISSUE r11) schedules output-quality faults
    (replay/faults.py QUALITY_KINDS: black_frame on the first camera,
    frozen_frame on the second, a global score_drift) and arms the full
    quality plane at soak scale: tight verdict hysteresis (0.6 s), a
    recorded canary golden-replay trace wired into the live engine
    (adopt-first-cycle golden), the detect class prior zeroed so the
    fleet produces real detections (bench.py's measured-regime
    transform — a random-init detector would otherwise emit nothing
    and neither drift nor the canary fold would have signal). The
    artifact gains a "quality" section: per-fault detection latency
    (first matching verdict transition / canary integrity episode after
    injection, in seconds and engine ticks) and the false-positive
    count over everything outside the fault windows. Without quality
    faults the tracker still runs (engine default) — the plain soak
    doubles as the zero-false-positive clean window.
    """
    import shutil
    import tempfile

    import jax

    from ..bus.memory_bus import MemoryFrameBus
    from ..engine import InferenceEngine
    from ..models import registry
    from ..obs import registry as obs_registry, tracer
    from ..obs.spans import stage_breakdown
    from ..resilience import CircuitBreaker, DeadLetterSpool, RetryPolicy
    from ..uplink.cloud import make_batch_handler
    from ..uplink.queue import AnnotationQueue
    from ..utils.config import EngineConfig

    backend = jax.default_backend()
    fleet = fleet or default_fleet(backend)
    h, w = src_hw

    assignment = {}
    i = 0
    for name, count in fleet.items():
        for _ in range(count):
            assignment[f"fleet{i:02d}"] = name
            i += 1
    family_of = {name: registry.get(name).kind for name in fleet}

    # Deterministic traffic: one synthetic trace shared by every camera
    # (replay-driven, not freerunning RNG — the soak's inputs are a file).
    if trace_path is None:
        trace_path = os.path.join(
            "/tmp", f"vep_soak_trace_{os.getpid()}.vtrace")
        record_synthetic_trace(
            trace_path, sorted(assignment), width=w, height=h, fps=fps,
            gop=30, frames=max(60, int(min(duration_s, 30.0) * fps)))
    player = TracePlayer(trace_path)

    # Frame lineage across the soak: cameras publish in-process, so the
    # collect span's pub_ms carries the ingest leg; engine spans complete
    # the chain. Restore the prior tracer config on exit — the soak runs
    # inside the test/tool process alongside other obs users.
    prev_trace = (tracer.enabled, tracer.sample_every)
    tracer.configure(enabled=True, sample_every=max(1, trace_sample_every))

    inner_bus = MemoryFrameBus()
    bus = StallBus(inner_bus)
    default_model = next(iter(fleet))

    # Annotation uplink under test: the REAL batch handler (retry +
    # breaker + dead-letter spool, uplink/cloud.py) over a flaky fake
    # transport. Timings are soak-scale (tens of ms) so the uplink_down
    # window exercises the whole ladder: retries, breaker open, spool,
    # drain-on-recovery — within one smoke run.
    ann_cloud = _FlakyCloud()
    spool_dir = tempfile.mkdtemp(prefix="vep_soak_spool_")
    ann_spool = DeadLetterSpool(spool_dir, max_bytes=8 << 20)
    ann_handler = make_batch_handler(
        None, "soak://annotate", client=ann_cloud, spool=ann_spool,
        retry=RetryPolicy(max_attempts=2, base_s=0.01, cap_s=0.05),
        breaker=CircuitBreaker(
            "uplink_soak", failure_threshold=2, recovery_timeout_s=0.5),
        post_deadline_s=5.0,
    )
    ann_q = AnnotationQueue(
        ann_handler, max_batch_size=299, poll_duration_ms=100,
        unacked_limit=100_000, requeue_interval_s=0.5,
    )
    ann_q.start()

    if profile_on_burn and prof_dir is None:
        prof_dir = tempfile.mkdtemp(prefix="vep_soak_prof_")
    has_quality = bool(quality_kinds)
    qcfg = {}
    if has_quality:
        # Soak-scale quality knobs: verdicts must enter/exit within a
        # 20 s smoke, and the drift window must roll several times. The
        # canary trace shares the fleet geometry so its batches slot
        # into already-compiled programs (and already-warm buckets).
        canary_trace = os.path.join(
            "/tmp", f"vep_canary_{os.getpid()}.vtrace")
        record_synthetic_trace(
            canary_trace, ["_canary"], width=w, height=h, fps=fps,
            gop=6, frames=6)
        qcfg = dict(
            quality_enter_s=0.6,
            quality_exit_s=0.6,
            quality_window_s=2.0,
            quality_canary=canary_trace,
            # Slow deliberately: the canary is an integrity probe, not a
            # throughput probe. Injected faster than the loaded engine's
            # effective tick, frames overwrite in the collector slot and
            # every cycle voids (a dropped packet makes the checksum
            # meaningless, so the checker refuses to judge it). 2 fps
            # over a 6-frame loop = one integrity verdict every 3 s,
            # which even the saturated CPU soak serves losslessly.
            quality_canary_fps=2.0,
        )
    eng_cfg = EngineConfig(
            model=default_model, tick_ms=tick_ms, stage_trace=True,
            batch_buckets=(1, 2, 4, 8, 16), track=False,
            annotation_emit="all",   # firehose: conservation needs volume
            # Profiling is opt-in for the soak: a capture pauses ~200 ms
            # of wall inside the measured window, so only the
            # --profile-on-burn legs pay it. Soak-scale trigger knobs:
            # small capture, short rate limit, and an SLO warmup shorter
            # than the smoke duration so episode triggers can fire too.
            prof=profile_on_burn,
            prof_dir=prof_dir or "",
            # The replay soak forks nothing, so the fork hazard behind
            # the EngineConfig prof_trigger=False default does not
            # apply here — arm the trigger path explicitly.
            prof_trigger=profile_on_burn,
            prof_trigger_ms=200,
            prof_trigger_min_interval_s=5.0,
            slo_warmup_s=(
                10.0 if (profile_on_burn or has_quality) else 60.0),
            **qcfg,
    )
    if engine_overrides:
        # Engine-config passthrough (r17): cascade-enabled soak members
        # (track=True + cascade=True + a tiny head model) ride the same
        # harness without a parameter per knob; replace() keeps override
        # keys validated against the dataclass fields.
        import dataclasses as _dc

        eng_cfg = _dc.replace(eng_cfg, **engine_overrides)
    eng = InferenceEngine(
        bus,
        eng_cfg,
        model_resolver=lambda d: assignment.get(d, ""),
        annotations=ann_q,
    )

    # device_stall fault: while the window is open every serving-step
    # call eats ~50 ms of fake device time. Per-call (not one long
    # block) so consecutive over-budget ticks build the SUSTAINED
    # pressure the ladder's escalate hysteresis requires.
    # score_drift fault: while its window is open every detect batch's
    # post-NMS scores are scaled ×0.75 — a SILENT numerics regression
    # (boxes intact, counts intact, just confidences off), the failure
    # class only the canary checksum + drift scorer can see.
    stall = {"until": 0.0}
    drift = {"until": 0.0}
    _orig_step = eng._step

    def _stalled_step(src_hw, bucket, model=None):
        fn = _orig_step(src_hw, bucket, model)

        def slow(*a, **k):
            if time.monotonic() < stall["until"]:
                time.sleep(0.05)
            out = fn(*a, **k)
            if time.monotonic() < drift["until"] and "scores" in out:
                out = dict(out)
                out["scores"] = out["scores"] * 0.75
            return out

        return slow

    eng._step = _stalled_step
    eng.warmup()
    if has_quality:
        # Measured-regime transform (replay/checksum.py zero_class_prior,
        # the bench.py idiom): random-init detect scores sit at ~1e-5,
        # below the NMS floor — zero detections means no drift signal
        # and an all-zero canary fold. Zeroing the class-prior biases
        # saturates the candidate sets so scores/classes carry real,
        # content-dependent numerics for the canary to pin.
        entry = eng._models.get(default_model)
        if entry is not None and entry[0].kind == "detect":
            spec0, mod0, vars0 = entry
            vars0 = zero_class_prior(vars0)
            eng._models[default_model] = (spec0, mod0, vars0)
            eng._variables = vars0
    eng.start()

    stop = threading.Event()
    cams = {
        d: _ReplayCamera(bus, d, player.frame_events(d), stop)
        for d in sorted(assignment)
    }

    # Result sink: one subscriber over all streams. latencies per family,
    # misrouting check, pausable for the slow_subscriber fault.
    lat_by_family: dict[str, list] = {k: [] for k in set(family_of.values())}
    lat_lock = threading.Lock()
    misrouted: list = []
    results = {"n": 0}
    slow_until = [0.0]
    measuring = threading.Event()

    def sink() -> None:
        for res in eng.subscribe(timeout=0.5):
            while time.monotonic() < slow_until[0] and not stop.is_set():
                time.sleep(0.05)   # slow subscriber: stop draining
            if stop.is_set():
                break
            expected = assignment.get(res.device_id)
            if expected is not None and res.model != expected:
                misrouted.append((res.device_id, res.model, expected))
            if not measuring.is_set():
                continue
            results["n"] += 1
            fam = family_of.get(res.model)
            if fam is not None:
                with lat_lock:
                    lat_by_family[fam].append(res.latency_ms)

    sink_thread = threading.Thread(target=sink, name="soak-sink", daemon=True)
    sink_thread.start()

    # Warmup: first frame per camera, wait for every (model, bucket)
    # program to compile before the measured window (bench_fleet idiom).
    for d, cam in cams.items():
        ev = cam.events[0]
        frame = decode_frame(ev)
        inner_bus.create_stream(d, frame.nbytes)
        inner_bus.publish(
            d, frame, meta_for(ev, frame, timestamp_ms=int(time.time() * 1000)))
    warm_deadline = time.monotonic() + warmup_timeout_s
    while time.monotonic() < warm_deadline:
        if len(eng.stats()) >= len(assignment):
            break
        time.sleep(1.0)
    warmup_s = warmup_timeout_s - (warm_deadline - time.monotonic())
    # Prewarm every bucket the degradation ladder can downshift to. The
    # warmup traffic only compiles each model's nominal bucket; the first
    # downshift then pays a mid-soak CPU compile that stalls the tick
    # loop for seconds — blanking quality sampling exactly when the
    # overload (and the scripted faults) hit. Compile them all now, in
    # the window the measurement already excludes.
    model_counts: dict = {}
    for mname in assignment.values():
        model_counts[mname] = model_counts.get(mname, 0) + 1
    for mname, count in model_counts.items():
        spec_m, _, vars_m = eng._ensure_model(mname)
        if spec_m.clip_len:
            continue
        for b in eng._cfg.batch_buckets:
            args = [np.zeros((b, h, w, 3), np.uint8)]
            if eng._quality_device:
                side = eng._cfg.quality_thumb
                args.append(np.zeros((b, side, side), np.float32))
            eng._step((h, w), b, mname)(vars_m, *args)
            if b >= count:
                break
    eng.stage_records.clear()
    # The measured window starts clean: warmup compiles would otherwise
    # register as recompile-storm episodes and skew the span breakdown.
    tracer.clear()
    eng.watchdog.reset()
    if eng.quality is not None:
        # Warmup frames (one per camera, then silence) would otherwise
        # seep into the measured window as flatline/freeze priors. The
        # canary is NOT reset: the golden it adopted from warmup cycles
        # is exactly the reference the measured window checks against.
        eng.quality.reset()

    if fault_plan is not None:
        events = list(fault_plan.events)
    elif has_quality:
        # Quality smoke runs without the churn script: camera kills and
        # bus stalls would starve the very streams whose verdicts the
        # detection-latency gate is timing.
        events = []
    else:
        events = list(
            FaultPlan.default_churn(sorted(assignment), duration_s).events)
    if has_quality:
        events += FaultPlan.quality(
            duration_s, sorted(assignment), quality_kinds).events
    plan = FaultPlan(events)
    plan.reset()

    measuring.set()
    for cam in cams.values():
        cam.start()

    t0 = time.monotonic()
    t0_wall = time.time()   # stage_records carry wall-clock stamps
    faults_applied = []
    step_cache_samples = []
    timeline: dict[int, dict] = {}
    seen_submits: dict[float, int] = {}
    next_sample = 0.0

    def drain_stage_records() -> None:
        while True:
            try:
                r = eng.stage_records.popleft()
            except IndexError:
                break
            b = int(max(0.0, r["t_emitted"] - t0_wall) // timeline_bin_s)
            slot = timeline.setdefault(b, {"real": 0, "padded": 0})
            slot["real"] += 1
            # one batch contributes its bucket once (keyed by submit time)
            key = r["t_submit"]
            if key not in seen_submits:
                seen_submits[key] = r["bucket"]
                slot["padded"] += r["bucket"]

    while True:
        now_s = time.monotonic() - t0
        if now_s >= duration_s:
            break
        for ev in plan.pop_due(now_s):
            faults_applied.append({
                "at_s": round(now_s, 2), "kind": ev.kind,
                "device_id": ev.device_id, "duration_s": ev.duration_s,
            })
            if ev.kind == "camera_kill":
                cams[ev.device_id].killed.set()
                bus.drop_stream(ev.device_id)
            elif ev.kind == "camera_restore":
                cams[ev.device_id].killed.clear()
            elif ev.kind == "frame_gap":
                cams[ev.device_id].gap_until = \
                    time.monotonic() + ev.duration_s
            elif ev.kind == "bus_stall":
                bus.stall_for(ev.duration_s)
            elif ev.kind == "slow_subscriber":
                slow_until[0] = time.monotonic() + ev.duration_s
            elif ev.kind == "uplink_down":
                ann_cloud.down_until = time.monotonic() + ev.duration_s
            elif ev.kind == "bus_flap":
                bus.flap_for(ev.duration_s)
            elif ev.kind == "device_stall":
                stall["until"] = time.monotonic() + ev.duration_s
            elif ev.kind == "black_frame":
                cams[ev.device_id].black_until = \
                    time.monotonic() + ev.duration_s
            elif ev.kind == "frozen_frame":
                cams[ev.device_id].frozen_until = \
                    time.monotonic() + ev.duration_s
            elif ev.kind == "score_drift":
                drift["until"] = time.monotonic() + ev.duration_s
        if now_s >= next_sample:
            step_cache_samples.append(
                {"t_s": round(now_s, 1), "programs": len(eng._step_cache)})
            drain_stage_records()
            next_sample = now_s + sample_every_s
        time.sleep(0.25)

    measuring.clear()
    stop.set()
    for cam in cams.values():
        cam.join(timeout=5)
    drain_stage_records()
    stats = eng.stats()
    subscriber_drops = eng.subscriber_drops
    programs_final = len(eng._step_cache)
    ticks = eng.ticks
    span_events = tracer.events()
    obs_section = {
        "metrics": obs_registry.snapshot(),
        "watch": eng.watchdog.snapshot(),
        "stage_breakdown": stage_breakdown(span_events),
        "trace": {
            "sample_every": tracer.sample_every,
            "events": len(span_events),
            "streams": len(tracer.streams()),
        },
        "quality": eng.quality.snapshot() if eng.quality is not None
        else None,
    }
    canary_snapshot = eng.canary.snapshot() if eng.canary is not None \
        else None
    tracer.configure(enabled=prev_trace[0], sample_every=prev_trace[1])
    ladder_snapshot = eng.ladder.snapshot() if eng.ladder is not None else None
    shed_frames = eng.shed_frames
    # r9 attribution snapshots, captured live like the ladder's: compile
    # cost + device-time/padding/MFU per bucket, and per-SLO burn state
    # (a >=2x-warmup soak may legitimately fire the fps objective on the
    # CPU backend — the artifact records it; the chaos gates don't care).
    perf_section = eng.perf.snapshot()
    slo_section = eng.slo.snapshot() if eng.slo is not None else None
    # r10: let an in-flight burn-triggered capture finish flushing its
    # bundle, then freeze the manifest list into the artifact.
    prof_section = None
    if eng.prof is not None:
        eng.prof.join_trigger()
        prof_section = eng.prof.snapshot()
    eng.stop()
    sink_thread.join(timeout=5)
    inner_bus.close()

    # Final uplink drain: uplink healthy again, every queued batch and
    # every spooled batch must make it out — the "zero lost annotations"
    # claim is this loop terminating with both depths at zero.
    ann_cloud.down_until = 0.0
    drain_deadline = time.monotonic() + 30.0
    while ann_q.depth() > 0 and time.monotonic() < drain_deadline:
        ann_q.requeue_rejected()
        if ann_q.drain_once() == 0:
            time.sleep(0.05)
    while ann_spool.pending() > 0 and time.monotonic() < drain_deadline:
        ann_handler([])   # empty batch = pure spool drain through cloud.py
    ann_q.stop()
    spool_snapshot = ann_spool.snapshot()
    shutil.rmtree(spool_dir, ignore_errors=True)
    if has_quality:
        try:
            os.unlink(canary_trace)
        except OSError:
            pass
    # Conservation: everything the engine enqueued was delivered exactly
    # once, minus only explicit spool evictions (bounded spool) — no
    # silent loss anywhere in queue -> handler -> spool -> drain.
    conserved = (
        ann_cloud.delivered + spool_snapshot["dropped_events"]
        == ann_q.published
    )
    resilience_section = {
        "ladder": ladder_snapshot,
        "shed_frames": shed_frames,
        "uplink": {
            "published": ann_q.published,
            "acked": ann_q.acked,
            "queue_dropped": ann_q.dropped,
            "rejected_batches": ann_q.rejected_batches,
            "posts": ann_cloud.posts,
            "post_failures": ann_cloud.post_failures,
            "delivered_events": ann_cloud.delivered,
            "final_queue_depth": ann_q.depth(),
            "breaker": ann_handler.breaker.snapshot(),
            "spool": spool_snapshot,
            "conserved": conserved,
        },
    }

    # Quality-fault attribution (ISSUE r10): for each injected quality
    # fault, find the verdict transition (or canary mismatch) that
    # answers it, and time it in ticks. Transitions carry the tracker's
    # monotonic clock, faults_applied carries offsets from t0 — same
    # clock, so the subtraction is exact. Any non-ok transition outside
    # every expected window is a false positive (the clean remainder of
    # the soak doubles as the zero-false-positive window).
    quality_section = None
    if has_quality and obs_section["quality"] is not None:
        qsnap = obs_section["quality"]
        enter_s = qcfg["quality_enter_s"]
        exit_s = qcfg["quality_exit_s"]
        verdict_for = {"black_frame": "black", "frozen_frame": "frozen"}
        expected: dict[str, list] = {}
        fault_reports = []
        episodes = obs_section["watch"].get("episodes", {})
        canary_episodes = episodes.get("canary_integrity", 0)
        for f in faults_applied:
            if f["kind"] not in verdict_for and f["kind"] != "score_drift":
                continue
            fault_mono = t0 + f["at_s"]
            report = dict(f)
            if f["kind"] == "score_drift":
                # Untimestamped by design (cycle accounting, not wall
                # time): detection = the canary mismatched and opened a
                # watchdog episode while the window was live.
                mism = (canary_snapshot or {}).get("mismatch_cycles", 0)
                report["detected"] = bool(mism and canary_episodes)
                report["mismatch_cycles"] = mism
                report["latency_s"] = None
                report["latency_ticks"] = None
            else:
                want = verdict_for[f["kind"]]
                trans = qsnap["streams"].get(
                    f["device_id"], {}).get("transitions", [])
                hit = next(
                    (t for t, v in trans
                     if v == want and t >= fault_mono - 0.5), None)
                report["detected"] = hit is not None
                report["latency_s"] = (
                    round(hit - fault_mono, 3) if hit is not None else None)
                report["latency_ticks"] = (
                    int(round((hit - fault_mono) / (tick_ms / 1000.0)))
                    if hit is not None else None)
                expected.setdefault(f["device_id"], []).append(
                    (fault_mono - 0.5,
                     fault_mono + f["duration_s"] + enter_s + exit_s + 3.0))
            fault_reports.append(report)
        false_positives = []
        for name, st in qsnap["streams"].items():
            for t, v in st["transitions"]:
                if v == "ok":
                    continue
                if any(lo <= t <= hi for lo, hi in expected.get(name, ())):
                    continue
                false_positives.append(
                    {"stream": name, "verdict": v,
                     "at_s": round(t - t0, 2)})
        quality_section = {
            "faults": fault_reports,
            "false_positives": false_positives,
            "canary": canary_snapshot,
            "canary_watchdog_episodes": canary_episodes,
            "tick_ms": tick_ms,
        }

    bucket_fill_timeline = [
        {
            "t_s": int(b * timeline_bin_s),
            "real": slot["real"],
            "padded": slot["padded"],
            "fill": round(slot["real"] / slot["padded"], 3)
            if slot["padded"] else None,
        }
        for b, slot in sorted(timeline.items())
    ]
    # Stable = the program set stopped growing before the soak ended
    # (churn-induced compiles allowed mid-run; unbounded growth is the
    # recompilation-storm failure this pins).
    step_cache_samples.append(
        {"t_s": round(duration_s, 1), "programs": programs_final})
    tail = [s["programs"] for s in step_cache_samples[-5:]]
    with lat_lock:
        per_family = {
            fam: _pct(vals) for fam, vals in sorted(lat_by_family.items())
        }
    return {
        "backend": backend,
        "duration_s": duration_s,
        "fleet": fleet,
        "streams": len(assignment),
        "src_hw": [h, w],
        "trace": os.path.basename(trace_path),
        "warmup_s": round(warmup_s, 1),
        "ticks": ticks,
        "results_measured": results["n"],
        "per_family_latency_ms": per_family,
        "bucket_fill_timeline": bucket_fill_timeline,
        "step_cache": {
            "samples": step_cache_samples,
            "final": programs_final,
            "stable": len(set(tail)) <= 1 if tail else False,
        },
        "misrouted_results": len(misrouted),
        "misrouted_examples": misrouted[:5],
        "subscriber_drops": subscriber_drops,
        "published": {d: c.published for d, c in cams.items()},
        "suppressed": {d: c.suppressed for d, c in cams.items()},
        "streams_with_results": len(stats),
        "faults_applied": faults_applied,
        "obs": obs_section,
        "resilience": resilience_section,
        "perf": perf_section,
        "slo": slo_section,
        "prof": prof_section,
        "quality": quality_section,
    }


# ---------------------------------------------------------------------------
# Full single-process pipeline e2e
# ---------------------------------------------------------------------------


def run_e2e(
    *, duration_s: float = 30.0, warmup_s: float = 8.0,
    width: int = 128, height: int = 96, fps: float = 30.0,
    model: str = "tiny_yolov8", workdir: Optional[str] = None,
) -> dict:
    """Replay a trace through the FULL pipeline — subprocess ingest worker
    (``replay://`` source) -> shm bus -> collector -> engine -> gRPC serve
    -> client — and record publish->receive latency percentiles: the <40 ms
    p50 SLA observed as ONE number on ONE pipeline run (VERDICT r5 missing
    #3). Returns the E2E_r06.json payload."""
    import shutil
    import tempfile

    import grpc

    from ..obs import registry as obs_registry, tracer
    from ..obs.spans import stage_breakdown
    from ..proto import pb, pb_grpc
    from ..serve.models import StreamProcess
    from ..serve.server import Server
    from ..utils.config import Config

    import jax

    backend = jax.default_backend()
    tmp = workdir or tempfile.mkdtemp(prefix="vep_e2e_")
    trace_path = os.path.join(tmp, "e2e.vtrace")
    record_synthetic_trace(
        trace_path, ["e2e0"], width=width, height=height, fps=fps, gop=30,
        frames=max(90, int(fps * 10)))

    cfg = Config()
    cfg.bus.shm_dir = os.path.join("/dev/shm", f"vep_e2e_{os.getpid()}")
    cfg.annotation.endpoint = "http://127.0.0.1:1/annotate"   # no egress
    cfg.engine.model = model
    cfg.engine.track = False
    # Server.__init__ reconfigures the global tracer from cfg.obs — the
    # e2e artifact carries the stage-segmented breakdown (ingest leg via
    # pub_ms on collect spans; the publish span lives in the subprocess
    # worker's rings, not ours).
    cfg.obs.trace = True
    cfg.obs.sample_every = 4
    srv = Server(cfg, data_dir=tmp, grpc_port=0, rest_port=0,
                 enable_engine=True)
    srv.start()
    lat: list[float] = []
    lat_all: list[float] = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    measure_after = [float("inf")]

    def client() -> None:
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.bound_grpc_port}")
        stub = pb_grpc.ImageStub(channel)
        while not stop.is_set():
            try:
                for res in stub.Inference(pb.InferenceRequest(), timeout=5):
                    if stop.is_set():
                        break
                    if not res.timestamp:
                        continue
                    sample = time.time() * 1000 - res.timestamp
                    with lat_lock:
                        lat_all.append(sample)
                        if time.monotonic() >= measure_after[0]:
                            lat.append(sample)
            except grpc.RpcError:
                if not stop.is_set():
                    time.sleep(0.5)
        channel.close()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    try:
        srv.process_manager.start(StreamProcess(
            name="e2e0",
            rtsp_endpoint=f"replay://{trace_path}?device=e2e0&pace=1&loop=1",
        ))
        # Warmup covers worker boot + first-geometry compile; then measure.
        time.sleep(warmup_s)
        tracer.clear()   # measured-window spans only
        measure_after[0] = time.monotonic()
        time.sleep(duration_s)
    finally:
        stop.set()
        t.join(timeout=10)
        span_events = tracer.events()
        obs_section = {
            "metrics": obs_registry.snapshot(),
            "watch": srv.engine.watchdog.snapshot()
            if srv.engine is not None else None,
            "stage_breakdown": stage_breakdown(span_events),
            "trace": {
                "sample_every": tracer.sample_every,
                "events": len(span_events),
            },
            "perf": srv.engine.perf.snapshot()
            if srv.engine is not None else None,
            "slo": srv.engine.slo.snapshot()
            if srv.engine is not None and srv.engine.slo is not None
            else None,
        }
        tracer.configure(enabled=False)
        srv.stop()
        shutil.rmtree(cfg.bus.shm_dir, ignore_errors=True)
        if workdir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    with lat_lock:
        measured = list(lat)
        total = len(lat_all)
    return {
        "metric": f"e2e_single_path_latency_{model}_{backend}",
        "pipeline": "replay://(worker subprocess) -> shm bus -> collector "
                    "-> engine -> gRPC Inference stream -> client",
        "backend": backend,
        "model": model,
        "src_hw": [height, width],
        "fps": fps,
        "duration_s": duration_s,
        "warmup_s": warmup_s,
        "results_total": total,
        "results_measured": len(measured),
        "latency_ms": _pct(measured),
        "unit": "ms publish->client-receive",
        "obs": obs_section,
    }

def _fleet_member_main(argv=None) -> None:
    """Entry for ONE fleet-soak member subprocess (``python -m
    video_edge_ai_proxy_tpu.replay.harness --instance m0 ...``), spawned
    by :func:`run_fleet_obs` / :func:`run_router_soak`. Protocol over
    stdout (JSON lines; server logs go to stderr): ``{"ready": ...,
    "rest_port", "grpc_port"}`` after boot, ``{"quiesced": ...}`` after
    the replay stream stopped and drained (counters static — the
    parent's conservation-scrape window), then the member blocks on
    stdin until the parent releases it, dumps its span rings to
    ``--spans-out`` and exits.

    ``--serve-only`` (r16, router soak): boot NO stream of its own — the
    fleet router places streams over REST — and run a stdin command loop
    instead of the timed window: ``burn`` forces the engine's SLO-burn
    verdict on (deterministic ladder pressure; pair with ``--slo-off``
    so the real SLO engine never recomputes it), ``calm`` clears it,
    ``exit`` releases the member. Each command is acked with a JSON
    line."""
    import argparse
    import json
    import shutil
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--instance", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--trace", default="",
                    help="replay trace for the self-started stream "
                         "(ignored with --serve-only)")
    ap.add_argument("--device", default="",
                    help="self-started stream name (ignored with "
                         "--serve-only)")
    ap.add_argument("--model", default="tiny_yolov8")
    ap.add_argument("--duration", type=float, default=12.0)
    ap.add_argument("--warmup", type=float, default=8.0,
                    help="extra replay seconds before the measured window "
                         "(covers worker boot + first-geometry compile)")
    ap.add_argument("--spans-out", required=True)
    ap.add_argument("--native", action="store_true")
    ap.add_argument("--serve-only", action="store_true")
    ap.add_argument("--slo-off", action="store_true",
                    help="disable the SLO engine so the burn flag is "
                         "script-controlled, not recomputed per window")
    ap.add_argument("--ladder-escalate", type=float, default=None,
                    help="override engine.ladder_escalate_after_s (the "
                         "router soak spaces rungs so migration lands "
                         "between shed_to_fleet and bucket_downshift)")
    ap.add_argument("--shed-staleness-ms", type=float, default=None,
                    help="override engine.shed_staleness_ms (the router "
                         "soak sets it high so the shed rung itself "
                         "drops nothing and the conservation ledger "
                         "stays attributable to migration alone)")
    ap.add_argument("--batch-bucket", type=int, default=0,
                    help="pin a single collector batch bucket so a "
                         "migrated stream joining mid-soak never "
                         "triggers a new device program (compile would "
                         "drop frames via latest-frame-wins)")
    ap.add_argument("--ladder-slo-only", action="store_true",
                    help="neuter the ladder's physical pressure inputs "
                         "(queue depth / tick lag) so the injected SLO "
                         "burn is the ONLY rung driver — on the CPU "
                         "backend an inference tick takes ~20x the 10ms "
                         "tick budget, which would walk every member's "
                         "ladder and make the router soak ping-pong")
    ap.add_argument("--trace-every", type=int, default=None,
                    help="override obs.sample_every (the router soak "
                         "traces every frame so short post-migration "
                         "residence still yields a stitchable chain)")
    ap.add_argument("--prewarm", action="append", default=[],
                    metavar="HxWxB[:model]",
                    help="compile this program during boot (repeatable); "
                         "soak members prewarm every geometry they will "
                         "serve so no in-soak compile ever overwrites an "
                         "uncollected frame (latest-frame-wins) and the "
                         "conservation ledger holds from the FIRST frame")
    ap.add_argument("--aot-cache", default="",
                    help="shared persistent AOT cache dir (r19, "
                         "engine/aot_cache.py): sets engine.aot_cache + "
                         "aot_cache_dir; a member sharing a populated dir "
                         "prewarms via persistent-cache hits and the "
                         "manifest supplies the program set when no "
                         "--prewarm flags are given (the spawned-member "
                         "path)")
    ap.add_argument("--capacity", action="store_true",
                    help="enable the r18 capacity attribution plane "
                         "(headroom + saturation forecast) — the "
                         "autoscale soak's supervisor steers on it")
    ap.add_argument("--capacity-fast-window", type=float, default=None,
                    help="override engine.capacity_fast_window_s (soaks "
                         "run minutes, not hours: the fast burn window "
                         "must fit inside the soak's ramp)")
    args = ap.parse_args(argv)
    if not args.serve_only and (not args.trace or not args.device):
        ap.error("--trace/--device required without --serve-only")
    if not args.native:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from ..obs import tracer
    from ..serve.models import StreamProcess
    from ..serve.server import Server
    from ..utils.config import Config

    cfg = Config()
    cfg.bus.shm_dir = os.path.join("/dev/shm", f"vep_fleet_{os.getpid()}")
    cfg.annotation.endpoint = "http://127.0.0.1:1/annotate"   # no egress
    cfg.engine.model = args.model
    cfg.engine.track = False
    cfg.obs.trace = True
    cfg.obs.sample_every = 4
    cfg.obs.instance = args.instance   # const instance label on /metrics
    if args.slo_off:
        cfg.engine.slo = False
    if args.ladder_escalate is not None:
        cfg.engine.ladder_escalate_after_s = args.ladder_escalate
    if args.shed_staleness_ms is not None:
        cfg.engine.shed_staleness_ms = args.shed_staleness_ms
    if args.batch_bucket:
        cfg.engine.batch_buckets = (args.batch_bucket,)
    if args.trace_every is not None:
        cfg.obs.sample_every = args.trace_every
    if args.prewarm:
        entries = []
        for spec in args.prewarm:
            geom, _, mdl = spec.partition(":")
            h, w, b = (int(v) for v in geom.split("x"))
            entries.append([h, w, b, mdl] if mdl else [h, w, b])
        cfg.engine.prewarm = entries
    if args.aot_cache:
        cfg.engine.aot_cache = True
        cfg.engine.aot_cache_dir = args.aot_cache
    if args.capacity:
        cfg.engine.capacity = True
    if args.capacity_fast_window is not None:
        cfg.engine.capacity_fast_window_s = args.capacity_fast_window
    srv = Server(cfg, data_dir=args.workdir, grpc_port=0, rest_port=0,
                 enable_engine=True)
    srv.start()
    if args.ladder_slo_only and srv.engine is not None \
            and srv.engine.ladder is not None:
        # Physical pressure (drain depth / tick lag vs the 10ms budget)
        # is unavoidable on the CPU backend; push both thresholds out of
        # reach so observe()'s slo_burning input is the only escalation
        # driver and the soak's rung walk is script-controlled.
        srv.engine.ladder.depth_threshold = 10**9
        srv.engine.ladder.lag_factor = 10**9
    print(json.dumps({
        "ready": True, "instance": args.instance,
        "rest_port": srv._rest.bound_port,
        "grpc_port": srv.bound_grpc_port,
    }), flush=True)
    try:
        if args.serve_only:
            # Router-soak mode: the router owns placement; this process
            # only answers burn/calm/exit (ack each so the parent can
            # sequence without sleeps).
            for line in sys.stdin:
                cmd = line.strip()
                if cmd == "burn":
                    if srv.engine is not None:
                        srv.engine._slo_burning = True
                elif cmd == "calm":
                    if srv.engine is not None:
                        srv.engine._slo_burning = False
                elif cmd == "exit":
                    print(json.dumps({"ack": "exit",
                                      "instance": args.instance}),
                          flush=True)
                    break
                else:
                    continue
                print(json.dumps({"ack": cmd, "instance": args.instance}),
                      flush=True)
        else:
            srv.process_manager.start(StreamProcess(
                name=args.device,
                rtsp_endpoint=(
                    f"replay://{args.trace}?device={args.device}"
                    "&pace=1&loop=1"
                ),
            ))
            time.sleep(args.warmup + args.duration)
            srv.process_manager.stop(args.device)
            time.sleep(1.0)   # engine drain: counters static after this
            print(json.dumps({"quiesced": True, "instance": args.instance}),
                  flush=True)
            sys.stdin.readline()   # parent finished conservation scrapes
    finally:
        events = tracer.events()
        with open(args.spans_out, "w") as f:
            json.dump({"events": events}, f)
        tracer.configure(enabled=False)
        srv.stop()
        shutil.rmtree(cfg.bus.shm_dir, ignore_errors=True)


def run_fleet_obs(
    *, n_members: int = 3, duration_s: float = 12.0, warmup_s: float = 8.0,
    width: int = 128, height: int = 96, fps: float = 30.0,
    model: str = "tiny_yolov8", native: bool = False,
    workdir: Optional[str] = None,
) -> dict:
    """r14 fleet telemetry soak: N REAL server processes (each with its
    own subprocess ingest worker, shm bus, engine, gRPC + REST), one
    FleetAggregator scraping them, and one gRPC client per member
    recording the ``InferenceResult.trace_id`` echo. Produces the
    ``FLEETOBS_r01.json`` payload with the four hard gates:

    - ``merged_lint_clean`` — the aggregator's single Prometheus page
      passes ``metrics.lint_exposition``;
    - ``all_members_present`` — every member alive + fresh in the ranked
      health view at quiesce;
    - ``stitched_traces`` >= 1 — at least one trace_id stamped in a
      member's WORKER process (nonzero on the wire) observed through the
      engine's collect/device/emit spans AND received by the client —
      the full worker -> bus -> engine -> client lineage;
    - ``counters_conserved`` — after quiesce, every merged counter
      equals the sum of the members' individually-scraped values.
    """
    import json as _json
    import shutil
    import subprocess
    import sys
    import tempfile
    import urllib.request

    import grpc

    from ..obs.fleet import FleetAggregator, _strip_label, parse_exposition
    from ..obs.metrics import lint_exposition
    from ..obs.spans import to_chrome_trace, validate_chrome_trace
    from ..proto import pb, pb_grpc

    tmp = workdir or tempfile.mkdtemp(prefix="vep_fleetobs_")
    procs: list = []
    spans_paths: list = []
    try:
        for i in range(n_members):
            device = f"fleet{i}"
            trace_path = os.path.join(tmp, f"{device}.vtrace")
            record_synthetic_trace(
                trace_path, [device], width=width, height=height, fps=fps,
                gop=30, frames=max(90, int(fps * 10)))
            spans_out = os.path.join(tmp, f"m{i}_spans.json")
            spans_paths.append(spans_out)
            member_dir = os.path.join(tmp, f"m{i}")
            os.makedirs(member_dir, exist_ok=True)
            cmd = [
                sys.executable, "-m",
                "video_edge_ai_proxy_tpu.replay.harness",
                "--instance", f"m{i}", "--workdir", member_dir,
                "--trace", trace_path, "--device", device,
                "--model", model, "--duration", str(duration_s),
                "--warmup", str(warmup_s), "--spans-out", spans_out,
            ]
            if native:
                cmd.append("--native")
            env = dict(os.environ)
            if not native:
                env["JAX_PLATFORMS"] = "cpu"
            procs.append(subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=open(os.path.join(tmp, f"m{i}.stderr"), "w"),
                env=env, text=True))

        def read_msg(proc, key, timeout_s=120.0):
            """Next stdout JSON line carrying ``key`` (skips log noise);
            SystemExit with the member's stderr tail on death/timeout."""
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    raise SystemExit(
                        f"fleet member died (rc={proc.poll()}); see "
                        f"{tmp}/m*.stderr")
                try:
                    msg = _json.loads(line)
                except ValueError:
                    continue
                if key in msg:
                    return msg
            raise SystemExit(f"fleet member: no {key!r} within {timeout_s}s")

        boots = [read_msg(p, "ready") for p in procs]
        rest_ports = [b["rest_port"] for b in boots]
        grpc_ports = [b["grpc_port"] for b in boots]

        agg = FleetAggregator(
            [f"m{i}=http://127.0.0.1:{rest_ports[i]}"
             for i in range(n_members)],
            scrape_interval_s=1.0)
        agg.start()

        client_tids: list = [set() for _ in range(n_members)]
        results_count = [0] * n_members
        stop = threading.Event()

        def client(i: int) -> None:
            channel = grpc.insecure_channel(f"127.0.0.1:{grpc_ports[i]}")
            stub = pb_grpc.ImageStub(channel)
            while not stop.is_set():
                try:
                    for res in stub.Inference(
                            pb.InferenceRequest(), timeout=5):
                        if stop.is_set():
                            break
                        results_count[i] += 1
                        if res.trace_id:
                            client_tids[i].add(res.trace_id)
                except grpc.RpcError:
                    if not stop.is_set():
                        time.sleep(0.5)
            channel.close()

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_members)]
        for t in threads:
            t.start()

        for p in procs:
            read_msg(p, "quiesced", timeout_s=warmup_s + duration_s + 120.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)

        # Conservation window: streams are stopped and drained, but a
        # few heartbeat counters (engine tick loop) keep moving. Bracket
        # the aggregator's scrape with two direct member scrapes and
        # gate ONLY the families that were provably static across the
        # whole window (frame/result counters are; tick counters
        # self-exclude) — merged value must equal the member-wise sum.
        def scrape_pages():
            pages = []
            for port in rest_ports:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                    pages.append(r.read().decode())
            return pages

        def counter_sums(pages):
            out: dict = {}
            for page in pages:
                for fam in parse_exposition(page):
                    if fam["kind"] != "counter":
                        continue
                    for _name, labels, value in fam["samples"]:
                        key = (fam["name"],
                               _strip_label(labels, "instance"))
                        out[key] = out.get(key, 0.0) + value
            return out

        pages_before = scrape_pages()
        agg.scrape_once()
        pages_after = scrape_pages()
        member_lint = [lint_exposition(p) for p in pages_after]
        before = counter_sums(pages_before)
        after = counter_sums(pages_after)
        static_keys = sorted(
            k for k, v in before.items() if after.get(k) == v)
        merged_counters = agg.fleet_stats()["counters"]
        mismatches = []
        for fam_name, labels in static_keys:
            want = before[(fam_name, labels)]
            got = merged_counters.get(fam_name, {}).get(
                labels, {}).get("value")
            if got is None or abs(got - want) > 1e-6:
                mismatches.append({
                    "family": fam_name, "labels": labels,
                    "member_sum": want, "merged": got})

        merged_text = agg.merged_exposition()
        lint_errors = lint_exposition(merged_text)
        health = agg.health()
        all_present = (
            len(health) == n_members
            and all(h["up"] and not h["stale"] for h in health))

        # Release members -> they dump spans and exit.
        for p in procs:
            try:
                p.stdin.write("exit\n")
                p.stdin.flush()
                p.stdin.close()
            except (BrokenPipeError, OSError):
                pass
        for p in procs:
            p.wait(timeout=60)
        agg.stop()

        member_spans = []
        for path in spans_paths:
            with open(path) as f:
                member_spans.append(_json.load(f).get("events", []))

        # One fleet timeline: per-member pid namespaces (the same merge
        # tools/obs_export.py --merge --member performs).
        merged_events: list = []
        for i, evs in enumerate(member_spans):
            merged_events.extend(to_chrome_trace(
                evs, pid=i + 1, process_name=f"m{i}")["traceEvents"])
        fleet_trace = {"traceEvents": merged_events,
                       "displayTimeUnit": "ms"}
        trace_problems = validate_chrome_trace(fleet_trace)

        # Cross-process stitching: the trace_id was minted in the ingest
        # WORKER process (FrameMeta on the shm bus), observed by the
        # engine's spans, and echoed to the gRPC client.
        stitched = []
        for i, evs in enumerate(member_spans):
            stages_by_tid: dict = {}
            for ev in evs:
                tid = ev.get("trace_id")
                if tid:
                    stages_by_tid.setdefault(tid, set()).add(ev["stage"])
            for tid, stages in sorted(stages_by_tid.items()):
                if ({"collect", "device", "emit"} <= stages
                        and tid in client_tids[i]):
                    stitched.append({
                        "member": f"m{i}", "trace_id": tid,
                        "stages": sorted(stages)})

        return {
            "metric": f"fleet_obs_{n_members}x_{model}",
            "pipeline": (
                f"{n_members}x [replay worker -> shm bus -> engine -> "
                "gRPC/REST] -> FleetAggregator + per-member clients"),
            "members": n_members,
            "duration_s": duration_s,
            "model": model,
            "fps": fps,
            "gates": {
                "merged_lint_clean": not lint_errors,
                "member_lint_clean": all(not e for e in member_lint),
                "all_members_present": all_present,
                "stitched_traces": len(stitched),
                "counters_conserved": bool(static_keys) and not mismatches,
                "fleet_trace_valid": not trace_problems,
            },
            "lint_errors": lint_errors[:10],
            "counters_gated": len(static_keys),
            "counter_mismatches": mismatches[:10],
            "trace_problems": trace_problems[:10],
            "health": health,
            "stitched_example": stitched[0] if stitched else None,
            "client_results": results_count,
            "client_trace_ids": [len(s) for s in client_tids],
            "merged_exposition_lines": len(merged_text.splitlines()),
            "merged_counter_families": len(merged_counters),
            "fleet_trace_events": len(merged_events),
            "span_events_per_member": [len(s) for s in member_spans],
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()   # by PID via Popen handle — never pkill
        if workdir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def run_router_soak(
    *, n_members: int = 3, streams_per_member: int = 2,
    width: int = 128, height: int = 96, fps: float = 2.0,
    model: str = "tiny_yolov8", scrape_interval_s: float = 1.0,
    ladder_escalate_s: float = 8.0, native: bool = False,
    workdir: Optional[str] = None,
) -> dict:
    """r16 fleet-router soak: N REAL serve-only server processes, one
    :class:`~..serve.router.StreamRouter` placing ``n_members *
    streams_per_member`` replay streams across them, then two fault
    legs with hard gates (the ``ROUTER_r01.json`` payload):

    - **burn leg** — force the SLO-burn verdict on one member
      (stdin ``burn``; the member runs ``--slo-off`` so nothing
      recomputes the flag). Its ladder walks shed → shed_to_fleet; the
      router sees the rung and gracefully migrates the member's streams
      (drain→cutover→resume at the replay cursor). Gate: at migration
      completion the member's ladder shows ``shed_to_fleet >= 1`` and
      ``bucket_downshift == 0`` transitions — horizontal re-placement
      engaged BEFORE the local ladder shrank device programs.
    - **kill leg** — SIGKILL one member. Gate: every one of its streams
      is re-placed with detection-to-resumed latency within one scrape
      interval (detection itself is bounded by the scrape cadence; the
      wall-clock kill→resumed bound is ``scrape_interval + 1s``).

    Cross-cutting gates: the frame-conservation ledger balances for
    EVERY stream (packet ids gap-free from the very FIRST delivery,
    zero duplicates — exactly-once across the handoffs; members prewarm
    their one device program at boot, so there is no compile ramp to
    excuse and no post-warmup ledger reset); every completed migration has a
    stitched worker→bus→engine→client lineage (span chain
    collect+device+emit for a trace id the destination's gRPC client
    also received — and the source's too on the graceful leg); and the
    router's ``vep_router_*`` exposition is ``lint_exposition``-clean.

    Determinism levers: members pin ONE batch bucket and prewarm its
    program at boot (any in-soak compile — first frame or migrated
    stream joining — would drop frames via latest-frame-wins and
    corrupt the ledger), shed staleness is set
    above the soak length (the shed rung itself drops nothing),
    ``ladder_escalate_s`` spaces the rungs so migration has a full
    window between shed_to_fleet and bucket_downshift, ``fps`` sits
    well below the CPU backend's per-member tick rate (latest-frame-wins
    never overwrites an uncollected frame, so steady state is lossless
    and the ledger attributes any gap to migration), and members run
    ``--ladder-slo-only`` (physical tick-lag pressure is unavoidable on
    CPU and would walk EVERY member's ladder — the injected burn must be
    the only rung driver or the fleet ping-pongs).
    """
    import json as _json
    import shutil
    import subprocess
    import sys
    import tempfile
    import urllib.request

    import grpc

    from ..obs import registry as obs_registry
    from ..obs.metrics import lint_exposition
    from ..proto import pb, pb_grpc
    from ..serve.router import StreamRouter

    tmp = workdir or tempfile.mkdtemp(prefix="vep_router_")
    member_names = [f"m{i}" for i in range(n_members)]
    bucket = 1
    while bucket < n_members * streams_per_member + 2:
        bucket *= 2
    procs: list = []
    spans_paths: list = []
    router: Optional[StreamRouter] = None
    stop = threading.Event()
    threads: list = []
    try:
        for i, mname in enumerate(member_names):
            spans_out = os.path.join(tmp, f"{mname}_spans.json")
            spans_paths.append(spans_out)
            member_dir = os.path.join(tmp, mname)
            os.makedirs(member_dir, exist_ok=True)
            cmd = [
                sys.executable, "-m",
                "video_edge_ai_proxy_tpu.replay.harness",
                "--instance", mname, "--workdir", member_dir,
                "--model", model, "--spans-out", spans_out,
                "--serve-only", "--slo-off", "--ladder-slo-only",
                "--ladder-escalate", str(ladder_escalate_s),
                "--shed-staleness-ms", "60000",
                "--batch-bucket", str(bucket),
                "--trace-every", "1",
                # The member's ONE device program compiles during boot
                # (before the ready line), not on the first delivered
                # frame: the compile ramp used to overwrite ~20 frames
                # per stream (latest-frame-wins) and forced a post-warmup
                # ledger reset — prewarmed, conservation holds from the
                # very first frame (r19; see MigrationLedger docstring).
                "--prewarm", f"{height}x{width}x{bucket}",
            ]
            if native:
                cmd.append("--native")
            env = dict(os.environ)
            if not native:
                env["JAX_PLATFORMS"] = "cpu"
            procs.append(subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=open(os.path.join(tmp, f"{mname}.stderr"), "w"),
                env=env, text=True))

        def read_msg(proc, key, timeout_s=240.0):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    raise SystemExit(
                        f"router-soak member died (rc={proc.poll()}); "
                        f"see {tmp}/m*.stderr")
                try:
                    msg = _json.loads(line)
                except ValueError:
                    continue
                if key in msg:
                    return msg
            raise SystemExit(f"router-soak member: no {key!r} within "
                             f"{timeout_s}s")

        def send_cmd(idx: int, cmd: str, ack: bool = True):
            procs[idx].stdin.write(cmd + "\n")
            procs[idx].stdin.flush()
            if ack:
                read_msg(procs[idx], "ack", timeout_s=30.0)

        boots = [read_msg(p, "ready") for p in procs]
        rest_ports = [b["rest_port"] for b in boots]
        grpc_ports = [b["grpc_port"] for b in boots]

        router = StreamRouter(
            [f"{m}=http://127.0.0.1:{rest_ports[i]}"
             for i, m in enumerate(member_names)],
            scrape_interval_s=scrape_interval_s,
            max_moves_per_pass=n_members * streams_per_member,
            # Drain poll/settle must cover a full CPU inference tick
            # (~0.2-0.4s): a frame collected just before the stop lands
            # on the src's counter up to one tick AFTER it first reads
            # static, and a cursor read inside that window would resume
            # the dst on an already-delivered packet (duplicate).
            drain_timeout_s=5.0, drain_poll_s=0.5)
        router.run_pass()                       # first health view
        attach_errors = {k: v for k, v in router.attach().items() if v}

        # Balanced initial placement by CONSTRUCTION of the names: walk
        # candidate stream names and keep the first streams_per_member
        # that consistent-hash onto each member — every member compiles
        # its (single) device program during warmup, so neither fault
        # leg's destination ever compiles on a migrated stream's frames.
        per_member: dict = {m: [] for m in member_names}
        cand = 0
        while any(len(v) < streams_per_member for v in per_member.values()):
            name = f"cam{cand:03d}"
            cand += 1
            owner = router.ring.place(name)
            if owner and len(per_member[owner]) < streams_per_member:
                per_member[owner].append(name)
            if cand > 10_000:
                raise SystemExit("hash search failed to balance placement")
        stream_names = [n for m in member_names for n in per_member[m]]
        # One long trace per stream: frames must OUTLAST the soak
        # (loop/EOF-restart would re-deliver packet ids and fake a
        # conservation violation).
        for name in stream_names:
            record_synthetic_trace(
                os.path.join(tmp, f"{name}.vtrace"), [name],
                width=width, height=height, fps=fps, gop=30,
                frames=int(fps * 240))

        # Per-member result consumers feed the router's conservation
        # ledger: (stream, member, packet, trace_id) for every delivered
        # InferenceResult — the client side of the lineage chain.
        tids: dict = {m: {} for m in member_names}

        def client(i: int) -> None:
            mname = member_names[i]
            channel = grpc.insecure_channel(f"127.0.0.1:{grpc_ports[i]}")
            stub = pb_grpc.ImageStub(channel)
            while not stop.is_set():
                try:
                    # NO deadline: a deadline-kicked re-subscribe loop
                    # would miss the results emitted during each gap and
                    # fake conservation-ledger losses. Streams keep
                    # flowing until shutdown, so the stop flag is always
                    # reached; a dead member raises instead.
                    for res in stub.Inference(pb.InferenceRequest()):
                        if stop.is_set():
                            break
                        if not res.device_id:
                            continue
                        router.ledger.note_delivery(
                            res.device_id, mname, res.frame_packet,
                            res.trace_id)
                        if res.trace_id:
                            tids[mname].setdefault(
                                res.device_id, set()).add(res.trace_id)
                except grpc.RpcError:
                    if not stop.is_set():
                        time.sleep(0.25)
            channel.close()

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_members)]
        for t in threads:
            t.start()

        for name in stream_names:
            placed = router.add_stream(
                name,
                f"replay://{tmp}/{name}.vtrace?device={name}&pace=1&loop=0",
                priority=stream_names.index(name))
            assert placed in per_member and name in per_member[placed]

        # Warmup: every stream delivering (worker boot + the one compile
        # per member), then let the pipeline settle.
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            if all(router.ledger.next_cursor(n) is not None
                   for n in stream_names):
                break
            time.sleep(0.25)
        else:
            raise SystemExit(
                "warmup: not every stream delivered results; see "
                f"{tmp}/m*.stderr")
        time.sleep(2.0)                         # pipeline settles
        router.start()                          # background control loop

        # ---- burn leg: m0 burns; ladder must hand off BEFORE downshift.
        burn_member = member_names[0]
        burn_streams = list(per_member[burn_member])
        send_cmd(0, "burn")
        t_burn = time.monotonic()
        deadline = t_burn + 2 * ladder_escalate_s + 3 * scrape_interval_s \
            + 10.0
        while time.monotonic() < deadline:
            if not router.streams_on(burn_member):
                break
            time.sleep(0.05)
        burn_evacuated = not router.streams_on(burn_member)
        t_burn_done = time.monotonic()
        # Ladder state AT migration completion — then calm immediately,
        # before idle burn pressure walks the member any further.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rest_ports[0]}/api/v1/router",
                timeout=5) as r:
            burn_ladder = _json.loads(r.read())
        send_cmd(0, "calm")
        burn_transitions = burn_ladder.get("transitions", {})
        # Wait out the ladder's recovery walk (one rung per
        # recover_after_s): while the burn member still reports
        # shed_to_fleet or above, the router would immediately re-shed
        # any stream the kill leg evacuates onto it.
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rest_ports[0]}/api/v1/router",
                    timeout=5) as r:
                if _json.loads(r.read()).get("rung") in ("normal", "shed"):
                    break
            time.sleep(0.25)
        time.sleep(3.0)                         # resumed streams deliver

        # ---- kill leg: SIGKILL the last member; the router must
        # re-place its streams within one scrape interval of detection.
        kill_idx = n_members - 1
        kill_member = member_names[kill_idx]
        kill_streams = list(router.streams_on(kill_member))
        procs[kill_idx].kill()   # by PID via Popen handle — never pkill
        procs[kill_idx].wait(timeout=10)
        t_kill = time.monotonic()
        deadline = t_kill + 3 * scrape_interval_s + 10.0
        while time.monotonic() < deadline:
            if not router.streams_on(kill_member):
                break
            time.sleep(0.02)
        kill_wall_s = time.monotonic() - t_kill
        kill_evacuated = not router.streams_on(kill_member)
        time.sleep(4.0)                         # resumed streams deliver

        router.stop()
        migrations = list(router.ledger.migrations)
        kill_migs = [m for m in migrations if m["reason"] == "member_dead"]
        burn_migs = [m for m in migrations
                     if m["src"] == burn_member and m["ok"]]
        kill_detect_s = max(
            (m["replace_s"] for m in kill_migs if m.get("ok")),
            default=None)

        stop.set()
        for t in threads:
            t.join(timeout=10)
        balance = router.ledger.balance()

        # Release survivors -> span dumps; the killed member left none.
        for i, p in enumerate(procs):
            if i == kill_idx:
                continue
            try:
                send_cmd(i, "exit", ack=False)
                p.stdin.close()
            except (BrokenPipeError, OSError):
                pass
        for i, p in enumerate(procs):
            if i != kill_idx:
                p.wait(timeout=60)

        member_spans: dict = {}
        for mname, path in zip(member_names, spans_paths):
            if not os.path.exists(path):
                member_spans[mname] = []
                continue
            with open(path) as f:
                member_spans[mname] = _json.load(f).get("events", [])

        def stitched(mname: str, stream: str) -> bool:
            """A trace id with the full collect+device+emit span chain on
            ``mname`` that ``mname``'s gRPC client also delivered for
            ``stream`` — worker->bus->engine->client, one id."""
            stages_by_tid: dict = {}
            for ev in member_spans.get(mname, []):
                tid = ev.get("trace_id")
                if tid:
                    stages_by_tid.setdefault(tid, set()).add(ev["stage"])
            want = tids.get(mname, {}).get(stream, set())
            return any({"collect", "device", "emit"} <= stages
                       and tid in want
                       for tid, stages in stages_by_tid.items())

        lineage = []
        for m in migrations:
            if not m.get("ok"):
                continue
            row = {"stream": m["stream"], "src": m["src"],
                   "dst": m["dst"], "reason": m["reason"],
                   "dst_stitched": stitched(m["dst"], m["stream"])}
            if (not row["dst_stitched"] and m["dst"] == kill_member
                    and not member_spans.get(kill_member)):
                # A burn-leg migration may land on the member the kill
                # leg later SIGKILLs — the kill forfeits its span dump,
                # so the on-wire trace ids its gRPC client DID deliver
                # for the stream are the surviving lineage evidence.
                row["dst_stitched"] = bool(
                    tids.get(kill_member, {}).get(m["stream"]))
                row["dst_evidence"] = \
                    "client-delivered trace ids (span dump lost to kill)"
            if m["src"] != kill_member:
                row["src_stitched"] = stitched(m["src"], m["stream"])
            lineage.append(row)
        lineage_ok = bool(lineage) and all(
            r["dst_stitched"] and r.get("src_stitched", True)
            for r in lineage)

        exposition = obs_registry.render()
        lint_errors = lint_exposition(exposition)
        router_families = sorted({
            line.split()[2] for line in exposition.splitlines()
            if line.startswith("# TYPE vep_router_")})

        gates = {
            "attach_clean": not attach_errors,
            "burn_streams_evacuated": burn_evacuated and bool(burn_migs),
            "burn_shed_to_fleet_before_downshift": (
                burn_transitions.get("shed_to_fleet", 0) >= 1
                and burn_transitions.get("bucket_downshift", 0) == 0),
            "kill_streams_replaced": (
                kill_evacuated and bool(kill_streams)
                and all(m.get("ok") for m in kill_migs)),
            "kill_replace_within_scrape": (
                kill_detect_s is not None
                and kill_detect_s <= scrape_interval_s),
            "kill_replace_wall_bounded": (
                kill_wall_s <= scrape_interval_s + 1.0),
            "ledger_balanced": balance["balanced"],
            "migrated_lineage_stitched": lineage_ok,
            "router_metrics_lint_clean": (
                not lint_errors and len(router_families) >= 6),
        }
        return {
            "metric": f"fleet_router_{n_members}x{streams_per_member}_"
                      f"{model}",
            "pipeline": (
                f"{n_members}x serve-only member <- StreamRouter "
                "(consistent hash + burn/kill migration) <- per-member "
                "gRPC clients -> conservation ledger"),
            "members": n_members,
            "streams": len(stream_names),
            "fps": fps,
            "model": model,
            "scrape_interval_s": scrape_interval_s,
            "ladder_escalate_s": ladder_escalate_s,
            "gates": gates,
            "placement": per_member,
            "burn": {
                "member": burn_member,
                "streams": burn_streams,
                "migrate_s": round(t_burn_done - t_burn, 3),
                "transitions_at_migration": burn_transitions,
                "ladder": burn_ladder,
                "migrations": burn_migs,
            },
            "kill": {
                "member": kill_member,
                "streams": kill_streams,
                "replace_detect_s": kill_detect_s,
                "replace_wall_s": round(kill_wall_s, 3),
                "migrations": kill_migs,
            },
            "ledger": {
                "balanced": balance["balanced"],
                "lost": balance["lost"],
                "duplicated": balance["duplicated"],
                "streams": balance["streams"],
            },
            "lineage": lineage,
            "lint_errors": lint_errors[:10],
            "router_families": router_families,
            "router_snapshot": router.snapshot(),
        }
    finally:
        stop.set()
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()   # by PID via Popen handle — never pkill
        if workdir is None:
            shutil.rmtree(tmp, ignore_errors=True)


class LoadShape:
    """Production-shaped churn schedule for the autoscale soak (r19).

    Four shapes the reference deployments actually see, folded into one
    deterministic timetable (no RNG — reruns hit identical schedules):

    - **diurnal ramp** — ``ramp_streams`` cameras connect one every
      ``ramp_interval_s`` on top of the ``base_streams`` steady tenants:
      the morning build-up whose utilization *slope* the r18 capacity
      forecast extrapolates into ``time_to_saturation_s`` — the signal
      the supervisor must act on BEFORE saturation, not after. The ramp
      deliberately outlasts a spawned member's boot, so the arrivals
      still connecting when the fresh member comes up land on it (the
      headroom-tiered admission prefers the emptiest member) — scale-out
      absorbs the tail of the very build-up that triggered it.
    - **connect/disconnect storm** — ``storm_streams`` cameras connect
      within seconds (an NVR rebooting, a site coming back from a
      network partition) and later disconnect just as fast. The storm
      lands AFTER the ramp so a forecast-driven scale-out has already
      added capacity when it hits.
    - **hot-spot camera** — the first base stream runs ``hot_fps``
      against everyone else's ``base_fps``: one member always carries
      visibly more load than its peers, so placement/retire decisions
      ride on real per-member skew, not uniform load.
    - **mixed model tenants** — stream specs rotate through ``models``
      (``""`` = the member default), so members serve multiple device
      programs and the AOT prewarm manifest has to carry the full
      program SET, not one geometry.

    ``specs()`` lists every stream (name, fps, model, phase);
    ``events()`` is the sorted ``{"t", "op", "stream"}`` timetable
    relative to the soak's post-warmup t=0 (base connects at t<=0 run
    before the supervisor starts).
    """

    def __init__(
        self, *, base_streams: int = 3, ramp_streams: int = 6,
        ramp_start_s: float = 2.0, ramp_interval_s: float = 4.0,
        storm_streams: int = 6, storm_start_s: float = 28.0,
        storm_spacing_s: float = 0.4, storm_hold_s: float = 18.0,
        drain_interval_s: float = 0.8,
        base_fps: float = 0.5, hot_fps: float = 1.5,
        models: tuple = ("", "tiny_mobilenet_v2"),
    ):
        if base_streams < 1 or storm_streams < 1:
            raise ValueError("need at least one base and one storm stream")
        if storm_start_s <= ramp_start_s + ramp_streams * ramp_interval_s:
            raise ValueError(
                "storm must start after the ramp finishes (the shape's "
                "point is that forecast-driven scale-out lands first)")
        self.base_streams = int(base_streams)
        self.ramp_streams = int(ramp_streams)
        self.ramp_start_s = float(ramp_start_s)
        self.ramp_interval_s = float(ramp_interval_s)
        self.storm_streams = int(storm_streams)
        self.storm_start_s = float(storm_start_s)
        self.storm_spacing_s = float(storm_spacing_s)
        self.storm_hold_s = float(storm_hold_s)
        self.drain_interval_s = float(drain_interval_s)
        self.base_fps = float(base_fps)
        self.hot_fps = float(hot_fps)
        self.models = tuple(models)

    def specs(self) -> list:
        out = []
        tenant = 0
        for phase, count, prefix in (
                ("base", self.base_streams, "base"),
                ("ramp", self.ramp_streams, "ramp"),
                ("storm", self.storm_streams, "storm")):
            for i in range(count):
                hot = phase == "base" and i == 0
                out.append({
                    "stream": f"{prefix}{i:03d}",
                    "phase": phase,
                    "hot": hot,
                    "fps": self.hot_fps if hot else self.base_fps,
                    "model": self.models[tenant % len(self.models)],
                })
                tenant += 1
        return out

    def events(self) -> list:
        ev = []
        for spec in self.specs():
            name, phase = spec["stream"], spec["phase"]
            i = int(name[-3:])
            if phase == "base":
                ev.append({"t": 0.0, "op": "connect", "stream": name})
            elif phase == "ramp":
                t_on = self.ramp_start_s + i * self.ramp_interval_s
                ev.append({"t": t_on, "op": "connect", "stream": name})
                # Ramp sheds after the storm has fully drained: the
                # surplus the retire leg waits on is sustained, not a
                # lull between waves.
                t_off = (self.storm_start_s + self.storm_hold_s
                         + self.storm_streams * self.drain_interval_s
                         + 1.0 + i * self.drain_interval_s)
                ev.append({"t": t_off, "op": "disconnect", "stream": name})
            else:
                t_on = self.storm_start_s + i * self.storm_spacing_s
                ev.append({"t": t_on, "op": "connect", "stream": name})
                t_off = (self.storm_start_s + self.storm_hold_s
                         + i * self.drain_interval_s)
                ev.append({"t": t_off, "op": "disconnect", "stream": name})
        ev.sort(key=lambda e: (e["t"], e["stream"], e["op"]))
        return ev

    @property
    def duration_s(self) -> float:
        return max(e["t"] for e in self.events())


def run_autoscale_soak(
    *, width: int = 128, height: int = 96, model: str = "tiny_yolov8",
    scrape_interval_s: float = 1.0,
    capacity_scrape_interval_s: float = 30.0,
    decision_interval_s: float = 1.0, spawn_horizon_s: float = 1800.0,
    surplus_headroom: float = 0.3, surplus_hold_s: float = 8.0,
    spawn_cooldown_s: float = 12.0, retire_cooldown_s: float = 60.0,
    capacity_fast_window_s: float = 5.0,
    storm_admission_bound_s: float = 12.0,
    shape: Optional[LoadShape] = None,
    native: bool = False, workdir: Optional[str] = None,
) -> dict:
    """r19 autoscale soak: a :class:`~..serve.supervisor.FleetSupervisor`
    with a REAL subprocess spawner over a :class:`LoadShape` churn
    schedule — the ``AUTOSCALE_r01.json`` payload.

    Two members boot sequentially against a shared persistent AOT cache
    dir (m0 cold — it POPULATES the cache and the prewarm manifest; m1's
    identical prewarm set is already a persistent-cache hit). The
    supervisor's spawned member boots with NO ``--prewarm`` flags at
    all: its program set comes purely from the manifest, every compile a
    cache hit — the spawn path the r19 cache exists for.

    Gates:

    - ``scale_out_on_forecast`` / ``scale_out_beats_burn`` — the one
      spawn is reason ``saturation_forecast`` (the ramp's utilization
      slope crossed the horizon) and landed while fleet ``min_headroom``
      was still positive: capacity arrived BEFORE the burn, not after.
    - ``spawn_prewarm_from_manifest`` — the spawned member's
      ``/api/v1/stats`` prewarm block shows the manifest supplied (and
      it completed) every recorded program with the cache enabled.
    - ``spawn_first_frame_within_scrape`` — Popen→first-served-frame on
      the spawned member lands inside ONE capacity-forecast scrape
      interval (``capacity_scrape_interval_s``, the O(10 s) cadence a
      production fleet scrapes capacity at — distinct from the router's
      1 s liveness scrape): the member is serving before the forecast
      plane would even re-sample.
    - ``storm_admission_bounded`` — connect→first-frame p99 across the
      storm stays under ``storm_admission_bound_s``.
    - ``retire_on_surplus`` / ``no_flap`` — after the storm and ramp
      drain, sustained surplus retires exactly one member (drained via
      the r16 lineage-verified ``scale_in`` migration) and the member
      set neither re-spawns on the drain's utilization echo nor
      oscillates: one spawn, one retire, back at ``min_members``.
    - ``ledger_balanced`` — zero frames lost, zero duplicated across
      admission, storm churn, scale-out and the retire drain. Members
      prewarm every program they serve, so conservation holds from the
      very first frame of every stream with NO warmup exclusion.
    - ``supervisor_metrics_lint_clean`` — ``vep_supervisor_*`` is
      ``lint_exposition``-clean.

    Determinism levers carry over from :func:`run_router_soak` (pinned
    single bucket, prewarmed programs, ``--slo-off --ladder-slo-only``,
    shed staleness above the soak length, fps under the CPU tick rate);
    new here: ``capacity_fast_window_s`` shrinks the burn window to fit
    the soak's ramp, the supervisor's symmetric spawn cooldown outlasts
    it so the retire drain's slope echo cannot re-spawn, and
    ``retire_cooldown_s`` outlasts the whole churn schedule — the CPU
    twin's utilization never dents headroom, so the surplus BAR is held
    throughout and the cooldown is what makes "sustained surplus" mean
    "after the storm and ramp drained" instead of "the first quiet
    10 s" (on the real chip the bar itself does this work).
    """
    import json as _json
    import itertools
    import shutil
    import subprocess
    import sys
    import tempfile
    import urllib.request

    import grpc

    from ..obs import registry as obs_registry
    from ..obs.metrics import lint_exposition
    from ..proto import pb, pb_grpc
    from ..serve.router import StreamRouter
    from ..serve.supervisor import FleetSupervisor

    shape = shape or LoadShape()
    tmp = workdir or tempfile.mkdtemp(prefix="vep_autoscale_")
    aot_dir = os.path.join(tmp, "aot_cache")
    bucket = 8
    specs = {s["stream"]: s for s in shape.specs()}
    tenant_models = sorted({s["model"] for s in shape.specs()
                            if s["model"]})

    stop = threading.Event()
    rx_lock = threading.Lock()
    first_rx: dict = {}          # stream -> monotonic of first delivery
    member_first_rx: dict = {}   # member -> monotonic of first frame served
    t_admit: dict = {}           # stream -> monotonic at admit()
    procs_by_name: dict = {}
    boots: dict = {}             # member -> {"boot_s", rest/grpc ports}
    spawn_info: dict = {}
    retire_info: dict = {}
    failures: list = []
    threads: list = []
    router: Optional[StreamRouter] = None
    sup: Optional[FleetSupervisor] = None

    def read_msg(proc, key, timeout_s=300.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise SystemExit(
                    f"autoscale member died (rc={proc.poll()}); "
                    f"see {tmp}/*.stderr")
            try:
                msg = _json.loads(line)
            except ValueError:
                continue
            if key in msg:
                return msg
        raise SystemExit(f"autoscale member: no {key!r} within {timeout_s}s")

    def _boot_member(mname: str, *, prewarm: bool):
        """Popen → ready line; returns (base_url, grpc_port). With
        ``prewarm=False`` the member gets NO --prewarm flags: its
        program set must come from the shared AOT cache's manifest."""
        member_dir = os.path.join(tmp, mname)
        os.makedirs(member_dir, exist_ok=True)
        cmd = [
            sys.executable, "-m",
            "video_edge_ai_proxy_tpu.replay.harness",
            "--instance", mname, "--workdir", member_dir,
            "--model", model,
            "--spans-out", os.path.join(tmp, f"{mname}_spans.json"),
            "--serve-only", "--slo-off", "--ladder-slo-only",
            "--shed-staleness-ms", "600000",
            "--batch-bucket", str(bucket),
            "--capacity",
            "--capacity-fast-window", str(capacity_fast_window_s),
            "--aot-cache", aot_dir,
        ]
        if prewarm:
            cmd += ["--prewarm", f"{height}x{width}x{bucket}"]
            for mdl in tenant_models:
                cmd += ["--prewarm", f"{height}x{width}x{bucket}:{mdl}"]
        if native:
            cmd.append("--native")
        env = dict(os.environ)
        if not native:
            env["JAX_PLATFORMS"] = "cpu"
        t0 = time.monotonic()
        proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"{mname}.stderr"), "w"),
            env=env, text=True)
        procs_by_name[mname] = proc
        msg = read_msg(proc, "ready")
        boots[mname] = {
            "boot_s": round(time.monotonic() - t0, 3),
            "rest_port": msg["rest_port"], "grpc_port": msg["grpc_port"],
            "prewarm_flags": prewarm,
        }
        return f"http://127.0.0.1:{msg['rest_port']}", msg["grpc_port"]

    def _start_client(mname: str, grpc_port: int) -> None:
        def _client():
            channel = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
            stub = pb_grpc.ImageStub(channel)
            while not stop.is_set():
                try:
                    for res in stub.Inference(pb.InferenceRequest()):
                        if stop.is_set():
                            break
                        if not res.device_id:
                            continue
                        now = time.monotonic()
                        router.ledger.note_delivery(
                            res.device_id, mname, res.frame_packet,
                            res.trace_id)
                        with rx_lock:
                            first_rx.setdefault(res.device_id, now)
                            member_first_rx.setdefault(mname, now)
                except grpc.RpcError:
                    if not stop.is_set():
                        time.sleep(0.25)
            channel.close()
        t = threading.Thread(target=_client, daemon=True,
                             name=f"autoscale-client-{mname}")
        threads.append(t)
        t.start()

    def _send_exit(mname: str) -> None:
        proc = procs_by_name.get(mname)
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.stdin.write("exit\n")
            proc.stdin.flush()
            proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()   # by PID via Popen handle — never pkill

    try:
        for spec in shape.specs():
            record_synthetic_trace(
                os.path.join(tmp, f"{spec['stream']}.vtrace"),
                [spec["stream"]], width=width, height=height,
                fps=spec["fps"], gop=30, frames=int(spec["fps"] * 240))

        # m0 boots COLD (populates the persistent cache + manifest), m1
        # boots against the populated dir — sequentially, so m1's boot
        # time already shows the cache-hit delta.
        urls = {}
        for mname in ("m0", "m1"):
            urls[mname], _ = _boot_member(mname, prewarm=True)

        router = StreamRouter(
            [f"{m}={urls[m]}" for m in ("m0", "m1")],
            scrape_interval_s=scrape_interval_s,
            max_moves_per_pass=16,
            drain_timeout_s=5.0, drain_poll_s=0.5)
        router.run_pass()
        attach_errors = {k: v for k, v in router.attach().items() if v}
        for mname in ("m0", "m1"):
            _start_client(mname, boots[mname]["grpc_port"])
        router.start()

        admit_seq = itertools.count()

        def _admit(name: str) -> None:
            url = (f"replay://{tmp}/{name}.vtrace?device={name}"
                   "&pace=1&loop=0")
            t_admit[name] = time.monotonic()
            try:
                router.admit(name, url, priority=next(admit_seq),
                             inference_model=specs[name]["model"])
            except Exception as exc:  # noqa: BLE001 — gate, don't abort
                failures.append(f"admit {name}: {type(exc).__name__}: "
                                f"{exc}")

        events = shape.events()
        for ev in [e for e in events if e["t"] <= 0.0]:
            _admit(ev["stream"])
        base_names = [s["stream"] for s in shape.specs()
                      if s["phase"] == "base"]
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            with rx_lock:
                if all(n in first_rx for n in base_names):
                    break
            time.sleep(0.25)
        else:
            raise SystemExit("warmup: base streams never all delivered; "
                             f"see {tmp}/*.stderr")
        # Let the connect transient leave the fast burn window: the
        # supervisor must see the RAMP's slope, not the base warmup's.
        time.sleep(2.0 * capacity_fast_window_s)

        spawn_seq = itertools.count()

        def spawner():
            mname = f"a{next(spawn_seq)}"
            t0 = time.monotonic()
            url, grpc_port = _boot_member(mname, prewarm=False)
            _start_client(mname, grpc_port)
            # The manifest-driven prewarm block, captured at ready: the
            # spawned member must hold every recorded program with the
            # cache on — nothing left to compile on first dispatch.
            prewarm = None
            try:
                with urllib.request.urlopen(
                        f"{url}/api/v1/stats", timeout=5) as r:
                    prewarm = _json.loads(r.read())["engine"]["prewarm"]
            except Exception:  # noqa: BLE001 — gate reads None
                pass
            spawn_info[mname] = {
                "t_spawn": t0,
                "boot_s": round(time.monotonic() - t0, 3),
                "prewarm": prewarm,
            }
            return mname, url

        def retirer(mname: str) -> None:
            retire_info[mname] = {"t_retire": time.monotonic()}
            _send_exit(mname)

        sup = FleetSupervisor(
            router, spawner=spawner, retirer=retirer,
            min_members=2, max_members=3,
            decision_interval_s=decision_interval_s,
            spawn_horizon_s=spawn_horizon_s,
            surplus_headroom=surplus_headroom,
            surplus_hold_s=surplus_hold_s,
            spawn_cooldown_s=spawn_cooldown_s,
            retire_cooldown_s=retire_cooldown_s)
        sup.start()

        t0 = time.monotonic()
        for ev in [e for e in events if e["t"] > 0.0]:
            wait = t0 + ev["t"] - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            if ev["op"] == "connect":
                _admit(ev["stream"])
            else:
                router.remove_stream(ev["stream"])

        # The retire leg: sustained surplus after the drain.
        deadline = time.monotonic() + surplus_hold_s \
            + retire_cooldown_s + 60.0
        while time.monotonic() < deadline:
            if any(e["action"] == "retire" for e in list(sup.events)):
                break
            time.sleep(0.25)
        # Post-retire observation: long enough for a flap to show.
        time.sleep(max(4.0, 3.0 * decision_interval_s))
        sup.stop()
        sup_snapshot = sup.snapshot()
        router.stop()

        stop.set()
        for t in threads:
            t.join(timeout=10)
        balance = router.ledger.balance()

        spawns = [e for e in sup.events if e["action"] == "spawn"]
        retires = [e for e in sup.events if e["action"] == "retire"]
        spawned = spawns[0]["member"] if spawns else None
        spawn_first_frame_s = None
        if spawned and spawned in spawn_info:
            with rx_lock:
                served = member_first_rx.get(spawned)
            if served is not None:
                spawn_first_frame_s = round(
                    served - spawn_info[spawned]["t_spawn"], 3)
        spawn_prewarm = (spawn_info.get(spawned, {}).get("prewarm")
                        if spawned else None)

        storm_names = [s["stream"] for s in shape.specs()
                       if s["phase"] == "storm"]
        with rx_lock:
            storm_lat = sorted(
                round(first_rx[n] - t_admit[n], 3) for n in storm_names
                if n in first_rx and n in t_admit)
        storm_p99 = (storm_lat[max(0, min(len(storm_lat) - 1,
                     int(round(0.99 * (len(storm_lat) - 1)))))]
                     if storm_lat else None)

        exposition = obs_registry.render()
        lint_errors = lint_exposition(exposition)
        sup_families = sorted({
            line.split()[2] for line in exposition.splitlines()
            if line.startswith("# TYPE vep_supervisor_")})

        gates = {
            "attach_clean": not attach_errors,
            "scale_out_on_forecast": bool(spawns) and
                spawns[0]["reason"] == "saturation_forecast",
            "scale_out_beats_burn": bool(spawns) and
                (spawns[0].get("min_headroom") or 0.0) > 0.0,
            "spawn_prewarm_from_manifest": bool(
                spawn_prewarm and spawn_prewarm.get("aot_cache")
                and spawn_prewarm.get("complete")
                and spawn_prewarm.get("required", 0) >= 1
                + len(tenant_models)),
            "spawn_first_frame_within_scrape": (
                spawn_first_frame_s is not None
                and spawn_first_frame_s <= capacity_scrape_interval_s),
            "storm_admission_bounded": (
                len(storm_lat) == len(storm_names)
                and storm_p99 <= storm_admission_bound_s),
            "retire_on_surplus": bool(retires),
            "no_flap": (len(spawns) == 1 and len(retires) == 1
                        and len(router.clients) == 2),
            "ledger_balanced": balance["balanced"],
            "no_admission_errors": not failures,
            "supervisor_metrics_lint_clean": (
                not lint_errors and len(sup_families) >= 6),
        }
        return {
            "metric": f"autoscale_{shape.base_streams}b{shape.ramp_streams}"
                      f"r{shape.storm_streams}s_{model}",
            "pipeline": (
                "2 cold/warm members + FleetSupervisor (subprocess "
                "spawner, shared AOT prewarm cache) <- LoadShape "
                "ramp/storm/hot-spot/mixed-tenant churn <- per-member "
                "gRPC clients -> conservation ledger"),
            "model": model,
            "shape": {
                "base": shape.base_streams, "ramp": shape.ramp_streams,
                "storm": shape.storm_streams,
                "base_fps": shape.base_fps, "hot_fps": shape.hot_fps,
                "models": list(shape.models),
                "duration_s": shape.duration_s,
            },
            "config": {
                "scrape_interval_s": scrape_interval_s,
                "capacity_scrape_interval_s": capacity_scrape_interval_s,
                "decision_interval_s": decision_interval_s,
                "spawn_horizon_s": spawn_horizon_s,
                "surplus_headroom": surplus_headroom,
                "surplus_hold_s": surplus_hold_s,
                "capacity_fast_window_s": capacity_fast_window_s,
                "storm_admission_bound_s": storm_admission_bound_s,
                "bucket": bucket,
            },
            "gates": gates,
            "boots": boots,
            "spawn": {
                "member": spawned,
                "event": spawns[0] if spawns else None,
                "boot_s": spawn_info.get(spawned, {}).get("boot_s")
                if spawned else None,
                "first_frame_s": spawn_first_frame_s,
                "prewarm": spawn_prewarm,
            },
            "storm": {
                "streams": len(storm_names),
                "admitted_first_frame_s": storm_lat,
                "p99_s": storm_p99,
            },
            "retire": {
                "member": retires[0]["member"] if retires else None,
                "event": retires[0] if retires else None,
            },
            "ledger": {
                "balanced": balance["balanced"],
                "lost": balance["lost"],
                "duplicated": balance["duplicated"],
                "streams": balance["streams"],
            },
            "failures": failures,
            "lint_errors": lint_errors[:10],
            "supervisor_families": sup_families,
            "supervisor_snapshot": sup_snapshot,
        }
    finally:
        stop.set()
        if sup is not None:
            sup.stop()
        if router is not None:
            router.stop()
        for mname in list(procs_by_name):
            _send_exit(mname)
        for proc in procs_by_name.values():
            if proc.poll() is None:
                proc.kill()   # by PID via Popen handle — never pkill
        if workdir is None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    _fleet_member_main()
