"""Deterministic record/replay + chaos-soak subsystem (ISSUE r6 tentpole).

A flight recorder for the whole pipeline: the trace format (`trace.py`)
captures per-camera packet/frame events from the ingest worker and the bus
publish path (`recorder.py`); the player (`player.py` + the ``replay://``
URL scheme in ``ingest/sources.py``) re-delivers them deterministically —
byte-identical frames across runs — so the SAME traffic can drive the full
pipeline ingest→bus→collector→engine→serve. `faults.py`/`harness.py` layer
scripted chaos (camera kill/re-add, frame gaps, bus stall, slow
subscribers) on top for fleet soaks, and `checksum.py` is the shared
content-derived result checksum (quantized winning boxes+classes mod 2^31)
used by the harness, bench.py, tools/bench_levers.py and
tools/bench_configs.py.

The reference repo has no counterpart: its integration story was manual
docker-compose driving (``README.md:109-136``) and every perf/robustness
claim was unreproducible. MOSAIC (arxiv 2305.03222) argues end-to-end
benchmarking of edge video pipelines needs exactly this replay plane.

No jax imports at module scope anywhere in this package: recording runs
inside ingest workers whose control plane must stay importable without
initializing a backend (CLAUDE.md conventions).
"""

from .checksum import (
    CHECKSUM_MASK,
    device_checksum,
    fold_checksum,
    golden_lookup,
    zero_class_prior,
)
from .faults import FaultEvent, FaultPlan
from .player import ReplaySource, TracePlayer
from .recorder import RecordingBus, TraceRecorder
from .trace import TRACE_MAGIC, TRACE_VERSION, TraceError, TraceWriter, read_trace

__all__ = [
    "CHECKSUM_MASK",
    "device_checksum",
    "fold_checksum",
    "golden_lookup",
    "zero_class_prior",
    "FaultEvent",
    "FaultPlan",
    "ReplaySource",
    "TracePlayer",
    "RecordingBus",
    "TraceRecorder",
    "TRACE_MAGIC",
    "TRACE_VERSION",
    "TraceError",
    "TraceWriter",
    "read_trace",
]
