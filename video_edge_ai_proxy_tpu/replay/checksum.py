"""Content-derived result checksum (ISSUE r6 tentpole part 4).

The r4/r5 bench "checksum" was ``valid.sum()`` — a SHAPE constant: with the
class prior zeroed the candidate set saturates and every run of a correct
OR box-broken program returns exactly ``max_det * batch * iters`` (VERDICT
r5 weak #1: "a box-decode bug cannot trip it"). This module replaces it
with an integer hash of the actual numerics, shared by every recorder of
numbers (bench.py, tools/bench_levers.py, tools/bench_configs.py, the
replay harness):

    detect:   sum over valid detections of
                  1*x1 + 3*y1 + 5*x2 + 7*y2          (boxes quantized to px)
                + 11*class_id + 13*round(score*1000)
    embed:    sum of round(embedding * 100)
    classify: sum of top_ids + round(top_probs * 1000)

accumulated in int32 (wraparound is two's-complement, deterministic) and
masked to mod 2^31 — so the value fits every JSON consumer and matches
across hosts. A one-element weight perturbation moves scores -> moves the
hash (tests/test_replay.py proves it); identical traffic + identical
weights reproduce it bit-exactly, which is what the golden table pins.

Goldens live in ``replay/goldens.json`` keyed ``<tool>:<program>:<backend>``.
Missing golden = record-only (the artifact carries the value to commit);
present + mismatch = the caller fails loudly (bench.py integrity gate).

jax imports stay inside functions (CLAUDE.md: control-plane code must
import without initializing a backend).
"""

from __future__ import annotations

import json
import os
from typing import Optional

CHECKSUM_MASK = 0x7FFFFFFF  # mod 2^31
GOLDENS_PATH = os.path.join(os.path.dirname(__file__), "goldens.json")

_BOX_W = (1, 3, 5, 7)
_CLS_W = 11
_SCORE_W = 13


def device_checksum(out: dict):
    """Serving-step output tree -> int32 scalar (jax; scan-foldable).

    Handles all three serving families by their output signature
    (engine/runner.py build_serving_step contract): detect
    {boxes, scores, classes, valid}, embed {embedding}, classify/video
    {top_probs, top_ids}.
    """
    import jax.numpy as jnp

    if "boxes" in out:
        v = out["valid"].astype(jnp.int32)
        q = jnp.round(out["boxes"].astype(jnp.float32)).astype(jnp.int32)
        w = jnp.asarray(_BOX_W, jnp.int32)
        s = jnp.sum(q * w * v[..., None], dtype=jnp.int32)
        s = s + jnp.sum(
            (_CLS_W * out["classes"].astype(jnp.int32)
             + _SCORE_W * jnp.round(
                 out["scores"].astype(jnp.float32) * 1000.0
             ).astype(jnp.int32)) * v,
            dtype=jnp.int32,
        )
        return s
    if "embedding" in out:
        return jnp.sum(
            jnp.round(out["embedding"].astype(jnp.float32) * 100.0
                      ).astype(jnp.int32),
            dtype=jnp.int32,
        )
    return jnp.sum(out["top_ids"].astype(jnp.int32), dtype=jnp.int32) + \
        jnp.sum(jnp.round(out["top_probs"].astype(jnp.float32) * 1000.0
                          ).astype(jnp.int32), dtype=jnp.int32)


def fold_checksum(carry, out: dict):
    """Scan-body accumulator: carry (int32 scalar) -> new masked carry.
    Masking every fold keeps the value in [0, 2^31) at all times."""
    import jax.numpy as jnp

    return (carry + device_checksum(out)) & jnp.int32(CHECKSUM_MASK)


def finalize_checksum(total) -> int:
    """Device/host accumulator -> committed int in [0, 2^31)."""
    return int(total) & CHECKSUM_MASK


def host_slot_checksum(host: dict, i: int) -> int:
    """One batch slot of an already-fetched detect output -> masked int.

    Host-side (numpy) twin of the detect branch of ``device_checksum``,
    used by the canary integrity loop (obs/quality.py CanaryChecker) on
    the engine's drain thread: same quantization (boxes rounded to px,
    scores to 1e-3) and weights, accumulated in Python ints and masked
    to 2^31. The canary golden is DEFINED by this fold (recorded and
    compared through the same code path), so it does not need to match a
    device-folded value bit-for-bit — only to be deterministic for
    identical results, which integer math is.
    """
    import numpy as np

    valid = np.asarray(host["valid"][i]).astype(bool)
    boxes = np.round(
        np.asarray(host["boxes"][i], np.float64)[valid]).astype(np.int64)
    cls = np.asarray(host["classes"][i], np.int64)[valid]
    scores = np.round(
        np.asarray(host["scores"][i], np.float64)[valid] * 1000.0
    ).astype(np.int64)
    s = int((boxes * np.asarray(_BOX_W, np.int64)).sum()
            + (_CLS_W * cls + _SCORE_W * scores).sum())
    return s & CHECKSUM_MASK


def zero_class_prior(variables):
    """Zero the detection head's class-prior biases for BENCH programs.

    The from-scratch-trainability prior (models/yolov8.py: cls{i}_out bias
    = log(5/nc/(640/stride)^2) ~= -11.5) puts every random-init score at
    ~1e-5 — below the NMS score threshold — so a random-init benchmark's
    NMS loop would run over empty candidate sets and its checksum would be
    0 (the r4 failure mode, VERDICT r4 weak #2). Zeroing ONLY these bias
    vectors restores the measured regime: sigmoid(~0) ~= 0.5 > 0.25
    threshold, candidate sets saturate, the suppression loop does real
    work. The compute graph is unchanged (same bias add, different
    constants) — a production engine with an imported checkpoint
    overwrites these values anyway. Lives here (not bench.py) because the
    replay harness needs the identical program transform for its
    deterministic checksums."""
    import jax.numpy as jnp

    def walk(node, in_cls_out=False):
        if isinstance(node, dict):
            return {
                k: walk(
                    v,
                    in_cls_out or (
                        isinstance(k, str)
                        and k.startswith("cls") and k.endswith("_out")
                    ),
                )
                for k, v in node.items()
            }
        if in_cls_out and getattr(node, "ndim", None) == 1:
            return jnp.zeros_like(node)
        return node

    return walk(variables)


def load_goldens(path: Optional[str] = None) -> dict:
    path = path or GOLDENS_PATH
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def golden_lookup(key: str, path: Optional[str] = None) -> Optional[int]:
    """Committed golden for ``key`` (e.g. "bench:yolov8n:cpu:2x2"), or
    None when no golden exists for this program/backend yet — callers
    record the fresh value instead of failing."""
    val = load_goldens(path).get(key)
    return int(val) if isinstance(val, int) else None


def check_golden(
    key: str, value: int, *, tool: str, path: Optional[str] = None,
) -> Optional[int]:
    """Compare ``value`` against the committed golden. Returns the golden
    (None = not committed). Raises SystemExit on drift — numeric drift in
    a program whose inputs and weights are pinned is a correctness bug,
    not noise, and must never be silently committed into an artifact."""
    golden = golden_lookup(key, path)
    if golden is not None and golden != value:
        raise SystemExit(
            f"{tool} checksum drift: {key} produced {value}, golden is "
            f"{golden} — the program's numerics changed "
            f"(replay/goldens.json)")
    return golden
