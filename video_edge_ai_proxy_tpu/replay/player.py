"""Trace replay: deterministic re-delivery of recorded camera traffic.

Two consumers (ISSUE r6 tentpole part 2):

- ``ReplaySource`` — a ``VideoSource`` behind the ``replay://`` URL scheme
  (``ingest/sources.py``), so a stock ingest worker drives the FULL
  pipeline ingest→bus→collector→engine→serve from a trace instead of a
  camera. 1x wall-clock pacing re-creates recorded inter-arrival gaps;
  ``pace=0`` replays as fast as possible. Frames are byte-identical across
  runs (trace.decode_frame): same pattern math for synth events, lossless
  zlib round-trip for payload events.
- ``TracePlayer`` — direct in-process iteration over (device, frame, meta)
  for the lockstep determinism harness (replay/harness.py), which needs
  every frame delivered exactly once with no wall clock in the loop.

URL: ``replay:///abs/path.vtrace?device=cam0&pace=1&loop=0&start=0``
``device`` defaults to the trace's only stream (error if ambiguous);
``loop=1`` restarts at EOF instead of returning None (soaks longer than
the trace); without it EOF falls into the worker's reconnect loop, which
re-opens the source and replays from the start anyway — ``loop=0`` exists
so bounded runs (tests) actually terminate. ``start=N`` (r16) skips the
first N frame events and paces from the (N+1)-th arrival offset — the
fleet router's migration "resume" leg: the destination member re-opens
the stream at the source's handoff cursor, so recorded packet ids (and
therefore the content-derived trace ids) stay disjoint across the
handoff and the conservation ledger can prove exactly-once delivery.
"""

from __future__ import annotations

import time
from typing import Iterator, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..bus.interface import FrameMeta
from ..ingest.sources import PacketInfo, VideoSource
from . import trace as trace_mod


def meta_for(ev: dict, frame: np.ndarray,
             timestamp_ms: Optional[int] = None) -> FrameMeta:
    """Frame event -> the FrameMeta the original publish carried.
    ``timestamp_ms`` None keeps the RECORDED epoch stamp (deterministic
    lockstep replays); pass a fresh stamp for live-pipeline replays where
    latency accounting must use this run's clock."""
    return FrameMeta(
        width=frame.shape[1],
        height=frame.shape[0],
        channels=frame.shape[2] if frame.ndim == 3 else 1,
        timestamp_ms=int(ev["ts_ms"] if timestamp_ms is None
                         else timestamp_ms),
        pts=ev["pts"] if ev["pts"] is not None else 0,
        dts=ev["dts"] if ev["dts"] is not None else 0,
        packet=ev["packet"],
        is_keyframe=ev["key"],
        frame_type="I" if ev["key"] else "P",
        time_base=ev.get("tb", 1.0 / 90000.0),
    )


class TracePlayer:
    """Parsed trace + deterministic frame iteration (no wall clock)."""

    def __init__(self, path: str):
        self.path = path
        self.header, self.events = trace_mod.read_trace(path)
        self.devices = trace_mod.trace_devices(self.events)

    def stream_info(self, device_id: str) -> Optional[dict]:
        for ev in self.events:
            if ev.get("ev") == "stream" and ev.get("device") == device_id:
                return ev
        return None

    def frame_events(self, device_id: Optional[str] = None) -> list[dict]:
        return list(trace_mod.iter_frames(self.events, device_id))

    def iter_frames(
        self, device_id: Optional[str] = None,
    ) -> Iterator[tuple[str, np.ndarray, FrameMeta]]:
        """(device_id, frame, meta) in trace order — every frame exactly
        once, recorded timestamps preserved. The lockstep harness path."""
        for ev in trace_mod.iter_frames(self.events, device_id):
            frame = trace_mod.decode_frame(ev)
            yield ev["device"], frame, meta_for(ev, frame)


class ReplaySource(VideoSource):
    """``replay://`` VideoSource: a recorded stream played back through
    the stock ingest worker. grab() paces on the recorded ``t_ms``
    arrival offsets (1x) or runs flat-out (``pace=0``); retrieve()
    reproduces the recorded bytes exactly."""

    kind = "replay"

    def __init__(self, url: str):
        u = urlparse(url)
        q = {k: v[-1] for k, v in parse_qs(u.query).items()}
        # replay://rel/path and replay:///abs/path both resolve: urlparse
        # puts a relative first segment in netloc.
        self.trace_path = (u.netloc + u.path) if u.netloc else u.path
        self.device = q.get("device", "")
        self.pace = q.get("pace", "1") not in ("0", "false")
        self.loop = q.get("loop", "0") in ("1", "true")
        try:
            self.start = max(0, int(q.get("start", "0")))
        except ValueError:
            raise ValueError(
                f"replay url start={q.get('start')!r} is not an integer")
        self._player: Optional[TracePlayer] = None
        self._events: list[dict] = []
        self._i = -1
        self._t0 = 0.0
        self._base_ms = 0.0
        self._cur: Optional[dict] = None

    def open(self) -> None:
        try:
            self._player = TracePlayer(self.trace_path)
        except (OSError, trace_mod.TraceError) as exc:
            raise ConnectionError(f"cannot open trace: {exc}") from exc
        if not self.device:
            if len(self._player.devices) != 1:
                raise ConnectionError(
                    f"trace {self.trace_path} has streams "
                    f"{self._player.devices}; pass ?device=<id>")
            self.device = self._player.devices[0]
        self._events = self._player.frame_events(self.device)
        if self.start:
            # Resume leg: replay from the handoff cursor. Pacing re-bases
            # on the first REMAINING event below, so inter-arrival gaps
            # after the cutover match the recording from that point.
            self._events = self._events[self.start:]
        if not self._events:
            raise ConnectionError(
                f"trace {self.trace_path} has no frames for "
                f"device {self.device!r}"
                + (f" at start={self.start}" if self.start else ""))
        info = self._player.stream_info(self.device) or {}
        first = self._events[0]
        shape = first.get("shape") or [
            first["synth"]["h"], first["synth"]["w"], 3]
        self.height = int(info.get("h") or shape[0])
        self.width = int(info.get("w") or shape[1])
        self.fps = float(info.get("fps") or 30.0)
        self._i = -1
        self._t0 = time.monotonic()
        self._base_ms = self._events[0]["t_ms"]
        self._cur = None

    def grab(self) -> Optional[PacketInfo]:
        if self._player is None:
            return None
        self._i += 1
        if self._i >= len(self._events):
            if not self.loop:
                return None
            # Loop: re-base the pacing clock so inter-arrival gaps repeat.
            self._i = 0
            self._t0 = time.monotonic()
        ev = self._events[self._i]
        if self.pace:
            due = self._t0 + (ev["t_ms"] - self._base_ms) / 1000.0
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        self._cur = ev
        # Trace events decode standalone (synth math / zlib round-trip),
        # so a start= resume point is a legitimate decode entry even
        # mid-GOP: report it as a keyframe. Without this the worker's
        # lazy-decode valve (_should_decode) skips exactly the cursor
        # packet — no client-activity stamp exists yet on a
        # freshly-booted migration destination — and the conservation
        # ledger reads a one-frame loss per handoff.
        key = bool(ev["key"]) or (self._i == 0 and self.start > 0)
        return PacketInfo(
            packet=ev["packet"],
            is_keyframe=key,
            pts=ev["pts"],
            dts=ev["dts"],
            timestamp_ms=int(time.time() * 1000),
            time_base=ev.get("tb", 1.0 / 90000.0),
        )

    def retrieve(self) -> Optional[np.ndarray]:
        if self._cur is None:
            return None
        return trace_mod.decode_frame(self._cur)

    def close(self) -> None:
        self._player = None
        self._events = []
        self._cur = None
