"""Versioned, append-only trace file format (ISSUE r6 tentpole part 1).

One trace file = a header line + one JSON line per event, newline-framed:

    {"magic": "VEPTRACE", "version": 1, "created_ms": ...}
    {"ev": "stream", "device": "cam0", "w": 1280, "h": 720, "fps": 30, ...}
    {"ev": "frame", "device": "cam0", "t_ms": 33.4, "pts": 3000, ...}
    ...
    {"ev": "end", "frames": 512}

Why JSONL and not a binary container: append-only crash tolerance for free
(a worker killed mid-run leaves a valid prefix — the reader tolerates a
missing ``end`` record), line-level versioned evolution, and greppable
traces. Frame pixels are carried one of two ways:

- ``synth``: ``{"w", "h", "n"}`` — the frame is frame ``n`` of the
  deterministic SyntheticSource pattern and is REGENERATED at replay
  (bytes per event: ~100). This is how fleet-soak traces stay tiny.
- ``data``: base64(zlib(raw BGR24 bytes)) + ``shape`` — lossless payload
  capture for real camera frames (zlib round-trips exactly, so replay is
  byte-identical).

``t_ms`` is the arrival time relative to the trace's first event
(monotonic clock at record time) — the player's 1x wall-clock pacing
re-creates recorded inter-arrival gaps from it. ``ts_ms`` preserves the
original epoch publish timestamp for latency bookkeeping.

The reference repo records nothing (every run is live RTSP); this format
is what makes its behavior claims reproducible here.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import zlib
from typing import Iterator, Optional

import numpy as np

TRACE_MAGIC = "VEPTRACE"
TRACE_VERSION = 1


class TraceError(ValueError):
    """Malformed trace: bad magic, unsupported version, corrupt line."""


class TraceWriter:
    """Append-only writer. Thread-safe (the bus tap records from whatever
    thread publishes); every event is written as one line + flush so a
    crash loses at most the in-flight line, never the framing."""

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path, "w", encoding="utf-8")
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._frames = 0
        self._closed = False
        header = {
            "magic": TRACE_MAGIC,
            "version": TRACE_VERSION,
            "created_ms": int(time.time() * 1000),
        }
        self._fh.write(json.dumps(header, separators=(",", ":")) + "\n")
        self._fh.flush()

    def rel_ms(self) -> float:
        """Milliseconds since the trace opened (the event clock)."""
        return (time.monotonic() - self._t0) * 1000.0

    def append(self, event: dict) -> None:
        with self._lock:
            if self._closed:
                return
            if event.get("ev") == "frame":
                self._frames += 1
            self._fh.write(json.dumps(event, separators=(",", ":")) + "\n")
            self._fh.flush()

    def stream_event(
        self, device_id: str, *, width: int, height: int,
        fps: float = 0.0, gop: int = 0, kind: str = "",
    ) -> None:
        self.append({
            "ev": "stream", "device": device_id, "t_ms": round(self.rel_ms(), 3),
            "w": int(width), "h": int(height), "fps": float(fps),
            "gop": int(gop), "kind": kind,
        })

    def frame_event(
        self, device_id: str, *,
        pts, dts, is_keyframe: bool, packet: int, timestamp_ms: int,
        time_base: float = 1.0 / 90000.0,
        synth: Optional[dict] = None,
        frame: Optional[np.ndarray] = None,
    ) -> None:
        """One published frame. Exactly one of ``synth`` (pattern seed
        ``{"w","h","n"}``) or ``frame`` (raw pixels, zlib+base64) carries
        the pixel content."""
        ev = {
            "ev": "frame", "device": device_id,
            "t_ms": round(self.rel_ms(), 3),
            "pts": pts, "dts": dts, "key": bool(is_keyframe),
            "packet": int(packet), "ts_ms": int(timestamp_ms),
            "tb": time_base,
        }
        if synth is not None:
            ev["synth"] = {"w": int(synth["w"]), "h": int(synth["h"]),
                           "n": int(synth["n"])}
        elif frame is not None:
            arr = np.ascontiguousarray(frame)
            ev["shape"] = list(arr.shape)
            ev["dtype"] = str(arr.dtype)
            ev["data"] = base64.b64encode(
                zlib.compress(arr.tobytes(), 1)).decode("ascii")
        else:
            raise ValueError("frame_event needs synth= or frame=")
        self.append(ev)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._fh.write(json.dumps(
                {"ev": "end", "frames": self._frames},
                separators=(",", ":")) + "\n")
            self._fh.close()


def decode_frame(event: dict) -> np.ndarray:
    """Frame event -> HxWx3 uint8 BGR24 array, byte-identical to what was
    recorded. Synthetic events regenerate through the SAME pattern math
    the live SyntheticSource uses (single source of truth)."""
    synth = event.get("synth")
    if synth is not None:
        from ..ingest.sources import SyntheticSource

        return SyntheticSource.render(synth["h"], synth["w"], synth["n"])
    raw = zlib.decompress(base64.b64decode(event["data"]))
    return np.frombuffer(raw, dtype=event.get("dtype", "uint8")).reshape(
        event["shape"]).copy()


def read_trace(path: str) -> tuple[dict, list[dict]]:
    """Parse a trace -> (header, events). Raises TraceError on bad magic /
    unsupported version; tolerates a missing ``end`` record and one torn
    final line (crash mid-append leaves a valid prefix by design)."""
    with open(path, "r", encoding="utf-8") as fh:
        first = fh.readline()
        try:
            header = json.loads(first)
        except ValueError as exc:
            raise TraceError(f"unreadable trace header in {path}") from exc
        if not isinstance(header, dict) or header.get("magic") != TRACE_MAGIC:
            raise TraceError(f"{path} is not a {TRACE_MAGIC} trace")
        if header.get("version") != TRACE_VERSION:
            raise TraceError(
                f"trace version {header.get('version')} unsupported "
                f"(reader speaks {TRACE_VERSION})")
        events: list[dict] = []
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                break  # torn final line: keep the valid prefix
            if isinstance(ev, dict):
                events.append(ev)
    return header, events


def iter_frames(
    events: list[dict], device_id: Optional[str] = None,
) -> Iterator[dict]:
    """Frame events, optionally restricted to one device, in trace order."""
    for ev in events:
        if ev.get("ev") != "frame":
            continue
        if device_id is not None and ev.get("device") != device_id:
            continue
        yield ev


def trace_devices(events: list[dict]) -> list[str]:
    """Device ids appearing in the trace, first-seen order."""
    seen: list[str] = []
    for ev in events:
        d = ev.get("device")
        if d and d not in seen:
            seen.append(d)
    return seen
