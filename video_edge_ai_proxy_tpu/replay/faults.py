"""Scripted fault plans for chaos soaks (ISSUE r6 tentpole part 3).

A FaultPlan is a deterministic, time-ordered script of pipeline faults —
the chaos in a soak run is part of the experiment's inputs, not a random
draw, so a failing soak replays exactly. Fault kinds and what injects
them (replay/harness.py):

- ``camera_kill`` / ``camera_restore`` — stop a camera's publisher and
  drop its bus stream mid-run / re-add it (collector churn: cursors,
  geometry cache, tracker + _ann_state GC must all survive).
- ``frame_gap`` — suppress one camera's publishes for ``duration_s``
  (burst loss: the latest-wins collector must idle the stream, not stall
  the batch).
- ``bus_stall`` — delay EVERY publish for ``duration_s`` (a wedged shm
  writer / slow Redis: the engine tick must degrade, not deadlock).
- ``slow_subscriber`` — stop draining the result subscription for
  ``duration_s`` (backpressure: the engine must drop-and-count via
  subscriber_drops, never block the drain thread).
- ``uplink_down`` — the annotation cloud endpoint fails every POST for
  ``duration_s`` (resilience wiring: retries back off, the breaker
  opens, batches land in the dead-letter spool and re-drain on
  recovery — zero annotations lost).
- ``bus_flap`` — publishes raise ``ConnectionError`` for ``duration_s``
  (a flapping link: cameras tolerate it, the bus breaker and resp
  idempotency-aware resync keep readers degraded, not wedged).
- ``device_stall`` — every device step call slows for ``duration_s``
  (a contended/thermal-throttled chip: sustained tick-budget overrun
  must walk the engine's degradation ladder, then recover).
- ``black_frame`` — one camera publishes all-zero frames for
  ``duration_s`` (lens cap / dead sensor: obs/quality.py must verdict
  the stream "black" within the hysteresis bound, then recover it).
- ``frozen_frame`` — one camera republishes the same frame for
  ``duration_s`` (a wedged decoder/DVR loop: the device diff-energy
  signal must drive a "frozen" verdict, then recover).
- ``score_drift`` — every detect step's scores are scaled down for
  ``duration_s`` (silent model/numerics regression: the drift scorer
  must move and the canary checksum must mismatch while it lasts).
- ``shard_fault`` — ONE mesh shard's step execution fails hard (or, with
  ``duration_s`` > 0, stalls its drain fetch for that long) from ``at_s``
  on (``device_id`` carries the shard index as a string — the device-
  fault domain's chaos kind, injected by tools/fault_smoke.py as a
  per-shard failing/stalling step wrapper; the engine must detect,
  fail over to the survivor mesh, and prove frame conservation).

JSON round-trip so plans can be committed next to artifacts.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

KINDS = (
    "camera_kill", "camera_restore", "frame_gap", "bus_stall",
    "slow_subscriber", "uplink_down", "bus_flap", "device_stall",
    "black_frame", "frozen_frame", "score_drift", "shard_fault",
)

#: Schedule template for the resilience kinds (fraction of the soak
#: window: start, duration) — disjoint windows, each with recovery slack
#: before the next, so the artifact attributes effects to causes.
_RESILIENCE_WINDOWS = {
    "uplink_down": (0.15, 0.20),
    "bus_flap": (0.50, 0.06),
    "device_stall": (0.62, 0.15),
}

#: The kinds `tools/soak_replay.py --faults` may select (the churn kinds
#: need per-device scheduling and run via default_churn instead).
RESILIENCE_KINDS = tuple(_RESILIENCE_WINDOWS)

#: Schedule template for the output-quality kinds (ISSUE r10): black and
#: frozen run on DISTINCT cameras (per-device targeting), drift is
#: global (a step-wrapper perturbation), so their windows may overlap —
#: but they stay disjoint anyway so the detection-latency gate in
#: tools/soak_replay.py attributes each verdict to one cause, and each
#: window leaves recovery slack for the exit-hysteresis to clear.
#: score_drift gets the widest slot: the canary judges integrity one
#: full checksum cycle at a time (loop_len / canary fps ≈ 3 s in the
#: soak harness), so the drift must stay up long enough for at least
#: one complete cycle — ideally two — to close inside it.
_QUALITY_WINDOWS = {
    "black_frame": (0.10, 0.20),
    "frozen_frame": (0.35, 0.20),
    "score_drift": (0.58, 0.35),
}

QUALITY_KINDS = tuple(_QUALITY_WINDOWS)


@dataclass(order=True)
class FaultEvent:
    at_s: float                 # seconds from soak start
    kind: str = field(compare=False)
    device_id: str = field(default="", compare=False)
    duration_s: float = field(default=0.0, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")


class FaultPlan:
    """Time-ordered fault script with a cursor (pop_due)."""

    def __init__(self, events=()):
        self.events = sorted(events)
        self._i = 0

    def reset(self) -> None:
        self._i = 0

    def pop_due(self, now_s: float) -> list[FaultEvent]:
        """Events whose time has come since the last call (monotone)."""
        due = []
        while self._i < len(self.events) and \
                self.events[self._i].at_s <= now_s:
            due.append(self.events[self._i])
            self._i += 1
        return due

    def to_json(self) -> str:
        return json.dumps([asdict(e) for e in self.events], indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls([FaultEvent(**e) for e in json.loads(text)])

    @classmethod
    def default_churn(
        cls, device_ids, duration_s: float,
    ) -> "FaultPlan":
        """The acceptance-run script, scaled to the soak window: one
        camera killed at 25% and re-added at 55% (churn across a long gap
        — its collector/tracker state must GC and rebuild), a frame-gap
        burst on a second camera, one global bus stall, and one
        slow-subscriber window — each in its own quiet period so the
        artifact attributes effects to causes."""
        devs = sorted(device_ids)
        ev = []
        if devs:
            ev += [
                FaultEvent(at_s=duration_s * 0.25, kind="camera_kill",
                           device_id=devs[0]),
                FaultEvent(at_s=duration_s * 0.55, kind="camera_restore",
                           device_id=devs[0]),
            ]
        if len(devs) > 1:
            ev.append(FaultEvent(
                at_s=duration_s * 0.35, kind="frame_gap",
                device_id=devs[-1],
                duration_s=max(2.0, duration_s * 0.05)))
        ev.append(FaultEvent(
            at_s=duration_s * 0.70, kind="bus_stall",
            duration_s=max(1.0, duration_s * 0.02)))
        ev.append(FaultEvent(
            at_s=duration_s * 0.85, kind="slow_subscriber",
            duration_s=max(2.0, duration_s * 0.05)))
        return cls(ev)

    @classmethod
    def resilience(
        cls, duration_s: float, kinds=("uplink_down", "bus_flap",
                                       "device_stall"),
    ) -> "FaultPlan":
        """The chaos-smoke script: the three resilience fault kinds in
        disjoint windows scaled to the soak length (``make chaos-smoke``
        runs all three; ``tools/soak_replay.py --faults`` selects)."""
        ev = []
        for kind in kinds:
            if kind not in _RESILIENCE_WINDOWS:
                raise ValueError(
                    f"not a resilience fault kind: {kind!r} "
                    f"(choose from {sorted(_RESILIENCE_WINDOWS)})"
                )
            frac, dur = _RESILIENCE_WINDOWS[kind]
            ev.append(FaultEvent(
                at_s=duration_s * frac, kind=kind,
                duration_s=max(1.0, duration_s * dur),
            ))
        return cls(ev)

    @classmethod
    def quality(
        cls, duration_s: float, device_ids,
        kinds=QUALITY_KINDS,
    ) -> "FaultPlan":
        """The quality-smoke script: black on the first camera, frozen
        on the second (distinct targets — both verdicts must fire
        independently), score_drift global, each in its _QUALITY_WINDOWS
        slot scaled to the soak length."""
        devs = sorted(device_ids)
        if not devs:
            raise ValueError("quality fault plan needs at least one camera")
        target = {
            "black_frame": devs[0],
            "frozen_frame": devs[1 % len(devs)],
            "score_drift": "",
        }
        ev = []
        for kind in kinds:
            if kind not in _QUALITY_WINDOWS:
                raise ValueError(
                    f"not a quality fault kind: {kind!r} "
                    f"(choose from {sorted(_QUALITY_WINDOWS)})"
                )
            frac, dur = _QUALITY_WINDOWS[kind]
            ev.append(FaultEvent(
                at_s=duration_s * frac, kind=kind,
                device_id=target[kind],
                duration_s=max(1.0, duration_s * dur),
            ))
        return cls(ev)
