"""Redis-wire-compatible frame bus.

The deployment bridge VERDICT round 1 called out: a site with reference
workers or Redis-reading clients can point this framework at the SAME Redis
and everything interoperates, because this backend speaks the reference's
exact wire contract:

- frame plane: ``XADD <device_id> MAXLEN ~ <n> * data <VideoFrame proto>``
  (producer, ``python/read_image.py:121``); consumers read the newest entry
  and unmarshal field ``data`` as a VideoFrame
  (``server/grpcapi/grpc_api.go:191-229``).
- control plane: hash ``last_access_time_<id>`` with fields
  ``last_query`` (epoch ms) / ``proxy_rtmp`` / ``store`` ("true"/"false"),
  and string key ``is_key_frame_only_<id>`` = "true"/"false"
  (``server/models/RedisConstants.go:18-27``, ``grpc_api.go:159-175``,
  ``python/read_image.py:36-45``).

Selected by ``bus.backend: redis`` + ``bus.redis_addr`` in conf.yaml. The
shm bus remains the same-host fast path; this is the interop/scale-out
path (SURVEY.md §7.2: "Redis-streams implementation (wire-compatible keys)
behind an interface, plus a shared-memory ring").
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..resilience.breaker import CircuitBreaker
from ..utils.logging import get_logger
from .interface import (
    FIELD_LAST_QUERY,
    KEY_KEYFRAME_ONLY_PREFIX,
    KEY_LAST_ACCESS_PREFIX,
    Frame,
    FrameBus,
    FrameMeta,
    note_publish,
)
from .resp import RespClient, RespError

log = get_logger("bus.redis")

# Stream IDs are "<ms>-<n>"; packed into one int so FrameBus cursors stay
# plain integers. 2^20 sub-ms entries per stream per millisecond is far
# beyond any camera's rate.
_SEQ_SHIFT = 20


def _id_to_seq(entry_id: bytes) -> int:
    ms, _, n = entry_id.decode().partition("-")
    return (int(ms) << _SEQ_SHIFT) | min(int(n or 0), (1 << _SEQ_SHIFT) - 1)


class RedisFrameBus(FrameBus):
    def __init__(self, addr: str = "127.0.0.1:6379", timeout_s: float = 5.0,
                 password: str = "", db: int = 0):
        """``password``/``db`` mirror the reference's RedisSubconfig
        (``config.go:28-35``: connection/database/password) — AUTH and
        SELECT run on every (re)connect so resyncs keep credentials."""
        handshake = []
        if password:
            handshake.append(("AUTH", password))
        if db:
            handshake.append(("SELECT", str(db)))
        self._addr, self._conn_timeout = addr, timeout_s
        self._handshake = tuple(handshake)
        self._client = RespClient.from_addr(addr, timeout_s,
                                            handshake=self._handshake)
        # Blocking XREADs park a socket for up to ~1 s; running them on
        # the SHARED client would head-of-line block every other Redis
        # operation in the process (engine tick, heartbeats, other gRPC
        # handlers) behind its lock. Each waiting thread gets its own
        # lazily-created connection instead — bounded by the gRPC thread
        # pool size, closed with the bus.
        self._block_local = threading.local()
        self._block_clients: list = []
        self._block_clients_lock = threading.Lock()
        self._maxlen: dict[str, int] = {}  # producer-side ring depth
        # streams() verdict cache: key -> (is_frame_stream, probed_at).
        # Accepts are permanent (drop_stream evicts); rejects re-probe
        # after _REPROBE_S so a foreign-looking key that later becomes a
        # real camera is picked up without per-poll payload fetches.
        self._stream_verdict: dict[str, tuple[bool, float]] = {}
        # Read-path circuit breaker: when Redis dies, the engine tick polls
        # every stream every ~10 ms — without a breaker that is hundreds of
        # reconnect storms per second and a raised exception per tick.
        # Open breaker => reads degrade (no frame / no streams) at memory
        # speed; one probe per recovery window re-closes it when the
        # server returns. Writes still raise so producers see the outage.
        self._breaker = CircuitBreaker(
            "redis_bus_read", failure_threshold=3, recovery_timeout_s=1.0
        )

    # -- frame plane --

    def create_stream(self, device_id: str, frame_bytes: int, slots: int = 4) -> None:
        # Ring depth == XADD MAXLEN; frame_bytes is a shm-ring concept with
        # no Redis equivalent (streams size dynamically).
        self._maxlen[device_id] = max(1, slots)
        self._client.command("DEL", device_id)
        # Seed the reference-shaped control hash (grpc_api.go:159-175
        # writes the same key on Query) so streams() can tell OUR empty
        # stream apart from a co-tenant app's stream key without probing
        # payloads. HSETNX: never clobber a live last_query.
        self._client.command(
            "HSETNX", KEY_LAST_ACCESS_PREFIX + device_id, FIELD_LAST_QUERY,
            "0",
        )
        # The FrameBus contract lists a created stream before its first
        # frame (streams()). XGROUP CREATE MKSTREAM materializes an EMPTY
        # stream key atomically — unlike an XADD+XDEL placeholder, no
        # co-reading reference consumer can ever observe a phantom entry
        # (the mixed-fleet case this backend exists for).
        self._client.command(
            "XGROUP", "CREATE", device_id, "_init", "$", "MKSTREAM"
        )
        self._client.command("XGROUP", "DESTROY", device_id, "_init")

    def publish(self, device_id: str, data: np.ndarray, meta: FrameMeta) -> int:
        from ..proto import pb

        arr = np.ascontiguousarray(data)
        vf = pb.VideoFrame(
            data=arr.tobytes(),
            width=meta.width or (arr.shape[1] if arr.ndim >= 2 else 0),
            height=meta.height or (arr.shape[0] if arr.ndim >= 2 else 0),
            timestamp=meta.timestamp_ms,
            frame_type=meta.frame_type,
            pts=meta.pts,
            dts=meta.dts,
            packet=meta.packet,
            keyframe=meta.keyframe_cnt,
            time_base=meta.time_base,
            is_keyframe=meta.is_keyframe,
            is_corrupt=meta.is_corrupt,
            trace_id=meta.trace_id,
            parent_span=meta.parent_span,
        )
        for i, dim in enumerate(arr.shape):
            vf.shape.dim.append(pb.ShapeProto.Dim(size=dim, name=str(i)))
        # unsafe_ok: XADD is non-idempotent (a resync retry can append the
        # frame twice), but the frame plane is latest-wins with MAXLEN ~
        # trimming — a duplicate newest entry is benign, losing the frame
        # to a transient flap is worse.
        entry_id = self._client.command(
            "XADD", device_id, "MAXLEN", "~",
            str(self._maxlen.get(device_id, 1)), "*",
            "data", vf.SerializeToString(),
            unsafe_ok=True,
        )
        note_publish("redis", device_id, arr.nbytes)
        return _id_to_seq(entry_id)

    def _guard_read(self, fn, fallback):
        """Run one read under the breaker; degrade to ``fallback`` on a
        dead link (and while the breaker is open) instead of raising."""
        if not self._breaker.allow():
            return fallback
        try:
            out = fn()
        except (OSError, ConnectionError) as exc:
            self._breaker.record_failure()
            log.warning("redis read failed (%s); breaker %s",
                        exc, self._breaker.state)
            return fallback
        self._breaker.record_success()
        return out

    def read_latest(self, device_id: str, min_seq: int = 0) -> Optional[Frame]:
        return self._guard_read(
            lambda: self._read_latest_unguarded(device_id, min_seq), None
        )

    def _read_latest_unguarded(
        self, device_id: str, min_seq: int = 0
    ) -> Optional[Frame]:
        if min_seq:
            # Cheap tip probe before shipping a multi-MB frame body: the
            # collector polls faster than cameras produce, so most reads
            # would fetch a frame only to drop it at the cursor check.
            try:
                info = self._client.command("XINFO", "STREAM", device_id)
            except RespError:
                return None  # no such key
            tip = dict(zip(info[::2], info[1::2])).get(b"last-generated-id")
            if tip is None or _id_to_seq(tip) <= min_seq:
                return None
        reply = self._client.command(
            "XREVRANGE", device_id, "+", "-", "COUNT", "1"
        )
        if not reply:
            return None
        entry_id, fields = reply[0]
        seq = _id_to_seq(entry_id)
        if seq <= min_seq:
            return None
        payload = None
        for k, v in zip(fields[::2], fields[1::2]):
            if k == b"data":
                payload = v
        if payload is None:
            return None
        return Frame(seq=seq, **_unmarshal(payload))

    def read_latest_blocking(
        self, device_id: str, min_seq: int = 0, timeout_s: float = 1.0
    ) -> Optional[Frame]:
        return self._guard_read(
            lambda: self._read_latest_blocking_unguarded(
                device_id, min_seq, timeout_s
            ),
            None,
        )

    def _read_latest_blocking_unguarded(
        self, device_id: str, min_seq: int = 0, timeout_s: float = 1.0
    ) -> Optional[Frame]:
        """Server-side wait via ``XREAD BLOCK`` — ONE round trip per miss
        window where the default poll costs hundreds (reference
        grpc_api.go:191-197 waits the same way, Block=1s).

        XREAD is used purely as a *wake-up*: it returns entries OLDEST-
        first after the cursor, and real Redis's lazy ``MAXLEN ~`` trim
        can leave a deep backlog — serving its reply would hand a
        GetFrame client a seconds-old frame. COUNT 1 bounds the wake-up
        to one body; the actual fetch is ``read_latest``'s newest-wins
        tip read. Each block is
        clamped under the socket timeout (a quiet stream must return a
        clean nil, not a socket error) and re-issued until ``timeout_s``
        is consumed."""
        import time

        last_id = "%d-%d" % (
            min_seq >> _SEQ_SHIFT, min_seq & ((1 << _SEQ_SHIFT) - 1),
        )
        client = self._blocking_client()
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining < 0.002:
                return None
            block_s = min(remaining, max(0.1, client.timeout_s - 1.0))
            # NEVER let the ms value floor to 0: BLOCK 0 means "block
            # forever" in Redis, turning a drained timeout budget into an
            # indefinite server-side hang.
            block_ms = max(1, int(block_s * 1000))
            reply = client.command(
                "XREAD", "COUNT", "1", "BLOCK", str(block_ms),
                "STREAMS", device_id, last_id,
            )
            if reply:
                # Something newer than min_seq exists; serve the tip.
                # Unguarded: this whole loop already runs under ONE
                # breaker admission (a nested allow() would reject the
                # half-open probe's own inner read).
                frame = self._read_latest_unguarded(device_id, min_seq=min_seq)
                if frame is not None:
                    return frame

    def _blocking_client(self) -> RespClient:
        """This thread's dedicated connection for blocking XREADs (see
        __init__ — parking the shared client would head-of-line block
        the whole process)."""
        client = getattr(self._block_local, "client", None)
        if client is None:
            client = RespClient.from_addr(
                self._addr, self._conn_timeout, handshake=self._handshake
            )
            self._block_local.client = client
            with self._block_clients_lock:
                self._block_clients.append(client)
        return client

    _REPROBE_S = 10.0  # rejected-key re-probe interval

    def streams(self) -> list[str]:
        return self._guard_read(self._streams_unguarded, [])

    def _streams_unguarded(self) -> list[str]:
        """Stream-typed keys that are actually camera frame streams.

        The db is shared in the mixed-fleet deployment this backend exists
        for, so a bare ``SCAN TYPE stream`` would report co-tenant apps'
        stream keys as cameras and the engine would unmarshal their
        entries as VideoFrame protos (round-2 advisor). A key qualifies
        when
        - reference-shaped control keys exist for it
          (``last_access_time_<id>`` / ``is_key_frame_only_<id>`` —
          ``create_stream`` seeds the former, the reference server writes
          it on Query, grpc_api.go:159-175), or
        - its newest entry carries the reference frame contract: a
          ``data`` field parsing as a VideoFrame with pixel payload
          (covers a reference worker XADD-ing before any query).
        Accepts are cached (evicted by drop_stream); rejects re-probe
        every ``_REPROBE_S`` so no per-poll payload traffic goes to
        foreign keys."""
        import time

        now = time.monotonic()
        out = []
        scanned = self._scan_keys("stream")
        for key in scanned:
            verdict = self._stream_verdict.get(key)
            if verdict is None or (
                not verdict[0] and now - verdict[1] > self._REPROBE_S
            ):
                verdict = (self._is_frame_stream(key), now)
                self._stream_verdict[key] = verdict
            if verdict[0]:
                out.append(key)
        # Prune verdicts for keys gone from the db (co-tenant apps churn
        # ephemeral stream names; without this the cache grows for the
        # life of the process).
        if len(self._stream_verdict) > len(scanned):
            keep = set(scanned)
            self._stream_verdict = {
                k: v for k, v in self._stream_verdict.items() if k in keep
            }
        return out

    def _is_frame_stream(self, key: str) -> bool:
        if self._client.command(
            "EXISTS", KEY_LAST_ACCESS_PREFIX + key,
            KEY_KEYFRAME_ONLY_PREFIX + key,
        ):
            return True
        reply = self._client.command("XREVRANGE", key, "+", "-", "COUNT", "1")
        if not reply:
            return False  # empty + no control keys: not one of ours
        _, fields = reply[0]
        payload = dict(zip(fields[::2], fields[1::2])).get(b"data")
        if payload is None:
            return False
        from ..proto import pb

        try:
            vf = pb.VideoFrame()
            vf.ParseFromString(payload)
        except Exception:
            return False
        return bool(vf.data) and bool(vf.shape.dim)

    def drop_stream(self, device_id: str) -> None:
        # Also remove the control keys create_stream seeded: an orphaned
        # last_access_time_<id> hash in the shared db would make a future
        # same-named FOREIGN stream key pass _is_frame_stream. The process
        # manager deletes the same keys on its own stop path — this keeps
        # bus-level users (engine-only deployments, tests) equally clean.
        self._client.command(
            "DEL", device_id,
            KEY_LAST_ACCESS_PREFIX + device_id,
            KEY_KEYFRAME_ONLY_PREFIX + device_id,
        )
        self._stream_verdict.pop(device_id, None)

    # -- control plane: plain KV --
    #
    # The cross-backend contract speaks flattened hash fields as
    # "<key>::<field>" (bus/interface.py's helpers); on Redis those live in
    # REAL hashes for reference interop, so the kv_* surface translates:
    # "::"-shaped names route to HGET/HSET/HDEL and kv_keys lists hash
    # fields in flattened form. list-then-get therefore works identically
    # on every backend.

    def kv_set(self, key: str, value: str) -> None:
        if "::" in key:
            base, _, field = key.partition("::")
            self._client.command("HSET", base, field, value)
            return
        self._client.command("SET", key, value)

    def kv_get(self, key: str) -> Optional[str]:
        if "::" in key:
            base, _, field = key.partition("::")
            out = self._client.command("HGET", base, field)
        else:
            out = self._client.command("GET", key)
        return out.decode() if isinstance(out, bytes) else out

    def kv_del(self, key: str) -> None:
        if "::" in key:
            base, _, field = key.partition("::")
            self._client.command("HDEL", base, field)
            return
        self._client.command("DEL", key)

    def kv_keys(self) -> list[str]:
        out = set(self._scan_keys("string"))
        for h in self._scan_keys("hash"):
            fields = self._client.command("HKEYS", h) or []
            out.update(f"{h}::{f.decode()}" for f in fields)
        return sorted(out)

    def _scan_keys(self, want_type: str) -> list[str]:
        # SCAN, never KEYS: this backend shares a production Redis with
        # reference components, and KEYS blocks the whole server. SCAN may
        # return a key on more than one page while the table rehashes, so
        # results dedup through a set.
        out: set[str] = set()
        cursor = b"0"
        while True:
            reply = self._client.command(
                "SCAN", cursor, "COUNT", "1000", "TYPE", want_type
            )
            cursor, keys = reply
            out.update(k.decode() for k in keys)
            if cursor in (b"0", 0, "0"):
                return sorted(out)

    # -- hash helpers: REAL Redis hashes (the shm bus flattens to
    # "<key>::<field>" KV pairs; here wire compatibility requires HSET so
    # reference readers' HGETALL sees the fields, grpc_api.go:166-175 /
    # rtsp_to_rtmp.py:117) --

    def hset(self, key: str, field_name: str, value: str) -> None:
        self._client.command("HSET", key, field_name, value)

    def hget(self, key: str, field_name: str) -> Optional[str]:
        out = self._client.command("HGET", key, field_name)
        return out.decode() if isinstance(out, bytes) else out

    def hgetall(self, key: str) -> dict[str, str]:
        out = self._client.command("HGETALL", key) or []
        return {
            k.decode(): v.decode() for k, v in zip(out[::2], out[1::2])
        }

    def hdel_all(self, key: str) -> None:
        self._client.command("DEL", key)

    # -- keyframe-only flag: reference stores Go strconv.FormatBool text
    # ("true"/"false", grpc_api.go:159-163), and the reference worker
    # compares against "true" (read_image.py:36-45) --

    def set_keyframe_only(self, device_id: str, enabled: bool) -> None:
        self.kv_set(
            KEY_KEYFRAME_ONLY_PREFIX + device_id,
            "true" if enabled else "false",
        )

    def keyframe_only(self, device_id: str) -> bool:
        return self.kv_get(KEY_KEYFRAME_ONLY_PREFIX + device_id) == "true"

    def close(self) -> None:
        self._client.close()
        with self._block_clients_lock:
            for c in self._block_clients:
                try:
                    c.close()
                except Exception:
                    pass
            self._block_clients.clear()


def _unmarshal(payload: bytes) -> dict:
    """VideoFrame proto -> Frame fields (the inverse of publish; same
    reshape the reference's examples do, ``examples/opencv_display.py``)."""
    from ..proto import pb

    vf = pb.VideoFrame()
    vf.ParseFromString(payload)
    dims = [d.size for d in vf.shape.dim]
    raw = np.frombuffer(vf.data, dtype=np.uint8)
    if dims and int(np.prod(dims)) == raw.size:
        data = raw.reshape(dims)
    elif vf.height and vf.width and raw.size == vf.height * vf.width * 3:
        data = raw.reshape(vf.height, vf.width, 3)
    else:
        data = raw
    meta = FrameMeta(
        width=vf.width, height=vf.height,
        channels=data.shape[2] if data.ndim == 3 else 1,
        timestamp_ms=vf.timestamp, pts=vf.pts, dts=vf.dts,
        packet=vf.packet, keyframe_cnt=vf.keyframe,
        is_keyframe=vf.is_keyframe, is_corrupt=vf.is_corrupt,
        frame_type=vf.frame_type, time_base=vf.time_base,
        trace_id=vf.trace_id, parent_span=vf.parent_span,
    )
    return {"data": data, "meta": meta}
