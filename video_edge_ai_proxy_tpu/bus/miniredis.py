"""In-process mini Redis server (RESP2) for tests.

fakeredis is not in this image, so the subset of Redis the bus backend and
the reference contract use is implemented directly: strings, hashes,
lists and streams with MAXLEN trimming, served over real sockets so the
RESP client and any reference tooling exercise the actual wire format.

This is test infrastructure: production deployments point
``bus.backend: redis`` at a real Redis (the point of wire compatibility).
``tests/test_redis_bus.py`` re-runs its whole suite against a real
``redis-server`` when one is on PATH (skip-gated conformance), so the
approximations below are bounded by that run, not by trust:

Known approximations vs real Redis (VERDICT r2 weak #2):
- ``XADD MAXLEN ~`` trims EXACTLY to the bound; real Redis trims lazily
  at node granularity (keeps >= bound entries). Consumers must not rely
  on "exactly maxlen survive" — the bus reads newest-first only.
- ``XINFO STREAM`` returns only ``length`` + ``last-generated-id``; the
  real reply has many more fields. The client reads it as a field map,
  so extras are ignored — asserting on the exact field SET would pass
  here and fail on Redis 6 vs 7 (both add fields over versions).
- ``SCAN`` paginates with keyset cursors over stable per-key ids (COUNT
  per page, default 10, MATCH/TYPE filtered after paging like real Redis
  — pages may be empty with a non-zero cursor). Because ids never shift,
  the real server's core guarantee holds: a key present for the whole
  scan is returned exactly once; keys created or deleted mid-scan may be
  missed, which the contract allows. Cursor VALUES differ from Redis's
  reverse-binary iteration (they are opaque in both).
- RESP2 only: no HELLO/RESP3 push protocol; AUTH is the single-password
  form (no ACL users).
- No expiry (TTL/EXPIRE), no transactions/pipelining guarantees beyond
  per-command atomicity under one dispatch lock.
"""

from __future__ import annotations

import socket
import threading
import time
from fnmatch import fnmatchcase
from typing import Dict, List, Optional, Tuple

StreamEntry = Tuple[Tuple[int, int], List[bytes]]  # ((ms, n), flat fields)


class MiniRedis:
    """``with MiniRedis() as addr: RespClient.from_addr(addr)``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 password: str = ""):
        self._password = password.encode() if password else b""
        self._strings: Dict[bytes, bytes] = {}
        self._hashes: Dict[bytes, Dict[bytes, bytes]] = {}
        self._streams: Dict[bytes, List[StreamEntry]] = {}
        self._last_stream_id: Dict[bytes, Tuple[int, int]] = {}
        self._lists: Dict[bytes, List[bytes]] = {}  # head = index 0
        # SCAN keyset cursors: key -> stable id (see _cmd_scan)
        self._scan_ids: Dict[bytes, int] = {}
        self._next_scan_id = 1
        self._lock = threading.Lock()
        # XADD signals blocked XREADs (Condition over the dispatch lock:
        # cond.wait releases it, so other connections keep serving).
        self._data_arrived = threading.Condition(self._lock)
        self.commands_served = 0   # per-command counter (RTT assertions)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.addr = "%s:%d" % self._srv.getsockname()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="miniredis", daemon=True
        )
        self._accept_thread.start()

    # -- lifecycle --

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def __enter__(self) -> str:
        return self.addr

    def __exit__(self, *_exc) -> None:
        self.close()

    # -- socket plumbing --

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        buf = b""

        def read_line() -> Optional[bytes]:
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf += chunk
            line, buf = buf.split(b"\r\n", 1)
            return line

        def read_exact(n: int) -> Optional[bytes]:
            nonlocal buf
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf += chunk
            out, buf = buf[:n], buf[n:]
            return out

        authed = not self._password

        def bad_frame() -> None:
            # Real Redis replies with a protocol error, then closes the
            # connection; it never crashes the serving thread or corrupts
            # other connections (the RESP framing fuzz test drives this).
            try:
                conn.sendall(b"-ERR Protocol error\r\n")
            except OSError:
                pass

        try:
            while not self._stop.is_set():
                line = read_line()
                if line is None:
                    return
                if not line.startswith(b"*") or not line[1:].isdigit():
                    return bad_frame()
                nargs = int(line[1:])
                if nargs > 1_000_000:     # inline bomb: refuse, don't loop
                    return bad_frame()
                parts: List[bytes] = []
                for _ in range(nargs):
                    hdr = read_line()
                    if hdr is None:
                        return
                    if not hdr.startswith(b"$") or not hdr[1:].isdigit():
                        return bad_frame()
                    data = read_exact(int(hdr[1:]))
                    if data is None or read_exact(2) is None:
                        return
                    parts.append(data)
                if not parts:
                    continue      # empty multibulk: ignored, like Redis
                cmd = parts[0].upper()
                # Connection-scoped auth, like Redis requirepass.
                if cmd == b"AUTH":
                    if not self._password:
                        conn.sendall(
                            b"-ERR Client sent AUTH, but no password is set\r\n")
                    elif parts[-1] == self._password:
                        authed = True
                        conn.sendall(b"+OK\r\n")
                    else:
                        conn.sendall(b"-WRONGPASS invalid password\r\n")
                    continue
                if not authed:
                    conn.sendall(b"-NOAUTH Authentication required.\r\n")
                    continue
                conn.sendall(self._dispatch(parts))
        except OSError:
            pass
        finally:
            conn.close()

    # -- RESP encoding --

    @staticmethod
    def _bulk(v: Optional[bytes]) -> bytes:
        if v is None:
            return b"$-1\r\n"
        return b"$%d\r\n%s\r\n" % (len(v), v)

    @classmethod
    def _arr(cls, items: list) -> bytes:
        out = b"*%d\r\n" % len(items)
        for it in items:
            if isinstance(it, list):
                out += cls._arr(it)
            elif isinstance(it, int):
                out += b":%d\r\n" % it
            else:
                out += cls._bulk(it)
        return out

    # -- command dispatch --

    def _dispatch(self, parts: List[bytes]) -> bytes:
        cmd = parts[0].upper().decode()
        fn = getattr(self, f"_cmd_{cmd.lower()}", None)
        if fn is None:
            return f"-ERR unknown command '{cmd}'\r\n".encode()
        with self._lock:
            self.commands_served += 1
            try:
                return fn(parts[1:])
            except Exception as exc:  # malformed args -> RESP error
                return f"-ERR {type(exc).__name__}: {exc}\r\n".encode()

    def _type_of(self, key: bytes) -> str:
        if key in self._streams:
            return "stream"
        if key in self._hashes:
            return "hash"
        if key in self._strings:
            return "string"
        if key in self._lists:
            return "list"
        return "none"

    def _cmd_ping(self, _args):
        return b"+PONG\r\n"

    def _cmd_select(self, args):
        # Single logical db; accept valid indices for connection-string
        # parity (AUTH stays in _serve_conn — it touches connection state).
        if len(args) == 1 and args[0].isdigit() and 0 <= int(args[0]) <= 15:
            return b"+OK\r\n"
        return b"-ERR DB index is out of range\r\n"

    def _cmd_set(self, args):
        self._strings[args[0]] = args[1]
        self._hashes.pop(args[0], None)
        self._streams.pop(args[0], None)
        return b"+OK\r\n"

    def _cmd_get(self, args):
        return self._bulk(self._strings.get(args[0]))

    def _cmd_del(self, args):
        n = 0
        for key in args:
            for table in (self._strings, self._hashes, self._streams,
                          self._lists):
                if key in table:
                    del table[key]
                    n += 1
        return b":%d\r\n" % n

    def _cmd_exists(self, args):
        return b":%d\r\n" % sum(1 for k in args if self._type_of(k) != "none")

    def _cmd_keys(self, args):
        pat = args[0].decode()
        keys = [
            k for k in (*self._strings, *self._hashes, *self._streams,
                        *self._lists)
            if fnmatchcase(k.decode(), pat)
        ]
        return self._arr(sorted(keys))

    def _cmd_scan(self, args):
        # Real cursor pagination (VERDICT r3 #8 — was one-shot). Keyset
        # cursors, not offsets: each key gets a stable id on first sight,
        # the cursor is "resume from id N", and deletions never renumber
        # the survivors — so a concurrent DEL cannot make the scan skip a
        # key that exists throughout (the guarantee real Redis's reverse-
        # binary cursor provides, and the one the unacked-recovery sweep
        # in uplink/redis_queue.py leans on). COUNT bounds the page
        # (default 10, like Redis); MATCH/TYPE filter AFTER paging, so
        # clients see possibly-empty pages with a non-zero cursor.
        if not args[0].isdigit():
            return b"-ERR invalid cursor\r\n"
        cursor = int(args[0])
        match, want_type, count = "*", None, 10
        i = 1
        while i < len(args):
            opt = args[i].upper()
            if opt == b"MATCH":
                match = args[i + 1].decode()
            elif opt == b"TYPE":
                want_type = args[i + 1].decode()
            elif opt == b"COUNT":
                count = int(args[i + 1])
                if count < 1:
                    return b"-ERR syntax error\r\n"
            else:
                return b"-ERR syntax error\r\n"
            i += 2
        live = set(
            (*self._strings, *self._hashes, *self._streams, *self._lists)
        )
        self._scan_ids = {k: v for k, v in self._scan_ids.items()
                          if k in live}
        for k in sorted(live - self._scan_ids.keys()):
            self._scan_ids[k] = self._next_scan_id
            self._next_scan_id += 1
        ordered = sorted(self._scan_ids.items(), key=lambda kv: kv[1])
        window = [(k, v) for k, v in ordered if v >= cursor]
        page, rest = window[:count], window[count:]
        next_cursor = rest[0][1] if rest else 0
        keys = [
            k for k, _ in page
            if fnmatchcase(k.decode(), match)
            and (want_type is None or self._type_of(k) == want_type)
        ]
        return self._arr([b"%d" % next_cursor, keys])

    def _cmd_type(self, args):
        return f"+{self._type_of(args[0])}\r\n".encode()

    def _cmd_hset(self, args):
        h = self._hashes.setdefault(args[0], {})
        added = 0
        for f, v in zip(args[1::2], args[2::2]):
            if f not in h:
                added += 1
            h[f] = v
        return b":%d\r\n" % added

    def _cmd_hsetnx(self, args):
        h = self._hashes.setdefault(args[0], {})
        if args[1] in h:
            return b":0\r\n"
        h[args[1]] = args[2]
        return b":1\r\n"

    def _cmd_hget(self, args):
        return self._bulk(self._hashes.get(args[0], {}).get(args[1]))

    def _cmd_hgetall(self, args):
        flat: list = []
        for f, v in self._hashes.get(args[0], {}).items():
            flat += [f, v]
        return self._arr(flat)

    def _cmd_hkeys(self, args):
        return self._arr(list(self._hashes.get(args[0], {}).keys()))

    def _cmd_xgroup(self, args):
        sub = args[0].upper()
        if sub == b"CREATE":
            key = args[1]
            if key not in self._streams:
                if b"MKSTREAM" not in (a.upper() for a in args):
                    return b"-ERR The XGROUP subcommand requires the key to exist\r\n"
                self._streams[key] = []  # MKSTREAM: empty stream, no entries
            return b"+OK\r\n"
        if sub == b"DESTROY":
            return b":1\r\n"  # groups aren't modeled beyond stream creation
        return b"-ERR unsupported XGROUP subcommand\r\n"

    def _cmd_hdel(self, args):
        h = self._hashes.get(args[0], {})
        n = 0
        for f in args[1:]:
            if f in h:
                del h[f]
                n += 1
        return b":%d\r\n" % n

    def _cmd_xadd(self, args):
        key = args[0]
        i = 1
        maxlen = None
        if args[i].upper() == b"MAXLEN":
            i += 1
            if args[i] in (b"~", b"="):
                i += 1
            maxlen = int(args[i])
            i += 1
        entry_id = args[i]
        i += 1
        fields = list(args[i:])
        now_ms = int(time.time() * 1000)
        if entry_id == b"*":
            last = self._last_stream_id.get(key, (0, -1))
            if now_ms > last[0]:
                new = (now_ms, 0)
            else:  # same ms (or clock went backwards): bump the sub-counter
                new = (last[0], last[1] + 1)
        else:
            ms, _, n = entry_id.partition(b"-")
            new = (int(ms), int(n or 0))
        self._last_stream_id[key] = new
        entries = self._streams.setdefault(key, [])
        entries.append((new, fields))
        if maxlen is not None and len(entries) > maxlen:
            del entries[: len(entries) - maxlen]
        self._data_arrived.notify_all()   # wake blocked XREADs
        return self._bulk(b"%d-%d" % new)

    def _cmd_xread(self, args):
        """XREAD [COUNT n] [BLOCK ms] STREAMS key... id...

        Blocking uses the dispatch-lock Condition: wait releases the
        lock, so other connections keep being served while this one
        blocks (real Redis semantics at this surface). "$" means
        "entries added after this call"."""
        count = block_ms = None
        i = 0
        while i < len(args):
            opt = args[i].upper()
            if opt == b"COUNT":
                count = int(args[i + 1])
                i += 2
            elif opt == b"BLOCK":
                block_ms = int(args[i + 1])
                i += 2
            elif opt == b"STREAMS":
                i += 1
                break
            else:
                return b"-ERR syntax error\r\n"
        rest = args[i:]
        nkeys = len(rest) // 2
        keys, ids = rest[:nkeys], rest[nkeys:]
        after: Dict[bytes, Tuple[int, int]] = {}
        for k, raw in zip(keys, ids):
            if raw == b"$":
                after[k] = self._last_stream_id.get(k, (0, 0))
            else:
                ms, _, n = raw.partition(b"-")
                after[k] = (int(ms), int(n or 0))

        def _collect():
            out = []
            for k in keys:
                found = [e for e in self._streams.get(k, [])
                         if e[0] > after[k]]
                if count is not None:
                    found = found[:count]
                if found:
                    out.append([k, [[b"%d-%d" % eid, fields]
                                    for eid, fields in found]])
            return out

        result = _collect()
        if result or block_ms is None:
            return self._arr(result) if result else b"*-1\r\n"
        # BLOCK 0 = "forever" in Redis; bound it to an hour so a buggy
        # client can never wedge a test process indefinitely.
        deadline = time.monotonic() + (block_ms / 1000.0 if block_ms else 3600)
        while not result:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return b"*-1\r\n"
            self._data_arrived.wait(remaining)
            result = _collect()
        return self._arr(result)

    def _cmd_xlen(self, args):
        return b":%d\r\n" % len(self._streams.get(args[0], []))

    def _cmd_xdel(self, args):
        entries = self._streams.get(args[0], [])
        want = set()
        for raw in args[1:]:
            ms, _, n = raw.partition(b"-")
            want.add((int(ms), int(n or 0)))
        before = len(entries)
        entries[:] = [e for e in entries if e[0] not in want]
        return b":%d\r\n" % (before - len(entries))

    def _cmd_xinfo(self, args):
        if args[0].upper() != b"STREAM":
            return b"-ERR syntax error\r\n"
        key = args[1]
        if key not in self._streams:
            return b"-ERR no such key\r\n"
        last = self._last_stream_id.get(key, (0, 0))
        return self._arr([
            b"length", len(self._streams[key]),
            b"last-generated-id", b"%d-%d" % last,
        ])

    @staticmethod
    def _range_bound(raw: bytes, is_start: bool):
        """One XRANGE/XREVRANGE id bound -> inclusive (ms, n) tuple.
        Supports the sentinels, explicit "ms[-n]" ids (missing seq
        defaults to 0 for a start bound, +inf for an end bound), and the
        exclusive "(id" form (Redis 6.2+) — converted to the adjacent
        inclusive id, so the comparison stays one tuple range check."""
        exclusive = raw.startswith(b"(")
        if exclusive:
            raw = raw[1:]
            if raw in (b"-", b"+"):
                # real Redis: "ERR Invalid stream ID specified"
                raise ValueError("exclusive sentinel bounds are invalid")
        if raw == b"-":
            return (0, 0)
        if raw == b"+":
            return (1 << 63, 1 << 63)
        ms, sep, n = raw.partition(b"-")
        bound = (int(ms), int(n) if sep else (0 if is_start else 1 << 63))
        if exclusive:
            if is_start:        # > bound  ==  >= next id
                bound = (bound[0], bound[1] + 1)
            elif bound[1] > 0:  # < bound  ==  <= previous id
                bound = (bound[0], bound[1] - 1)
            else:
                bound = (bound[0] - 1, 1 << 63)
        return bound

    def _xrange_entries(self, key, lo_raw, hi_raw):
        lo = self._range_bound(lo_raw, True)
        hi = self._range_bound(hi_raw, False)
        return [e for e in self._streams.get(key, []) if lo <= e[0] <= hi]

    def _cmd_xrevrange(self, args):
        # NOTE argument order: XREVRANGE key END START.
        count = None
        if len(args) >= 5 and args[3].upper() == b"COUNT":
            count = int(args[4])
        try:
            entries = list(reversed(
                self._xrange_entries(args[0], args[2], args[1])
            ))
        except ValueError as exc:
            return b"-ERR %s\r\n" % str(exc).encode()
        if count is not None:
            entries = entries[:count]
        return self._arr([
            [b"%d-%d" % eid, fields] for eid, fields in entries
        ])

    def _cmd_xrange(self, args):
        count = None
        if len(args) >= 5 and args[3].upper() == b"COUNT":
            count = int(args[4])
        try:
            entries = self._xrange_entries(args[0], args[1], args[2])
        except ValueError as exc:
            return b"-ERR %s\r\n" % str(exc).encode()
        if count is not None:
            entries = entries[:count]
        return self._arr([
            [b"%d-%d" % eid, fields] for eid, fields in entries
        ])

    # -- lists (the annotation queue's rmq-shaped plane) --

    def _cmd_lpush(self, args):
        lst = self._lists.setdefault(args[0], [])
        for v in args[1:]:
            lst.insert(0, v)
        return b":%d\r\n" % len(lst)

    def _cmd_rpush(self, args):
        lst = self._lists.setdefault(args[0], [])
        lst.extend(args[1:])
        return b":%d\r\n" % len(lst)

    def _cmd_llen(self, args):
        return b":%d\r\n" % len(self._lists.get(args[0], []))

    def _cmd_lrange(self, args):
        lst = self._lists.get(args[0], [])
        start, stop = int(args[1]), int(args[2])
        if start < 0:
            start += len(lst)
        if stop < 0:
            stop += len(lst)
        return self._arr(lst[max(start, 0): stop + 1])

    def _cmd_lpop(self, args):
        lst = self._lists.get(args[0])
        if not lst:
            return b"$-1\r\n"
        v = lst.pop(0)
        if not lst:
            del self._lists[args[0]]
        return self._bulk(v)

    def _cmd_rpop(self, args):
        lst = self._lists.get(args[0])
        if not lst:
            return b"$-1\r\n"
        v = lst.pop()
        if not lst:
            del self._lists[args[0]]
        return self._bulk(v)

    def _cmd_rpoplpush(self, args):
        src = self._lists.get(args[0])
        if not src:
            return b"$-1\r\n"
        v = src.pop()
        if not src:
            del self._lists[args[0]]
        self._lists.setdefault(args[1], []).insert(0, v)
        return self._bulk(v)

    def _cmd_lrem(self, args):
        key, count, value = args[0], int(args[1]), args[2]
        lst = self._lists.get(key, [])
        removed = 0
        if count >= 0:  # head -> tail; 0 = all
            limit = count or len(lst)
            out = []
            for v in lst:
                if v == value and removed < limit:
                    removed += 1
                else:
                    out.append(v)
        else:  # tail -> head, |count| occurrences
            limit = -count
            out = []
            for v in reversed(lst):
                if v == value and removed < limit:
                    removed += 1
                else:
                    out.append(v)
            out.reverse()
        if out:
            self._lists[key] = out
        else:
            self._lists.pop(key, None)
        return b":%d\r\n" % removed

    def _cmd_flushall(self, _args):
        self._strings.clear()
        self._hashes.clear()
        self._streams.clear()
        self._last_stream_id.clear()
        self._lists.clear()
        self._scan_ids.clear()
        return b"+OK\r\n"
